"""Synthetic topology generators as flat numpy edge arrays.

At 10k-100k nodes, building AdjacencyDatabase/LinkState Python object graphs
is pure overhead; benchmark topologies go straight to the padded directed-
edge arrays the kernels consume.  Mirrors the reference benchmark topology
classes (grid: RoutingBenchmarkUtils.h createGrid; fat-tree: createFabric
:320) plus a WAN small-world mesh for the 100k configs.

`Topology.ell` is the bucketed-ELL mirror (ops.sssp.build_ell) over padded
arrays, exactly as CsrTopology builds for production graphs."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _pad_cap(n: int, quantum: int = 512) -> int:
    return ((n + quantum) // quantum) * quantum


@dataclass
class Topology:
    name: str
    n_nodes: int
    n_edges: int  # directed
    node_capacity: int
    edge_capacity: int
    edge_src: np.ndarray  # [E_cap] int32
    edge_dst: np.ndarray  # [E_cap] int32
    edge_metric: np.ndarray  # [E_cap] int32
    edge_up: np.ndarray  # [E_cap] bool
    node_overloaded: np.ndarray  # [N_cap] bool
    ell: object = None
    banded: object = None  # ops.banded.BandedGraph | None
    _runner: object = None

    @property
    def runner(self):
        """Lazy ops.banded.SpfRunner — the production fixed-sweep
        execution path (band-aware kernel dispatch + adaptive hints)."""
        if self._runner is None:
            from openr_tpu.ops.banded import SpfRunner

            self._runner = SpfRunner(
                self.ell,
                self.banded,
                self.edge_src,
                self.edge_dst,
                self.edge_metric,
                self.edge_up,
                self.node_overloaded,
                self.n_edges,
            )
            # pin the runtime arrays device-resident: per-dispatch numpy
            # re-upload of ~11MB edge state measured ~130ms of pure wall
            # through the tunnel (round-5 tune).  Callers that mutate the
            # arrays in place AFTER this point must call runner.stage()
            # again (tests mutate before first runner access)
            self._runner.stage()
        return self._runner

    @classmethod
    def from_links(
        cls, name: str, n_nodes: int, links: np.ndarray, metrics: np.ndarray
    ) -> "Topology":
        """links [L, 2] int32 undirected, metrics [L] (or [L, 2] for
        asymmetric per-direction metrics)."""
        from openr_tpu.ops.banded import build_banded
        from openr_tpu.ops.sssp import build_ell

        if metrics.ndim == 1:
            metrics = np.stack([metrics, metrics], axis=1)
        # two directed edges per link, sorted by (dst, src) like CsrTopology
        src = np.concatenate([links[:, 0], links[:, 1]])
        dst = np.concatenate([links[:, 1], links[:, 0]])
        met = np.concatenate([metrics[:, 0], metrics[:, 1]])
        order = np.lexsort((src, dst))
        src, dst, met = src[order], dst[order], met[order]

        e = len(src)
        n_cap = _pad_cap(n_nodes)
        e_cap = _pad_cap(e)
        pad_node = n_cap - 1
        edge_src = np.full(e_cap, pad_node, dtype=np.int32)
        edge_dst = np.full(e_cap, pad_node, dtype=np.int32)
        edge_metric = np.ones(e_cap, dtype=np.int32)
        edge_up = np.zeros(e_cap, dtype=bool)
        edge_src[:e] = src
        edge_dst[:e] = dst
        edge_metric[:e] = met
        edge_up[:e] = True
        node_overloaded = np.zeros(n_cap, dtype=bool)
        ell = build_ell(
            edge_src, edge_dst, edge_metric, edge_up, node_overloaded, e
        )
        banded = build_banded(edge_src, edge_dst, e, n_nodes)
        return cls(
            name=name,
            n_nodes=n_nodes,
            n_edges=e,
            node_capacity=n_cap,
            edge_capacity=e_cap,
            edge_src=edge_src,
            edge_dst=edge_dst,
            edge_metric=edge_metric,
            edge_up=edge_up,
            node_overloaded=node_overloaded,
            ell=ell,
            banded=banded,
        )


def grid(n_side: int) -> Topology:
    """n_side x n_side unit-metric grid (reference createGrid)."""
    ids = np.arange(n_side * n_side, dtype=np.int32).reshape(n_side, n_side)
    horiz = np.stack([ids[:, :-1].ravel(), ids[:, 1:].ravel()], axis=1)
    vert = np.stack([ids[:-1, :].ravel(), ids[1:, :].ravel()], axis=1)
    links = np.concatenate([horiz, vert]).astype(np.int32)
    return Topology.from_links(
        f"grid{n_side * n_side}",
        n_side * n_side,
        links,
        np.ones(len(links), dtype=np.int32),
    )


def fat_tree(
    pods: int = 96,
    planes: int = 4,
    ssw_per_plane: int = 24,
    rsw_per_pod: int = 100,
) -> Topology:
    """Three-tier fabric (reference createFabric, RoutingBenchmarkUtils.h:320):
    each pod has `planes` fabric switches; fsw f of a pod uplinks to every
    spine in plane f and downlinks to every rack switch in its pod.
    Defaults give ~10k nodes with 4-way ECMP between pods."""
    n_ssw = planes * ssw_per_plane
    n_fsw = pods * planes
    n_rsw = pods * rsw_per_pod
    n = n_ssw + n_fsw + n_rsw

    def ssw_id(plane, s):
        return plane * ssw_per_plane + s

    def fsw_id(pod, f):
        return n_ssw + pod * planes + f

    def rsw_id(pod, r):
        return n_ssw + n_fsw + pod * rsw_per_pod + r

    links = []
    for pod in range(pods):
        for f in range(planes):
            fsw = fsw_id(pod, f)
            for s in range(ssw_per_plane):
                links.append((fsw, ssw_id(f, s)))
            for r in range(rsw_per_pod):
                links.append((fsw, rsw_id(pod, r)))
    links = np.asarray(links, dtype=np.int32)
    return Topology.from_links(
        f"fattree{n}", n, links, np.ones(len(links), dtype=np.int32)
    )


def wan(n_nodes: int = 100_000, chords: int = 2, seed: int = 0) -> Topology:
    """Small-world WAN mesh: ring of n nodes (adjacent + skip-2 links) plus
    `chords` random long-haul links per node, metrics 1..10 asymmetric —
    the 100k-node dual-metric WAN config (BASELINE config #3 shape)."""
    rng = np.random.RandomState(seed)
    ids = np.arange(n_nodes, dtype=np.int32)
    ring1 = np.stack([ids, (ids + 1) % n_nodes], axis=1)
    ring2 = np.stack([ids, (ids + 2) % n_nodes], axis=1)
    chord_list = []
    for _ in range(chords):
        perm = rng.permutation(n_nodes).astype(np.int32)
        chord_list.append(np.stack([ids, perm], axis=1))
    links = np.concatenate([ring1, ring2] + chord_list)
    # drop self-links from chord permutation collisions
    links = links[links[:, 0] != links[:, 1]]
    # dedupe (a, b) vs (b, a)
    key = np.sort(links, axis=1)
    _, keep = np.unique(key[:, 0].astype(np.int64) * n_nodes + key[:, 1], return_index=True)
    links = links[keep]
    metrics = rng.randint(1, 11, size=(len(links), 2)).astype(np.int32)
    return Topology.from_links(f"wan{n_nodes}", n_nodes, links, metrics)


def reversed_topology(topo: Topology) -> Topology:
    """Same nodes, every directed edge reversed (per-direction metrics
    travel with their edge) — the graph on which P-source SSSP computes
    all-sources-to-P-destinations distances (ops.allsources)."""
    from openr_tpu.ops.banded import build_banded
    from openr_tpu.ops.sssp import build_ell

    e = topo.n_edges
    src = topo.edge_dst[:e].copy()
    dst = topo.edge_src[:e].copy()
    met = topo.edge_metric[:e].copy()
    order = np.lexsort((src, dst))
    pad_node = topo.node_capacity - 1
    edge_src = np.full(topo.edge_capacity, pad_node, dtype=np.int32)
    edge_dst = np.full(topo.edge_capacity, pad_node, dtype=np.int32)
    edge_metric = np.ones(topo.edge_capacity, dtype=np.int32)
    edge_up = np.zeros(topo.edge_capacity, dtype=bool)
    edge_src[:e] = src[order]
    edge_dst[:e] = dst[order]
    edge_metric[:e] = met[order]
    edge_up[:e] = topo.edge_up[:e][order]
    node_overloaded = topo.node_overloaded.copy()
    ell = build_ell(
        edge_src, edge_dst, edge_metric, edge_up, node_overloaded, e
    )
    banded = build_banded(edge_src, edge_dst, e, topo.n_nodes)
    return Topology(
        name=topo.name + "-rev",
        n_nodes=topo.n_nodes,
        n_edges=e,
        node_capacity=topo.node_capacity,
        edge_capacity=topo.edge_capacity,
        edge_src=edge_src,
        edge_dst=edge_dst,
        edge_metric=edge_metric,
        edge_up=edge_up,
        node_overloaded=node_overloaded,
        ell=ell,
        banded=banded,
    )


def neighbors_of(topo: Topology, node: int) -> np.ndarray:
    """Unique out-neighbors of `node` among up edges."""
    mask = (topo.edge_src[: topo.n_edges] == node) & topo.edge_up[: topo.n_edges]
    return np.unique(topo.edge_dst[: topo.n_edges][mask])
