"""Host-subsystem benchmarks mirroring the reference folly-Benchmark
harnesses that do NOT involve the compute kernel:

- KvStore CRDT merge throughput   (BM_KvStoreMergeKeyValues,
  openr/kvstore/tests/KvStoreBenchmark.cpp:190)
- KvStore full dump               (BM_KvStoreDumpAll, :231)
- KvStore flooding update         (BM_KvStoreFloodingUpdate, :269 —
  end-to-end through a live 2-store mesh here)
- Fib route-programming pipeline  (BM_Fib, openr/fib/tests/
  FibBenchmark.cpp:214 — DecisionRouteUpdate -> agent programming)
- PersistentStore write throughput (PersistentStoreBenchmark)

All rows are host-side (no TPU); callable standalone or from bench.py.
"""

from __future__ import annotations

import os
import random
import tempfile
import time
from typing import Callable

from openr_tpu.kvstore.kvstore import generate_hash, merge_key_values
from openr_tpu.types import NextHop, Value

KEY_LEN = 32
VALUE_LEN = 1024  # kSizeOfValue in the reference harness


def _rand_str(rng: random.Random, n: int) -> str:
    # getrandbits+hex: random.choices dominated the harness SETUP time
    # (~10s at the 10k x 10k point) without affecting the measurement
    return rng.getrandbits(n * 4).to_bytes((n + 1) // 2, "big").hex()[:n]


def _time_ms(fn: Callable[[], None], reps: int) -> list[float]:
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1e3)
    return out


def _spin_until(cond: Callable[[], bool], what: str, timeout_s: float = 30.0) -> None:
    """Bounded wait: a subsystem regression must fail the bench row with a
    diagnostic, not hang the benchmark of record forever."""
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError(f"bench wait timed out: {what}")
        time.sleep(0.001)


def bench_merge_key_values(
    store_keys: int,
    update_keys: int,
    reps: int = 5,
    with_hashes: bool = False,
) -> dict:
    """CRDT merge: `update_keys` newer-version values against a store of
    `store_keys` (reference: updateKvStore + mergeKeyValues).

    `with_hashes` pre-sets Value.hash on the updates — the steady-state
    flooding scenario (peers forward values whose hash was computed at
    first merge); without it the row measures the first-advertisement
    worst case where merge must hash every value."""
    rng = random.Random(7)
    keys = [_rand_str(rng, KEY_LEN) for _ in range(store_keys)]
    base = {
        k: Value(
            version=1,
            originator_id="kvStore",
            value=_rand_str(rng, VALUE_LEN).encode(),
            ttl_ms=3_600_000,
        )
        for k in keys
    }
    # updates pre-generated OUTSIDE the timed region — the row measures the
    # CRDT merge, not random-string generation
    updates = []
    for version in range(2, 2 + reps):
        batch = {}
        for k in keys[:update_keys]:
            v = Value(
                version=version,
                originator_id="kvStore",
                value=_rand_str(rng, VALUE_LEN).encode(),
                ttl_ms=3_600_000,
            )
            if with_hashes:
                v.hash = generate_hash(v.version, v.originator_id, v.value)
            batch[k] = v
        updates.append(batch)
    times = []
    for update in updates:
        t0 = time.perf_counter()
        merged = merge_key_values(base, update, None)
        times.append((time.perf_counter() - t0) * 1e3)
        assert len(merged) == update_keys
    return {
        "store_keys": store_keys,
        "update_keys": update_keys,
        "with_hashes": with_hashes,
        "ms_min": round(min(times), 3),
        "keys_per_sec": round(update_keys / (min(times) / 1e3)),
    }


def bench_dump_all(n_keys: int, reps: int = 5) -> dict:
    """Full dump of a live store (reference: BM_KvStoreDumpAll)."""
    from openr_tpu.runtime.queue import ReplicateQueue
    from openr_tpu.kvstore.kvstore import KvStore

    rng = random.Random(11)
    updates: ReplicateQueue = ReplicateQueue()
    syncs: ReplicateQueue = ReplicateQueue()
    store = KvStore("bench", updates, syncs, None)
    store.run()
    try:
        key_vals = {}
        for _ in range(n_keys):
            val = Value(
                version=1,
                originator_id="bench",
                value=_rand_str(rng, VALUE_LEN).encode(),
                ttl_ms=-1,
            )
            val.hash = generate_hash(val.version, val.originator_id, val.value)
            key_vals[_rand_str(rng, KEY_LEN)] = val
        store.set_key_vals("0", key_vals)

        def run():
            pub = store.dump_all("0")
            assert len(pub.key_vals) == n_keys

        times = _time_ms(run, reps)
    finally:
        updates.close()
        syncs.close()
        store.stop()
        store.wait_until_stopped(5)
    return {"n_keys": n_keys, "ms_min": round(min(times), 3)}


def bench_flooding_update(n_keys: int, reps: int = 3) -> dict:
    """End-to-end flooding: set keys on store A, measure until they are
    merged at peer B over the in-process transport (reference:
    BM_KvStoreFloodingUpdate, but through a REAL 2-store mesh)."""
    from openr_tpu.runtime.queue import ReplicateQueue
    from openr_tpu.kvstore.kvstore import InProcessTransport, KvStore
    from openr_tpu.types import PeerSpec

    rng = random.Random(13)
    fab = InProcessTransport()
    stores = []

    def make(name):
        updates: ReplicateQueue = ReplicateQueue()
        syncs: ReplicateQueue = ReplicateQueue()
        st = KvStore(name, updates, syncs, None, transport=fab.bind(name))
        fab.register(name, st)
        st.run()
        stores.append((st, updates, syncs))
        return st

    a, b = make("a"), make("b")
    try:
        a.add_peers("0", {"b": PeerSpec(peer_addr="b")})
        b.add_peers("0", {"a": PeerSpec(peer_addr="a")})
        _spin_until(
            lambda: all(
                s is not None and s.name == "INITIALIZED"
                for s in (
                    a.get_peer_state("0", "b"),
                    b.get_peer_state("0", "a"),
                )
            ),
            "kvstore peering",
        )

        version = 1
        times = []
        for _ in range(reps):
            keys = [_rand_str(rng, KEY_LEN) for _ in range(n_keys)]
            key_vals = {
                k: Value(
                    version=version,
                    originator_id="a",
                    value=_rand_str(rng, VALUE_LEN).encode(),
                    ttl_ms=-1,
                )
                for k in keys
            }
            t0 = time.perf_counter()
            a.set_key_vals("0", key_vals)
            last = keys[-1]
            _spin_until(
                lambda: b.get_key_vals("0", [last]).key_vals.get(last)
                is not None,
                f"flooding of {n_keys} keys",
            )
            times.append((time.perf_counter() - t0) * 1e3)
            version += 1
    finally:
        for st, updates, syncs in stores:
            updates.close()
            syncs.close()
            st.stop()
        for st, *_ in stores:
            st.wait_until_stopped(5)
    return {
        "n_keys": n_keys,
        "ms_min": round(min(times), 3),
        "keys_per_sec": round(n_keys / (min(times) / 1e3)),
    }


def bench_fib_pipeline(n_prefixes: int, reps: int = 3) -> dict:
    """Route-programming pipeline: DecisionRouteUpdate pushed to a live
    Fib module until the agent has every route (reference: BM_Fib,
    FibBenchmark.cpp:214 'wait for the completion of routes update')."""
    from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
    from openr_tpu.fib.fib import FIB_CLIENT_OPENR, Fib, MockFibAgent
    from openr_tpu.runtime.queue import ReplicateQueue

    agent = MockFibAgent()
    route_updates: ReplicateQueue = ReplicateQueue()
    fib = Fib("bench", route_updates.get_reader(), agent)
    fib.run()
    try:
        times = []
        base = 0
        for _ in range(reps):
            update = DecisionRouteUpdate()
            for i in range(n_prefixes):
                prefix = f"fc00:{base + i:x}::/64"
                update.unicast_routes_to_update[prefix] = RibUnicastEntry(
                    prefix=prefix,
                    nexthops=frozenset(
                        {
                            NextHop(
                                address="fe80::1",
                                if_name="if0",
                                neighbor_node_name="peer",
                            )
                        }
                    ),
                )
            base += n_prefixes
            last = f"fc00:{base - 1:x}::/64"
            t0 = time.perf_counter()
            route_updates.push(update)
            _spin_until(
                lambda: last in agent.unicast.get(FIB_CLIENT_OPENR, {}),
                f"programming of {n_prefixes} routes",
            )
            times.append((time.perf_counter() - t0) * 1e3)
    finally:
        route_updates.close()
        fib.stop()
        fib.wait_until_stopped(5)
    return {
        "n_prefixes": n_prefixes,
        "ms_min": round(min(times), 3),
        "routes_per_sec": round(n_prefixes / (min(times) / 1e3)),
    }


def bench_persistent_store(n_writes: int = 1000, reps: int = 3) -> dict:
    """Durable KV write throughput (reference: PersistentStoreBenchmark)."""
    from openr_tpu.config_store.persistent_store import PersistentStore

    times = []
    for _ in range(reps):
        with tempfile.TemporaryDirectory() as tmp:
            store = PersistentStore(os.path.join(tmp, "store.bin"))
            payload = b"x" * 256

            t0 = time.perf_counter()
            for i in range(n_writes):
                store.store(f"key-{i % 64}", payload)
            times.append((time.perf_counter() - t0) * 1e3)
            store.close()
    return {
        "n_writes": n_writes,
        "ms_min": round(min(times), 3),
        "writes_per_sec": round(n_writes / (min(times) / 1e3)),
    }


def run_all() -> dict:
    """Per-row error containment: one failing subsystem records an error
    row instead of aborting the rest of the benchmark of record."""

    def guarded(fn, *args):
        try:
            return fn(*args)
        except Exception as exc:
            return {"error": f"{type(exc).__name__}: {exc}"}

    rows: dict = {}
    rows["kvstore_merge"] = [
        guarded(bench_merge_key_values, s, u)
        for s, u in ((10, 10), (1000, 10), (10_000, 100), (10_000, 10_000))
    ]
    # steady-state flooding: values arrive with hashes already set
    rows["kvstore_merge"].append(
        guarded(bench_merge_key_values, 10_000, 10_000, 5, True)
    )
    rows["kvstore_dump_all"] = [
        guarded(bench_dump_all, n) for n in (10, 1000, 10_000)
    ]
    rows["kvstore_flooding"] = [
        guarded(bench_flooding_update, n) for n in (10, 1000)
    ]
    rows["fib_pipeline"] = [
        guarded(bench_fib_pipeline, n) for n in (10, 1000, 9000)
    ]
    rows["persistent_store"] = guarded(bench_persistent_store)
    return rows


if __name__ == "__main__":
    import json

    print(json.dumps(run_all(), indent=1))
