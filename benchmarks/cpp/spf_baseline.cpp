// CPU Dijkstra baseline for the SPF benchmarks.
//
// Original implementation of the reference's per-source Dijkstra semantics
// (openr/decision/LinkState.cpp:809-878 runSpf): binary-heap Dijkstra over a
// CSR graph, positive integer metrics, down links never relax, overloaded
// (drained) nodes are reachable but give no transit unless they are the
// source.  One sequential run per source — exactly the work the reference
// does when all sources are queried (getSpfResult per node) — giving the
// honest CPU baseline the batched TPU kernel is compared against.
//
// Built as a shared library, driven via ctypes (benchmarks/cpp_baseline.py).

#include <chrono>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

constexpr int32_t kInf = 1 << 30;

struct Csr {
  std::vector<int32_t> offsets;  // [n_nodes + 1]
  std::vector<int32_t> dst;      // [n_edges]
  std::vector<int32_t> metric;   // [n_edges]
};

// Build an out-edge CSR from directed edge lists, dropping down edges.
Csr build_csr(int n_nodes, int n_edges, const int32_t* edge_src,
              const int32_t* edge_dst, const int32_t* edge_metric,
              const uint8_t* edge_up) {
  Csr csr;
  csr.offsets.assign(n_nodes + 1, 0);
  int kept = 0;
  for (int e = 0; e < n_edges; ++e) {
    if (edge_up && !edge_up[e]) continue;
    ++csr.offsets[edge_src[e] + 1];
    ++kept;
  }
  for (int v = 0; v < n_nodes; ++v) csr.offsets[v + 1] += csr.offsets[v];
  csr.dst.resize(kept);
  csr.metric.resize(kept);
  std::vector<int32_t> cursor(csr.offsets.begin(), csr.offsets.end() - 1);
  for (int e = 0; e < n_edges; ++e) {
    if (edge_up && !edge_up[e]) continue;
    int pos = cursor[edge_src[e]]++;
    csr.dst[pos] = edge_dst[e];
    csr.metric[pos] = edge_metric[e];
  }
  return csr;
}

void dijkstra(const Csr& csr, int n_nodes, const uint8_t* node_overloaded,
              int32_t source, int32_t* dist) {
  std::fill(dist, dist + n_nodes, kInf);
  dist[source] = 0;
  using Item = std::pair<int32_t, int32_t>;  // (dist, node)
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> heap;
  heap.emplace(0, source);
  while (!heap.empty()) {
    auto [d, u] = heap.top();
    heap.pop();
    if (d > dist[u]) continue;  // stale entry
    // drained nodes offer no transit unless they are the source
    // (LinkState.cpp:829-836)
    if (u != source && node_overloaded && node_overloaded[u]) continue;
    for (int i = csr.offsets[u]; i < csr.offsets[u + 1]; ++i) {
      int v = csr.dst[i];
      int32_t nd = d + csr.metric[i];
      if (nd < dist[v]) {
        dist[v] = nd;
        heap.emplace(nd, v);
      }
    }
  }
}

}  // namespace

extern "C" {

// Runs Dijkstra from each source sequentially.  Returns seconds spent in
// the SPF loop (graph build excluded).  If out_dist is non-null it receives
// n_sources * n_nodes int32 distances (kInf = unreachable).
double spf_all_sources(int n_nodes, int n_edges, const int32_t* edge_src,
                       const int32_t* edge_dst, const int32_t* edge_metric,
                       const uint8_t* edge_up, const uint8_t* node_overloaded,
                       const int32_t* sources, int n_sources,
                       int32_t* out_dist) {
  Csr csr = build_csr(n_nodes, n_edges, edge_src, edge_dst, edge_metric,
                      edge_up);
  std::vector<int32_t> scratch;
  if (!out_dist) scratch.resize(n_nodes);
  auto t0 = std::chrono::steady_clock::now();
  for (int s = 0; s < n_sources; ++s) {
    int32_t* row = out_dist ? out_dist + static_cast<int64_t>(s) * n_nodes
                            : scratch.data();
    dijkstra(csr, n_nodes, node_overloaded, sources[s], row);
  }
  auto t1 = std::chrono::steady_clock::now();
  return std::chrono::duration<double>(t1 - t0).count();
}
}
