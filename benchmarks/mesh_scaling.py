"""Virtual-mesh scaling evidence for the sharded SPF steps.

The multi-chip projections (source-axis sharding over a
``("batch", "node")`` mesh) rest on a linearity assumption: the
per-device executable does 1/B of the batch work with no hidden
replication, and collectives appear only when the node axis is split.
This harness VALIDATES that assumption with the strongest evidence a
single-core host can produce:

- **per-device compiled cost** (XLA ``compiled.cost_analysis()``): FLOPs
  and bytes accessed of the per-device program at batch-axis sizes 1/2/
  4/8 over the virtual CPU mesh.  Linear sharding means flops(B) ~
  flops(1)/B; a replicated or resharded intermediate would show up
  immediately as a flat term.
- **single-core wall ratio**: on one physical core the B virtual devices
  serialize, so wall(B-dev sharded, total S) / wall(1-dev, total S)
  measures the sharding OVERHEAD factor (partition + runtime), which
  multiplies any real-hardware projection.
- **collective check**: the batch-only layout's only collectives are
  the O(1)-byte scalar reductions of the convergence verdict
  (jnp.any/jnp.all across the sharded batch); splitting the node axis
  must introduce the real data collectives (all-gathers of the [N, S]
  row-gather operands — the documented ICI cost).

What this deliberately does NOT claim: real multi-chip wall-clock.  One
core cannot time 8 devices; the artifact records the measured per-device
cost division + overhead factor instead of asserting wall-time speedup
(bench_details carries both numbers and this note).
"""

from __future__ import annotations

import json
import os
import time

# wall budget shared with bench.py's rows (0 = uncapped); the blocked
# 1M-node section is the sacrificial row when the budget runs short
_BUDGET_S = float(os.environ.get("OPENR_BENCH_BUDGET_S", "0"))
_START = time.monotonic()


def _budget_left() -> float:
    if _BUDGET_S <= 0:
        return float("inf")
    return _BUDGET_S - (time.monotonic() - _START)


def _shed_marker(section: str) -> dict:
    """Pre-check shed row: emitted INSTEAD OF starting a compile-heavy
    section when the remaining wall budget cannot cover it — the row
    dies cleanly in the artifact rather than the harness dying at
    rc=124 mid-compile (BENCH_r05)."""
    return {
        "error": (
            f"skipped: wall budget exhausted before {section} "
            f"(shed marker, OPENR_BENCH_BUDGET_S)"
        )
    }


def _collect(step, args, mesh_desc: str, execute: bool = True):
    import jax

    lowered = step.lower(*args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # per-device list on some backends
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    # collective detection from the optimized HLO text
    hlo = compiled.as_text()
    collectives = sum(
        hlo.count(op)
        for op in ("all-gather", "all-reduce", "collective-permute")
    )
    row = {
        "mesh": mesh_desc,
        "flops_per_device": flops,
        "bytes_per_device": bytes_accessed,
        "collective_ops": collectives,
        "wall_ms_min": None,
    }
    if not execute:
        # structural row: per-device compiled cost and collective count
        # come straight from the AOT compile; skipping execution keeps
        # large-topology rows inside the harness wall budget
        return row
    out = compiled(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    row["wall_ms_min"] = round(min(times), 2)
    return row


def _collect_phase(lowered) -> dict:
    """Per-device compiled cost of one blocked phase kernel, with the
    collective mix enumerated by op (the per-phase attribution the
    node-sharding claim rests on).  NOTE on while-loop accounting: XLA's
    cost analysis charges a loop BODY once, so for the fori_loop phase
    kernels the numbers are per rank-1 min-plus step — the natural unit
    to compare against the ideal N^2/devices split (a full round is B
    such steps)."""
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    hlo = compiled.as_text()
    from openr_tpu.parallel import hlo_async

    gather_bytes = sum(
        hlo_async.shape_bytes(line.split("all-gather(")[0])
        for line in hlo.splitlines()
        if " all-gather(" in line
    )
    return {
        "flops_per_device": float(cost.get("flops", 0.0)),
        "bytes_per_device": float(cost.get("bytes accessed", 0.0)),
        "gather_bytes": gather_bytes,
        "collectives": {
            op: hlo.count(op)
            for op in (
                "all-gather",
                "all-reduce",
                "collective-permute",
                "all-to-all",
            )
        },
    }


def _blocked_rows(n_nodes: int, tile: int) -> dict:
    """Compile-only scaling evidence for the blocked-APSP phase kernels
    at planet scale (N >= 1M): per-device HBM bytes and FLOPs vs the
    ideal N^2/devices split, collectives per phase."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from openr_tpu.parallel import blocked as blk

    mesh = blk.make_blocked_mesh(jax.devices("cpu")[:8])  # 1 x 2 x 4
    n_dev = 8
    b = tile
    t = -(-n_nodes // b)
    n_pad = t * b
    s_dist = NamedSharding(mesh, P("batch", None, "row", None, "col"))
    s_repl = NamedSharding(mesh, P())
    s_diag = NamedSharding(mesh, P("batch"))
    s_row = NamedSharding(mesh, P("batch", None, None, "col"))
    s_col = NamedSharding(mesh, P("batch", None, "row", None))
    aval = jax.ShapeDtypeStruct
    dist = aval((1, t, b, t, b), jnp.uint32, sharding=s_dist)
    ov = aval((n_pad,), jnp.bool_, sharding=s_repl)
    k = aval((), jnp.int32)
    closed = aval((1, b, b), jnp.uint32, sharding=s_diag)
    row_p = aval((1, b, t, b), jnp.uint32, sharding=s_row)
    col_p = aval((1, t, b, b), jnp.uint32, sharding=s_col)

    phases = {
        "diag": _collect_phase(
            blk.blocked_diag.lower(dist, ov, k, mesh=mesh)
        ),
        "panels": _collect_phase(
            blk.blocked_panels.lower(dist, closed, ov, k, mesh=mesh)
        ),
        "outer": _collect_phase(
            blk.blocked_outer.lower(dist, row_p, col_p, ov, k, mesh=mesh)
        ),
    }
    # per-round collective bytes of the bulk-synchronous loop: the
    # gathers live in the diag + panels modules (outer is
    # collective-free) — summed from the compiled output shapes
    gather_bytes = 0
    for ph in ("diag", "panels"):
        gather_bytes += phases[ph].get("gather_bytes", 0)
    # ideal per-device cost of one rank-1 min-plus step of the dominant
    # outer phase (the unit the while-body accounting reports, see
    # _collect_phase): every device touches its Np^2/D state slab twice
    # (read + min-write) and runs the four elementwise ops of one masked
    # min-plus step per element (add, saturating min, drain select,
    # min-accumulate) — "ideal" asserts the 1/D division of the work,
    # i.e. zero replicated or resharded state
    ideal_bytes = 2.0 * n_pad * n_pad * 4 / n_dev
    ideal_flops = 4.0 * n_pad * n_pad / n_dev
    outer = phases["outer"]
    return {
        "n_nodes": n_nodes,
        "n_pad": n_pad,
        "tile": b,
        "rounds": t,
        "mesh": "batch=1,row=2,col=4",
        "phases": phases,
        "round_gather_bytes": gather_bytes,
        "outer_ideal_bytes_per_device": ideal_bytes,
        "outer_ideal_flops_per_device": ideal_flops,
        "outer_bytes_ratio": (
            round(outer["bytes_per_device"] / ideal_bytes, 4)
            if ideal_bytes
            else None
        ),
        "outer_flops_ratio": (
            round(outer["flops_per_device"] / ideal_flops, 4)
            if ideal_flops
            else None
        ),
        "note": (
            "structural rows: AOT-compiled phase kernels from sharded "
            "ShapeDtypeStructs — the [1M, 1M] uint32 state only exists "
            "sharded.  Per-device numbers are per rank-1 min-plus step "
            "(XLA charges a fori_loop body once); a round is B steps, "
            "the product T rounds.  Collectives per phase: the diag "
            "tile replicates, the panels all-gather over row/col, the "
            "outer update is collective-free."
        ),
    }


def _pipelined_row(n_nodes: int, tile: int, bulk_row: dict) -> dict:
    """Compile-only evidence for the software-pipelined blocked round
    at planet scale: AOT-lower `blocked_round_pipelined` on the 1x2x4
    virtual mesh, then let `parallel.hlo_async` materialize the async
    all-gather-start/done spans from the scheduled module and verify —
    from real def-use chains — that the panel gathers bracket the
    rank-5 outer-update while.  The headline asserts are hard: a
    regression that re-serializes the collectives fails the row."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from openr_tpu.parallel import blocked as blk
    from openr_tpu.parallel import hlo_async

    mesh = blk.make_blocked_mesh(jax.devices("cpu")[:8])  # 1 x 2 x 4
    b = tile
    t = -(-n_nodes // b)
    n_pad = t * b
    aval = jax.ShapeDtypeStruct
    args = (
        aval(
            (1, t, b, t, b),
            jnp.uint32,
            sharding=NamedSharding(mesh, P("batch", None, "row", None, "col")),
        ),
        aval(
            (1, b, t, b),
            jnp.uint32,
            sharding=NamedSharding(mesh, P("batch", None, None, "col")),
        ),
        aval(
            (1, t, b, b),
            jnp.uint32,
            sharding=NamedSharding(mesh, P("batch", None, "row", None)),
        ),
        aval((n_pad,), jnp.bool_, sharding=NamedSharding(mesh, P())),
        aval((), jnp.int32, sharding=NamedSharding(mesh, P())),
    )
    txt = (
        blk.blocked_round_pipelined.lower(*args, mesh=mesh)
        .compile()
        .as_text()
    )
    rep = hlo_async.async_report(txt)
    # headline: the start/done pairs BRACKET compute, per the def-use
    # graph of the compiled module — not an empty or illegal window
    assert rep["outer_update"] is not None, "no rank-5 outer-update while"
    assert rep["panel_overlap_ok"], rep["spans"]
    assert all(s["legal"] for s in rep["spans"]), rep["spans"]
    assert all(
        s["compute_in_span"]
        for s in rep["spans"]
        if s["spans_outer_update"]
    ), rep["spans"]
    bulk_bytes = (
        bulk_row.get("round_gather_bytes") if isinstance(bulk_row, dict)
        else None
    )
    return {
        "n_nodes": n_nodes,
        "n_pad": n_pad,
        "tile": b,
        "rounds": t,
        "mesh": "batch=1,row=2,col=4",
        "collectives": rep["n_collectives"],
        "outer_update_while": rep["outer_update"],
        "spans_bracketing_outer": len(
            [s for s in rep["spans"] if s["spans_outer_update"]]
        ),
        "overlap_frac_est": rep["overlap_frac_est"],
        "round_gather_bytes": rep["collective_bytes"],
        "bulk_round_gather_bytes": bulk_bytes,
        "gather_bytes_vs_bulk": (
            round(rep["collective_bytes"] / bulk_bytes, 4)
            if bulk_bytes
            else None
        ),
        "spans": [
            {
                "name": s["name"],
                "bytes_out": s["bytes_out"],
                "compute_ops_in_span": len(s["compute_in_span"]),
                "spans_outer_update": s["spans_outer_update"],
                "legal": s["legal"],
            }
            for s in rep["spans"]
        ],
        "note": (
            "compile-only: the fused pipelined round is AOT-lowered at "
            "N=1M and the async all-gather-start/done spans are "
            "materialized by parallel.hlo_async from the scheduled "
            "module's def-use chains (the CPU backend overlaps "
            "independent thunks as a dataflow DAG instead of emitting "
            "the start/done pair; legality is the same rule XLA's "
            "async scheduler applies on TPU).  The two panel gathers' "
            "spans bracket the rank-5 outer-update while; the diagonal "
            "replication is dep-chained through the row-panel gather, "
            "so a linear schedule provably cannot also nest it."
        ),
    }


def run(n_side: int = 32, n_sources: int = 1024, n_variants: int = 256) -> dict:
    import jax

    # the axon plugin pre-imports jax at interpreter startup, so env-var
    # platform selection may be ignored; pin CPU explicitly (the virtual
    # 8-device mesh only exists there)
    if jax.default_backend() != "cpu":
        jax.config.update("jax_default_device", jax.devices("cpu")[0])
    import jax.numpy as jnp
    import numpy as np

    from benchmarks import synthetic
    from openr_tpu.parallel import mesh as pmesh

    assert len(jax.devices("cpu")) >= 8, "needs the 8-device virtual mesh"
    topo = synthetic.grid(n_side)
    sources = jnp.arange(n_sources, dtype=jnp.int32) % topo.n_nodes
    base_args = (
        sources,
        topo.ell,
        jnp.asarray(topo.edge_src),
        jnp.asarray(topo.edge_dst),
        jnp.asarray(topo.edge_metric),
        jnp.asarray(topo.edge_up),
        jnp.asarray(topo.node_overloaded),
    )

    rows: dict = {"allsrc": [], "whatif": []}
    for b in (1, 2, 4, 8):
        mesh = pmesh.make_mesh(jax.devices("cpu")[:b], batch_axis=b)
        step = pmesh.spf_step_sharded(mesh)
        rows["allsrc"].append(_collect(step, base_args, f"batch={b}"))

    # masked what-if fleet over the variant axis
    if _budget_left() < 60:
        rows["whatif"] = _shed_marker("whatif")
    else:
        rng = np.random.default_rng(3)
        mask_t = np.ones((topo.edge_capacity, n_variants), dtype=bool)
        fail = rng.integers(0, topo.n_edges, size=n_variants)
        mask_t[fail, np.arange(n_variants)] = False
        wa_args = (
            jnp.zeros(n_variants, dtype=jnp.int32),
            topo.ell,
            jnp.asarray(topo.edge_src),
            jnp.asarray(topo.edge_dst),
            jnp.asarray(topo.edge_metric),
            jnp.asarray(topo.edge_up),
            jnp.asarray(topo.node_overloaded),
            jnp.asarray(mask_t),
        )
        for b in (1, 8):
            mesh = pmesh.make_mesh(jax.devices("cpu")[:b], batch_axis=b)
            step = pmesh.whatif_step_sharded(mesh)
            rows["whatif"].append(_collect(step, wa_args, f"batch={b}"))

    # node-axis split: collectives must appear
    if _budget_left() < 60:
        rows["node_axis"] = _shed_marker("node_axis")
    else:
        mesh_node = pmesh.make_mesh(jax.devices("cpu")[:8], batch_axis=1)
        step = pmesh.spf_step_sharded(mesh_node)
        rows["node_axis"] = _collect(step, base_args, "batch=1,node=8")

    # round-5: the reduced all-sources FLEET product with the dest axis
    # sharded over the batch mesh (parallel/mesh.fleet_product_sharded);
    # relax + bitmap must stay collective-free per shard, verdict only
    from openr_tpu.ops import allsources as asrc

    if _budget_left() < 90:
        # the fleet-product rows compile the full product program twice
        # (b=1 and b=8) — pre-check instead of dying mid-compile
        rows["fleet_product"] = _shed_marker("fleet_product")
        rows["fleet_product_wan100k"] = _shed_marker("fleet_product_wan100k")
        rows["blocked_1m"] = _shed_marker("blocked_1m")
        rows["blocked_pipelined_1m"] = _shed_marker("blocked_pipelined_1m")
        return _summary(topo, n_sources, n_variants, rows)

    wtopo = synthetic.wan(4096, chords=2, seed=1)
    wrev = synthetic.reversed_topology(wtopo)
    wrunner = wrev.runner
    rng = np.random.default_rng(2)
    dests = np.sort(
        rng.choice(wtopo.n_nodes, size=256, replace=False).astype(np.int32)
    )
    out = asrc.build_out_ell(
        wtopo.edge_src, wtopo.edge_dst, wtopo.n_edges, wtopo.n_nodes
    )
    # learn the sweep count once (single-device adaptive)
    _, _, ok = asrc.reduced_all_sources(
        dests, wrunner, out, wtopo.edge_metric, wtopo.edge_up,
        wtopo.node_overloaded,
    )
    assert bool(ok)
    es_w, ed_w, em_w, eu_w, ov_w = wrunner.arrays
    fleet_args = (
        jnp.asarray(dests),
        wrunner.bg,
        jnp.asarray(es_w),
        jnp.asarray(ed_w),
        jnp.asarray(em_w),
        jnp.asarray(eu_w),
        jnp.asarray(ov_w),
        out,
        jnp.asarray(wtopo.edge_metric),
        jnp.asarray(wtopo.edge_up),
    )
    rows["fleet_product"] = []
    for b in (1, 8):
        mesh = pmesh.make_mesh(jax.devices("cpu")[:b], batch_axis=b)
        step = pmesh.fleet_product_sharded(
            mesh,
            n_sweeps=wrunner.hint,
            n_words=out.n_words,
            depth=wrunner.depth,
            resid_rounds=wrunner.resid_rounds,
            small_dist=wrunner.small_dist,
            chord_mode=wrunner.chord_mode,
        )
        rows["fleet_product"].append(
            _collect(step, fleet_args, f"batch={b}")
        )

    # dest-sharded wan100k fleet product (ROADMAP open item): P=1024 over
    # the full 100k-node WAN.  Structural rows — executing the product
    # twice on the single-core virtual mesh adds no evidence beyond the
    # per-device compiled cost (see the note below), so the rows are
    # compile-only.  The sweep hint stays at the runner default: fixed
    # sweeps scale the b=1 and b=8 programs identically, so the flops
    # ratio and the collective count are hint-invariant.
    if _budget_left() < 120:
        # two more full-product compiles at 100k nodes — shed, do
        # not die mid-row (BENCH_r05 hit rc=124 exactly here)
        rows["fleet_product_wan100k"] = _shed_marker(
            "fleet_product_wan100k"
        )
    else:
        try:
            w100 = synthetic.wan()  # 100k nodes, chords=2
            w100runner = synthetic.reversed_topology(w100).runner
            rng100 = np.random.default_rng(7)
            dests100 = np.sort(
                rng100.choice(w100.n_nodes, size=1024, replace=False).astype(
                    np.int32
                )
            )
            out100 = asrc.build_out_ell(
                w100.edge_src, w100.edge_dst, w100.n_edges, w100.n_nodes
            )
            es_1, ed_1, em_1, eu_1, ov_1 = w100runner.arrays
            fleet100_args = (
                jnp.asarray(dests100),
                w100runner.bg,
                jnp.asarray(es_1),
                jnp.asarray(ed_1),
                jnp.asarray(em_1),
                jnp.asarray(eu_1),
                jnp.asarray(ov_1),
                out100,
                jnp.asarray(w100.edge_metric),
                jnp.asarray(w100.edge_up),
            )
            rows["fleet_product_wan100k"] = []
            for b in (1, 8):
                mesh = pmesh.make_mesh(jax.devices("cpu")[:b], batch_axis=b)
                step = pmesh.fleet_product_sharded(
                    mesh,
                    n_sweeps=w100runner.hint,
                    n_words=out100.n_words,
                    depth=w100runner.depth,
                    resid_rounds=w100runner.resid_rounds,
                    small_dist=w100runner.small_dist,
                    chord_mode=w100runner.chord_mode,
                )
                rows["fleet_product_wan100k"].append(
                    _collect(step, fleet100_args, f"batch={b}", execute=False)
                )
        except Exception as exc:  # keep the small-topology rows publishable
            rows["fleet_product_wan100k"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }

    # node-axis sharding: the blocked min-plus APSP rung
    # (parallel.blocked) at N >= 1M over the ("batch", "row", "col")
    # mesh.  Structural rows: each phase kernel is AOT-compiled from
    # ShapeDtypeStructs (a [1M, 1M] uint32 state is ~4 TB — it can only
    # ever exist SHARDED, which is the point), and the per-device
    # bytes/FLOPs of the compiled body are compared against the ideal
    # N^2/devices split with collectives attributed per phase.
    if _budget_left() < 60:
        rows["blocked_1m"] = _shed_marker("blocked_1m")
    else:
        try:
            rows["blocked_1m"] = _blocked_rows(n_nodes=1 << 20, tile=4096)
        except Exception as exc:
            rows["blocked_1m"] = {"error": f"{type(exc).__name__}: {exc}"}

    # pipelined blocked closure at the same N (compile-only): lower
    # the fused blocked_round_pipelined root, materialize async
    # all-gather-start/done spans from the scheduled HLO, and
    # headline-assert the pairs bracket the outer-update compute
    # (hard asserts live inside _pipelined_row).
    if _budget_left() < 90:
        rows["blocked_pipelined_1m"] = _shed_marker("blocked_pipelined_1m")
    else:
        try:
            rows["blocked_pipelined_1m"] = _pipelined_row(
                n_nodes=1 << 20, tile=4096, bulk_row=rows["blocked_1m"]
            )
        except Exception as exc:
            rows["blocked_pipelined_1m"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }

    return _summary(topo, n_sources, n_variants, rows)


def _summary(topo, n_sources: int, n_variants: int, rows: dict) -> dict:
    """Assemble the headline summary.  Any row may be a shed-marker or
    error dict (wall budget exhausted mid-run) — every cross-row ratio
    degrades to None instead of KeyErroring, so a partial run still
    emits valid JSON."""
    f1 = rows["allsrc"][0]["flops_per_device"]
    f8 = rows["allsrc"][3]["flops_per_device"]
    w1 = rows["allsrc"][0]["wall_ms_min"]
    w8 = rows["allsrc"][3]["wall_ms_min"]
    fleet = rows["fleet_product"]
    pipe = rows["blocked_pipelined_1m"]
    return {
        "topology": topo.name,
        "n_sources": n_sources,
        "n_variants": n_variants,
        "rows": rows,
        "flops_ratio_8dev": round(f8 / f1, 4) if f1 else None,
        "ideal_flops_ratio": 0.125,
        "singlecore_wall_overhead_8dev": (
            round(w8 / w1, 3) if w1 else None
        ),
        "batch_layout_collectives": rows["allsrc"][3]["collective_ops"],
        "node_layout_collectives": rows["node_axis"].get(
            "collective_ops"
        ),
        "fleet_flops_ratio_8dev": (
            round(
                fleet[1]["flops_per_device"]
                / fleet[0]["flops_per_device"],
                4,
            )
            if isinstance(fleet, list) and fleet[0]["flops_per_device"]
            else None
        ),
        "fleet_8dev_collectives": (
            fleet[1]["collective_ops"] if isinstance(fleet, list) else None
        ),
        "fleet_wan100k_flops_ratio_8dev": (
            round(
                rows["fleet_product_wan100k"][1]["flops_per_device"]
                / rows["fleet_product_wan100k"][0]["flops_per_device"],
                4,
            )
            if isinstance(rows["fleet_product_wan100k"], list)
            and rows["fleet_product_wan100k"][0]["flops_per_device"]
            else None
        ),
        "fleet_wan100k_8dev_collectives": (
            rows["fleet_product_wan100k"][1]["collective_ops"]
            if isinstance(rows["fleet_product_wan100k"], list)
            else None
        ),
        "blocked_1m_bytes_ratio": rows["blocked_1m"].get(
            "outer_bytes_ratio"
        ),
        "blocked_1m_flops_ratio": rows["blocked_1m"].get(
            "outer_flops_ratio"
        ),
        "blocked_pipelined_overlap_frac": pipe.get("overlap_frac_est"),
        "blocked_pipelined_spans_outer": pipe.get(
            "spans_bracketing_outer"
        ),
        "blocked_pipelined_gather_vs_bulk": pipe.get(
            "gather_bytes_vs_bulk"
        ),
        "note": (
            "virtual 8-device CPU mesh on ONE physical core: wall-clock "
            "speedup is unmeasurable here, so the linearity assumption "
            "is validated structurally — per-device compiled FLOPs must "
            "divide by the batch factor (flops_ratio_8dev ~ 0.125), the "
            "batch layout's collectives must be only the O(1) "
            "convergence-verdict scalar reductions, and the single-core "
            "wall ratio bounds the sharding overhead factor that "
            "multiplies any real-hardware projection"
        ),
    }


if __name__ == "__main__":
    print(json.dumps(run()))
