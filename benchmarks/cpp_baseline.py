"""ctypes driver for the C++ Dijkstra baseline (benchmarks/cpp/spf_baseline.cpp).

Compiles on demand with g++ -O3 (cached by source mtime) — the baseline for
`vs_baseline` is real native sequential Dijkstra, not a Python oracle."""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "cpp" / "spf_baseline.cpp"
_SO = _DIR / "cpp" / "build" / "libspf_baseline.so"

_lib = None


def _ensure_built() -> Path:
    _SO.parent.mkdir(parents=True, exist_ok=True)
    if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
        subprocess.run(
            [
                "g++",
                "-O3",
                "-march=native",
                "-std=c++17",
                "-shared",
                "-fPIC",
                str(_SRC),
                "-o",
                str(_SO),
            ],
            check=True,
        )
    return _SO


def load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(_ensure_built()))
        lib.spf_all_sources.restype = ctypes.c_double
        lib.spf_all_sources.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int,
            ctypes.c_void_p,
        ]
        _lib = lib
    return _lib


def spf_all_sources(
    n_nodes: int,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_metric: np.ndarray,
    edge_up: np.ndarray | None,
    node_overloaded: np.ndarray | None,
    sources: np.ndarray,
    want_dist: bool = False,
) -> tuple[float, np.ndarray | None]:
    """Returns (seconds, dist [S, n_nodes] or None)."""
    lib = load()
    n_edges = len(edge_src)
    edge_src = np.ascontiguousarray(edge_src, dtype=np.int32)
    edge_dst = np.ascontiguousarray(edge_dst, dtype=np.int32)
    edge_metric = np.ascontiguousarray(edge_metric, dtype=np.int32)
    if edge_up is None:
        edge_up = np.ones(n_edges, dtype=np.uint8)
    else:
        edge_up = np.ascontiguousarray(edge_up, dtype=np.uint8)
    if node_overloaded is None:
        node_overloaded = np.zeros(n_nodes, dtype=np.uint8)
    else:
        node_overloaded = np.ascontiguousarray(node_overloaded, dtype=np.uint8)
    sources = np.ascontiguousarray(sources, dtype=np.int32)
    out = (
        np.empty((len(sources), n_nodes), dtype=np.int32)
        if want_dist
        else None
    )
    secs = lib.spf_all_sources(
        n_nodes,
        n_edges,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        sources,
        len(sources),
        out.ctypes.data_as(ctypes.c_void_p) if out is not None else None,
    )
    return float(secs), out
