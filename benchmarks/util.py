"""Benchmark helpers: one JSON line per metric (SURVEY §6 harness)."""

from __future__ import annotations

import json
import time
from typing import Callable


def measure_ms(fn: Callable[[], None], reps: int = 3, warmup: int = 1) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def emit(metric: str, value: float, unit: str = "ms", **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 3), "unit": unit, **extra}))
