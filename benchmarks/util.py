"""Benchmark helpers: one JSON line per metric (SURVEY §6 harness)."""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

# Peak HBM bandwidth of the bench device, bytes/s.  Default is the
# v5e figure (819 GB/s per chip); override with OPENR_PEAK_HBM_BW for
# other parts so utilization fractions stay honest across hardware.
PEAK_HBM_BW = float(os.environ.get("OPENR_PEAK_HBM_BW", 819e9))


def achieved_bw_frac(
    bytes_moved: Optional[float], wall_ms: Optional[float]
) -> Optional[float]:
    """Fraction of peak HBM bandwidth achieved: bytes-moved /
    (wall x peak BW).  The utilization lens on every device row — a
    memory-bound kernel near 1.0 is done; a small fraction says the
    wall is dispatch/latency, not bandwidth.  None when either input is
    missing/degenerate (e.g. a row that never timed)."""
    if not bytes_moved or not wall_ms or wall_ms <= 0:
        return None
    return round(float(bytes_moved) / (wall_ms * 1e-3 * PEAK_HBM_BW), 4)


def peak_bw_source() -> str:
    """Provenance of the PEAK_HBM_BW figure used by achieved_bw_frac:
    "env" when the operator pinned OPENR_PEAK_HBM_BW, "default_v5e"
    otherwise.  Recorded next to roofline fractions so a row compared
    across machines says which denominator it was computed against."""
    return "env" if os.environ.get("OPENR_PEAK_HBM_BW") else "default_v5e"


def measure_ms(fn: Callable[[], None], reps: int = 3, warmup: int = 1) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def emit(metric: str, value: float, unit: str = "ms", **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 3), "unit": unit, **extra}))
