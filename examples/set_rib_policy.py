"""SetRibPolicyExample: install a RibPolicy through the ctrl API
(reference: examples/SetRibPolicyExample.cpp — build a RibPolicy with a
prefix-match statement and action weights, send setRibPolicy).

Run: python -m examples.set_rib_policy --port 2018 --prefix fc00::/64
"""

from __future__ import annotations

import argparse

from openr_tpu.ctrl import CtrlClient
from openr_tpu.decision.rib_policy import (
    RibPolicyConfig,
    RibPolicyStatementConfig,
    RibRouteActionWeight,
)


def build_policy(
    prefix: str, ttl_secs: int, default_weight: int = 1
) -> RibPolicyConfig:
    return RibPolicyConfig(
        statements=[
            RibPolicyStatementConfig(
                name="example-statement",
                prefixes=[prefix],
                set_weight=RibRouteActionWeight(
                    default_weight=default_weight,
                    area_to_weight={"0": 2},
                ),
            )
        ],
        ttl_secs=ttl_secs,
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="::1")
    parser.add_argument("--port", type=int, default=2018)
    parser.add_argument("--prefix", required=True)
    parser.add_argument("--ttl-secs", type=int, default=300)
    args = parser.parse_args(argv)

    client = CtrlClient(args.host, args.port)
    try:
        client.call(
            "setRibPolicy", policy=build_policy(args.prefix, args.ttl_secs)
        )
        print("policy installed:")
        print(client.call("getRibPolicy"))
    finally:
        client.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
