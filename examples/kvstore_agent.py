"""KvStoreAgent: consume openr_tpu as a LIBRARY next to a running daemon.

Mirrors /root/reference/examples/KvStoreAgent.cpp:15-45: an application
module with its own event base that (a) persists a key under its own
prefix, bumping the value periodically, and (b) subscribes to every key
under that prefix to observe other nodes' agents.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from openr_tpu.kvstore import KvStoreClientInternal
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import RQueue

log = logging.getLogger(__name__)

AGENT_KEY_PREFIX = "agentData:"


class KvStoreAgent(OpenrEventBase):
    """Reference: class KvStoreAgent (examples/KvStoreAgent.cpp)."""

    def __init__(
        self,
        node_id: str,
        kvstore,
        kvstore_updates: RQueue,
        area: str = "0",
        change_interval_s: float = 0.2,
        on_peer_data: Optional[Callable[[str, bytes], None]] = None,
    ) -> None:
        super().__init__(name=f"kvstore-agent-{node_id}")
        self.node_id = node_id
        self.area = area
        self.change_interval_s = change_interval_s
        self.on_peer_data = on_peer_data
        self.peer_data: dict[str, bytes] = {}
        self._val = 0
        self._kvstore = kvstore
        self._kvstore_updates = kvstore_updates
        self.client: Optional[KvStoreClientInternal] = None
        self._timer = None

    def start(self) -> None:
        self.run()
        self.wait_until_running()
        # the client lives on THIS event base (the library pattern: any
        # OpenrEventBase owner can host a KvStoreClientInternal)
        self.client = KvStoreClientInternal(
            self,
            self.node_id,
            self._kvstore,
            self._kvstore_updates,
        )
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        # watch everyone's agent keys (reference: setKvCallback + prefix
        # filter, KvStoreAgent.cpp:24-34)
        self.client.subscribe_key_filter(f"^{AGENT_KEY_PREFIX}", self._on_key)
        self._tick()

    def _on_key(self, key: str, value) -> None:
        if value is None or value.value is None:
            return
        if value.originator_id == self.node_id:
            return
        log.info(
            "got data from %s: %r", value.originator_id, value.value
        )
        self.peer_data[value.originator_id] = value.value
        if self.on_peer_data is not None:
            self.on_peer_data(value.originator_id, value.value)

    def _tick(self) -> None:
        # periodically change our value (reference: periodicValueChanger_,
        # KvStoreAgent.cpp:37-44); persistKey re-advertises with a version
        # bump if anyone overwrites us
        self._val += 1
        self.client.persist_key(
            self.area,
            f"{AGENT_KEY_PREFIX}{self.node_id}",
            str(self._val).encode(),
        )
        self._timer = self.schedule_timeout(self.change_interval_s, self._tick)

    def stop(self) -> None:  # type: ignore[override]
        if self.client is not None:
            self.client.stop()
        super().stop()
