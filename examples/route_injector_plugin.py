"""Route-injector plugin: the BGP-speaker seam in miniature.

Set `plugin_module: "examples.route_injector_plugin"` in the daemon config
and this module attaches at the reference's pluginStart point
(openr/Main.cpp:501-510): it originates a BGP-type prefix through the
PrefixManager queue and tails every computed route delta, mirroring what
the closed-source BGP speaker does with the same three queues.
"""

from __future__ import annotations

import logging
import threading

from openr_tpu.runtime.queue import QueueClosedError
from openr_tpu.types import PrefixEntry, PrefixType, PrefixUpdateRequest

log = logging.getLogger(__name__)

INJECTED_PREFIX = "fc00:b9b:1::/64"


class _Injector:
    def __init__(self, args) -> None:
        self.args = args
        self.seen_route_updates = 0
        self.injected = threading.Event()
        self._reader = args.route_updates_queue
        self._thread = threading.Thread(
            target=self._tail_routes, name="route-injector", daemon=True
        )

    def start(self) -> None:
        # originate one BGP-type prefix (reference: plugin pushes
        # PrefixEvent onto prefixUpdatesQueue)
        self.args.prefix_updates_queue.push(
            PrefixUpdateRequest(
                prefixes_to_add=[PrefixEntry(prefix=INJECTED_PREFIX)],
                type=PrefixType.BGP,
            )
        )
        self.injected.set()
        self._thread.start()

    def _tail_routes(self) -> None:
        # observe every DecisionRouteUpdate (reference: plugin consumes
        # routeUpdatesQueue reader for BGP re-advertisement)
        while True:
            try:
                update = self._reader.get()
            except QueueClosedError:
                return
            self.seen_route_updates += 1
            log.debug(
                "route update: +%d unicast -%d",
                len(update.unicast_routes_to_update),
                len(update.unicast_routes_to_delete),
            )

    def stop(self) -> None:
        # unblock the tail thread's get() deterministically (the daemon
        # stops plugins before it closes the queues)
        self._reader.close()
        self._thread.join(1.0)


def plugin_start(args) -> _Injector:
    injector = _Injector(args)
    injector.start()
    log.info("route injector attached for %s", args.node_name)
    return injector


def plugin_stop(handle: _Injector) -> None:
    handle.stop()
