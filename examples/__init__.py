"""Library-usage examples (reference: /examples — KvStoreAgent.cpp,
KvStorePoller.cpp, SetRibPolicyExample.cpp) plus a plugin-seam route
injector. Each is runnable against a live daemon and exercised by
tests/test_examples.py."""
