"""KvStorePoller: poll several nodes' ctrl endpoints and dump their
KvStore contents side by side (reference: examples/KvStorePoller.cpp —
fan out getKvStoreKeyValsArea to a set of (addr, port) endpoints).

Run: python -m examples.kvstore_poller host:port [host:port ...]
"""

from __future__ import annotations

import sys
from typing import Iterable, Optional

from openr_tpu.ctrl import CtrlClient


def poll(
    endpoints: Iterable[tuple[str, int]], area: str = "0"
) -> dict[str, Optional[dict[str, object]]]:
    """{endpoint: {key: Value}} for every reachable endpoint; unreachable
    endpoints map to None (the reference logs and skips them)."""
    out: dict[str, dict[str, object]] = {}
    for host, port in endpoints:
        name = f"[{host}]:{port}"
        client = CtrlClient(host, port)
        try:
            pub = client.call(
                "getKvStoreKeyValsFilteredArea", area=area, match_all=True
            )
            out[name] = dict(pub.key_vals)
        except (ConnectionError, OSError):
            out[name] = None
        finally:
            client.close()
    return out


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print("usage: kvstore_poller host:port [host:port ...]")
        return 2
    endpoints = []
    for spec in args:
        host, _, port = spec.rpartition(":")
        if not port.isdigit():
            print(f"bad endpoint {spec!r} (expected host:port)")
            return 2
        endpoints.append((host or "::1", int(port)))
    for name, keys in poll(endpoints).items():
        if keys is None:
            print(f"{name}: unreachable")
            continue
        print(f"{name}: {len(keys)} keys")
        for key, val in sorted(keys.items()):
            print(f"  {key} v={val.version} from={val.originator_id}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
