"""mTLS + peer-name ACL on the ctrl transport (reference: wangle TLS and
client-CN allowlist, openr/Main.cpp:546-612).  Certificates are minted
with the system openssl; two daemons peer over real TLS sockets, plaintext
and ACL-failing clients are rejected."""

from __future__ import annotations

import contextlib
import io
import subprocess

import pytest

from openr_tpu.cli import breeze
from openr_tpu.ctrl import CtrlClient
from openr_tpu.ctrl.tls import TlsConfig, check_acl
from openr_tpu.config import TlsConf
from openr_tpu.main import OpenrDaemon
from openr_tpu.spark import MockIoProvider
from openr_tpu.types import LinkEvent, PrefixEntry, PrefixType, normalize_prefix
from tests.test_platform_agent import free_port
from tests.test_system import FIB_CLIENT, make_config, wait_for


def _openssl(*argv: str) -> None:
    subprocess.run(["openssl", *argv], check=True, capture_output=True)


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """One CA + node certs 'tls-0', 'tls-1', 'rogue-node'."""
    root = tmp_path_factory.mktemp("pki")
    ca_key, ca_crt = root / "ca.key", root / "ca.crt"
    _openssl(
        "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt),
        "-days", "1", "-subj", "/CN=openr-test-ca",
    )
    certs = {}
    for name in ("tls-0", "tls-1", "rogue-node"):
        key, csr, crt = root / f"{name}.key", root / f"{name}.csr", root / f"{name}.crt"
        _openssl(
            "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={name}",
        )
        _openssl(
            "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
            "-CAkey", str(ca_key), "-CAcreateserial",
            "-out", str(crt), "-days", "1",
        )
        certs[name] = (str(crt), str(key))
    return str(ca_crt), certs


def _tls_conf(pki, name: str, acl: str = "tls-.*") -> TlsConf:
    ca, certs = pki
    crt, key = certs[name]
    return TlsConf(cert_path=crt, key_path=key, ca_path=ca, acl_regex=acl)


def _client_cfg(pki, name: str) -> TlsConfig:
    ca, certs = pki
    crt, key = certs[name]
    return TlsConfig(cert_path=crt, key_path=key, ca_path=ca)


def _make_tls_pair(pki, flood_optimization: bool = False):
    """Two TLS daemons wired over a mock spark fabric; stops whatever came
    up even if startup fails part-way."""
    fabric = MockIoProvider()
    ports = (free_port(), free_port())
    daemons = []
    try:
        for i, port in enumerate(ports):
            cfg = make_config(
                f"tls-{i}", ctrl_port=port,
                flood_optimization=flood_optimization,
            )
            cfg.tls_config = _tls_conf(pki, f"tls-{i}")
            d = OpenrDaemon(
                cfg,
                io_provider=fabric.endpoint(f"tls-{i}"),
                spark_v6_addr="::1",
            )
            d.start()
            daemons.append(d)
        fabric.connect("tls-0", "t0", "tls-1", "t1")
        daemons[0].netlink_events_queue.push(LinkEvent("t0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("t1", 1, True))
    except Exception:
        for d in daemons:
            d.stop()
        raise
    return daemons, ports


class TestTlsCtrl:
    @pytest.fixture
    def tls_pair(self, pki):
        daemons, ports = _make_tls_pair(pki)
        yield daemons, ports
        for d in daemons:
            d.stop()

    def test_kvstore_peering_and_routes_over_mtls(self, tls_pair):
        """The peer transport rides the same TLS ctrl servers: full
        convergence proves dual-direction mTLS works."""
        daemons, ports = tls_pair
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc05::/64")]
        )
        assert wait_for(
            lambda: normalize_prefix("fc05::/64")
            in daemons[0].fib_agent.unicast.get(FIB_CLIENT, {}),
            timeout=30,
        )

    def test_plaintext_client_rejected(self, tls_pair):
        daemons, ports = tls_pair
        client = CtrlClient("::1", ports[0], timeout_s=2.0)
        with pytest.raises((ConnectionError, OSError, RuntimeError)):
            client.call("getMyNodeName")
        client.close()

    def test_mtls_client_works_and_breeze(self, pki, tls_pair):
        daemons, ports = tls_pair
        client = CtrlClient("::1", ports[0], tls=_client_cfg(pki, "tls-1"))
        try:
            assert client.call("getMyNodeName") == "tls-0"
        finally:
            client.close()
        # breeze with TLS flags
        ca, certs = pki
        crt, key = certs["tls-1"]
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = breeze.main(
                ["-p", str(ports[0]), "--tls-cert", crt, "--tls-key", key,
                 "--tls-ca", ca, "kvstore", "peers"]
            )
        assert rc == 0, out.getvalue()

    def test_client_rejects_server_cn_not_in_acl(self, pki, tls_pair):
        """Client-side mirror of the ACL (ADVICE r2: tls.py:40): hostname
        checking is off, so the client verifies the server certificate's
        CN against the ACL regex after the handshake — a CA-signed but
        unexpected server identity must be rejected."""
        import ssl

        daemons, ports = tls_pair
        cfg = _client_cfg(pki, "tls-1")
        cfg.acl_regex = "some-other-node"
        client = CtrlClient("::1", ports[0], timeout_s=2.0, tls=cfg)
        with pytest.raises(ssl.SSLCertVerificationError):
            client.call("getMyNodeName")
        client.close()

    def test_acl_rejects_wrong_cn(self, pki, tls_pair):
        """rogue-node's cert is CA-valid but its CN fails the tls-.* ACL —
        the reference's peer-name allowlist behavior."""
        daemons, ports = tls_pair
        client = CtrlClient(
            "::1", ports[0], timeout_s=2.0, tls=_client_cfg(pki, "rogue-node")
        )
        with pytest.raises((ConnectionError, OSError, RuntimeError)):
            client.call("getMyNodeName")
        client.close()


class TestDualOverTcpTls:
    def test_flood_topology_forms_over_tls_tcp(self, pki):
        """DUAL messages and flood-topo registration ride the (TLS) ctrl
        transport between real daemons: the SPT must form and routes must
        converge — covering processKvStoreDualMessage /
        updateFloodTopologyChild over the wire (they are in-process
        everywhere else)."""
        daemons, ports = _make_tls_pair(pki, flood_optimization=True)
        try:
            daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc06::/64")]
            )
            assert wait_for(
                lambda: normalize_prefix("fc06::/64")
                in daemons[0].fib_agent.unicast.get(FIB_CLIENT, {}),
                timeout=30,
            )
            assert wait_for(
                lambda: all(
                    d.kvstore.get_flood_topo("0").flood_root_id == "tls-0"
                    for d in daemons
                ),
                timeout=20,
            ), [d.kvstore.get_flood_topo("0") for d in daemons]
            # child registration crossed the wire (async after SPT forms)
            assert wait_for(
                lambda: daemons[0]
                .kvstore.get_flood_topo("0")
                .infos["tls-0"]
                .children
                == ["tls-1"],
                timeout=20,
            ), daemons[0].kvstore.get_flood_topo("0")
        finally:
            for d in daemons:
                d.stop()


class TestAclUnit:
    def test_check_acl(self):
        cfg = TlsConfig("c", "k", "a", acl_regex="node-[0-9]+")
        assert check_acl(cfg, "node-12")
        assert not check_acl(cfg, "node-12x")
        assert not check_acl(cfg, "intruder")
        assert not check_acl(cfg, None)
