"""Query-serving layer tests (openr_tpu/serving): admission control,
epoch-keyed coalescing, double-buffered dispatch, invalidation-on-flap,
explicit shedding, both wire surfaces, and the seeded overload scenario.

Every batched answer is held to the serial baseline: the same query
submitted alone through the same backend, and the host Dijkstra oracle
(`LinkState.get_spf_result`).  Coalescing is made deterministic by
parking the pipeline — one batch gated inside the executor, one in the
staging slot, one in the coalescer's blocked put — so everything
submitted afterwards must ride a single batch.
"""

from __future__ import annotations

import re
import threading

import pytest

from openr_tpu.chaos import OpenLoopLoadGen
from openr_tpu.decision.spf_solver import DeviceSpfBackend
from openr_tpu.device.engine import EpochMismatchError
from openr_tpu.serving import (
    EngineBatchBackend,
    QueryScheduler,
    QueryShedError,
    SERVING_COUNTER_KEYS,
)
from openr_tpu.types import AdjacencyDatabase

from test_spf_solver import adj, build_link_state, square
from test_system import wait_for

# force the device path on tiny topologies: the serving layer's whole
# point is riding the engine's bucketed programs
_DEVICE = dict(min_device_nodes=1, min_device_sources=1)


def make_scheduler(ls=None, **kwargs):
    ls = square() if ls is None else ls
    backend = EngineBatchBackend(
        {"0": ls}, spf_backend=DeviceSpfBackend(**_DEVICE)
    )
    sched = QueryScheduler(backend, **kwargs)
    sched.run()
    return ls, backend, sched


def serial_backend(ls):
    """A fresh backend for serial single-query baselines (its own engine,
    so the scheduler's residency/cache state can't leak into it)."""
    return EngineBatchBackend(
        {"0": ls}, spf_backend=DeviceSpfBackend(**_DEVICE)
    )


class _Gate:
    """trace_hook that records the pipeline event timeline and blocks
    every execute until released."""

    def __init__(self) -> None:
        self.events: list[tuple[str, int, int]] = []  # (event, batch id, n)
        self.release = threading.Event()
        self._lock = threading.Lock()

    def __call__(self, event: str, batch) -> None:
        with self._lock:
            self.events.append((event, id(batch), len(batch.pendings)))
        if event == "execute_begin":
            self.release.wait(15)

    def count(self, event: str) -> int:
        with self._lock:
            return sum(1 for e in self.events if e[0] == event)


def park_pipeline(sched, gate):
    """Fill the double buffer: warm batch 1 gated inside the executor,
    batch 2 in the staging slot, batch 3 in the coalescer's blocked put.
    Everything submitted after this parks in the admission queue and is
    coalesced in ONE round once the gate opens."""
    warm = [sched.submit("paths", sources=("1",))]
    assert wait_for(lambda: gate.count("execute_begin") == 1, 10)
    warm.append(sched.submit("paths", sources=("1",)))
    assert wait_for(lambda: gate.count("stage") == 2, 10)
    warm.append(sched.submit("paths", sources=("1",)))
    assert wait_for(lambda: gate.count("stage") == 3, 10)
    return warm


class TestCoalescingBitExact:
    def test_paths_batch_bit_exact_vs_serial_and_oracle(self):
        ls, backend, sched = make_scheduler()
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            futs = {
                s: sched.submit("paths", sources=(s,)) for s in "1234"
            }
            gate.release.set()
            results = {s: f.result(20) for s, f in futs.items()}
            for f in warm:
                f.result(20)

            # all four single-source queries rode ONE batch at one epoch
            assert {r.batch_size for r in results.values()} == {4}
            assert {r.epoch for r in results.values()} == {int(ls.version)}

            serial = serial_backend(ls)
            for s, r in results.items():
                spf = r.value[s]
                one = serial.run_paths(
                    "0", [s], expect_epoch=int(ls.version)
                )[s]
                oracle = ls.get_spf_result(s)
                for view in (one, oracle):
                    assert set(spf) == set(view)
                    for dest in view:
                        assert spf[dest].metric == view[dest].metric
                        assert spf[dest].next_hops == view[dest].next_hops

            counters = sched.get_counters()
            assert counters["serving.replies"] == 7
            assert counters["serving.coalesced"] >= 3
            assert counters["serving.shed"] == 0
            assert counters["serving.errors"] == 0
            # mean occupancy gauge is milli-queries-per-batch
            assert counters["serving.batch_occupancy"] > 1000
            assert counters["serving.p99_us"] >= counters["serving.p50_us"]
        finally:
            gate.release.set()
            sched.stop()

    def test_what_if_coalesced_matches_serial(self):
        ls, backend, sched = make_scheduler()
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            fa = sched.submit(
                "what_if", sources=("1",), scenarios=((("1", "2"),),)
            )
            fb = sched.submit(
                "what_if",
                sources=("1",),
                scenarios=((("3", "4"),), (("2", "4"),)),
            )
            gate.release.set()
            ra, rb = fa.result(20), fb.result(20)
            for f in warm:
                f.result(20)
            # same source view -> one coalesced what-if batch
            assert ra.batch_size == 2 and rb.batch_size == 2

            serial = serial_backend(ls)
            sa = serial.run_what_if(
                "0", ["1"], [[("1", "2")]], expect_epoch=int(ls.version)
            )
            sb = serial.run_what_if(
                "0",
                ["1"],
                [[("3", "4")], [("2", "4")]],
                expect_epoch=int(ls.version),
            )
            # scenario ids are renumbered to each query's own view
            assert ra.value == sa
            assert rb.value == sb
            assert [row["scenario"] for row in rb.value] == [0, 1]
        finally:
            gate.release.set()
            sched.stop()

    def test_ksp_coalesced_matches_serial(self):
        # 1-2-3 chain (10+10) plus a 50-metric direct 1-3 chord: k=2
        # from "1" has a real second path
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3", metric=50)],
                "2": [adj("2", "1"), adj("2", "3")],
                "3": [adj("3", "2"), adj("3", "1", metric=50)],
            }
        )
        ls, backend, sched = make_scheduler(ls)
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            fa = sched.submit("ksp", sources=("1",), dests=("3",), k=2)
            fb = sched.submit(
                "ksp", sources=("1",), dests=("2", "3"), k=2
            )
            gate.release.set()
            ra, rb = fa.result(20), fb.result(20)
            for f in warm:
                f.result(20)
            assert ra.batch_size == 2 and rb.batch_size == 2

            serial = serial_backend(ls)
            sa = serial.run_ksp(
                "0", "1", ["3"], k=2, expect_epoch=int(ls.version)
            )
            assert ra.value == sa
            # the k=2 (edge-disjoint) tier is exactly the 1-3 chord
            assert len(ra.value["3"]) == 1
            assert len(ra.value["3"][0]) == 1
            sb = serial.run_ksp(
                "0", "1", ["2", "3"], k=2, expect_epoch=int(ls.version)
            )
            assert rb.value == sb
        finally:
            gate.release.set()
            sched.stop()


class TestPipelineMechanics:
    def test_double_buffer_overlaps_stage_with_execute(self):
        ls, backend, sched = make_scheduler()
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            gate.release.set()
            for f in warm:
                f.result(20)
            events = [e[0] for e in gate.events]
            # batch 2 was STAGED while batch 1 was still executing: the
            # second stage event lands before the first execute_end
            second_stage = [i for i, e in enumerate(events) if e == "stage"][1]
            first_end = events.index("execute_end")
            assert second_stage < first_end, events
        finally:
            gate.release.set()
            sched.stop()

    def test_admission_overflow_sheds_oldest_explicitly(self):
        ls, backend, sched = make_scheduler(max_pending=4)
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            futs = [
                sched.submit("paths", sources=("1",)) for _ in range(12)
            ]
            gate.release.set()
            replied = shed = 0
            for f in futs + warm:
                try:
                    f.result(20)
                    replied += 1
                except QueryShedError:
                    shed += 1
            # drop-oldest on a 4-slot queue: 8 of the 12 shed, every
            # one of them with an explicit error — nothing unresolved
            assert shed == 8 and replied == 7
            assert all(f.done() for f in futs + warm)
            counters = sched.get_counters()
            assert counters["serving.admitted"] == 15
            assert counters["serving.shed"] == 8
            assert counters["serving.replies"] == 7
            assert sched.admission.stats()["overflows"] == 8
        finally:
            gate.release.set()
            sched.stop()

    def test_flap_invalidates_coalesced_but_undispatched_batch(self):
        ls, backend, sched = make_scheduler()
        gate = _Gate()
        sched.trace_hook = gate
        try:
            warm = park_pipeline(sched, gate)
            # every parked batch pinned the pre-flap epoch; removing the
            # 2-4 link moves the topology out from under them
            epoch_before = int(ls.version)
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="2",
                    adjacencies=[adj("2", "1")],
                    is_overloaded=False,
                    node_label=102,
                    area="0",
                )
            )
            assert int(ls.version) != epoch_before
            gate.release.set()
            results = [f.result(20) for f in warm]
            # dispatch noticed the mismatch, re-pinned, recomputed fresh
            assert sched.get_counters()["serving.invalidations"] >= 1
            oracle = ls.get_spf_result("1")
            for r in results:
                assert r.epoch == int(ls.version)
                spf = r.value["1"]
                assert spf["4"].next_hops == oracle["4"].next_hops == {"3"}
                assert spf["4"].metric == oracle["4"].metric
        finally:
            gate.release.set()
            sched.stop()

    def test_engine_refuses_moved_epoch_before_device_work(self):
        ls = square()
        backend = serial_backend(ls)
        csr = backend.spf.csr_mirror(ls)
        engine = backend.spf.engine
        with pytest.raises(EpochMismatchError) as ei:
            engine.spf_results(csr, ["1"], expect_epoch=int(csr.version) + 1)
        assert ei.value.expected == int(csr.version) + 1
        assert ei.value.actual == int(csr.version)
        assert engine.counters["device.engine.epoch_invalidations"] == 1
        # the matching epoch serves normally
        res = engine.spf_results(csr, ["1"], expect_epoch=int(csr.version))
        assert "1" in res

    def test_shutdown_resolves_every_future(self):
        ls, backend, sched = make_scheduler()
        futs = [
            sched.submit("paths", sources=(s,)) for s in "1234" * 8
        ]
        sched.stop()
        assert all(f.done() for f in futs)
        outcomes = {"replied": 0, "shed": 0}
        for f in futs:
            try:
                f.result(0)
                outcomes["replied"] += 1
            except QueryShedError:
                outcomes["shed"] += 1
        # zero silent drops at shutdown: every future resolved, and the
        # scheduler's own ledger agrees with what the callers saw
        assert outcomes["replied"] + outcomes["shed"] == len(futs)
        counters = sched.get_counters()
        assert counters["serving.replies"] == outcomes["replied"]
        assert counters["serving.shed"] == outcomes["shed"]

    def test_counter_keys_follow_convention(self):
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in SERVING_COUNTER_KEYS)


@pytest.mark.chaos
class TestOverloadScenario:
    """Seeded open-loop overload (tier-1 deterministic-seed variant of
    the soak): offered load far above capacity must shed with explicit
    errors — never drop silently — and a device fault mid-run demotes to
    the host rung without dropping service."""

    def test_overload_sheds_explicitly_and_fault_keeps_serving(self):
        ls = square()
        backend = EngineBatchBackend(
            {"0": ls}, spf_backend=DeviceSpfBackend(**_DEVICE)
        )
        sched = QueryScheduler(backend, max_pending=16)
        sched.run()
        try:
            engine = backend.spf.engine
            gen = OpenLoopLoadGen(
                sched,
                nodes=["1", "2", "3", "4"],
                seed=20260805,
                clients=4,
            )
            # phase 1: burst far above a 16-slot admission queue
            r1 = gen.run_burst(per_client=100)
            assert r1.submitted == 400
            assert r1.accounted == r1.submitted, "silent drop detected"
            assert r1.shed > 0, "open-loop overload never shed"
            assert r1.replied > 0
            assert sched.admission.stats()["overflows"] == r1.shed

            # phase 2: hard device fault on every SPF entry — the
            # degradation ladder's host rung keeps answering
            def fault(op: str) -> None:
                if op == "spf":
                    raise RuntimeError("injected device fault")

            engine.fault_hook = fault
            r2 = gen.run_burst(per_client=10)
            engine.fault_hook = None
            assert r2.accounted == r2.submitted, "silent drop under fault"
            assert r2.replied > 0, "host-fallback rung stopped serving"

            counters = sched.get_counters()
            assert counters["serving.host_fallbacks"] > 0
            # scheduler ledger == client-observed outcomes, both phases
            assert counters["serving.shed"] == r1.shed + r2.shed
            assert counters["serving.replies"] == r1.replied + r2.replied
            assert counters["serving.errors"] == r1.errors + r2.errors == 0
            # static topology: residency synced the graph exactly once
            assert engine.counters["device.engine.full_restages"] == 1

            # a post-fault reply is still bit-exact vs the host oracle
            res = sched.submit("paths", sources=("1",)).result(20)
            oracle = ls.get_spf_result("1")
            assert set(res.value["1"]) == set(oracle)
            for dest, nr in oracle.items():
                assert res.value["1"][dest].metric == nr.metric
                assert res.value["1"][dest].next_hops == nr.next_hops
        finally:
            sched.stop()


class TestServingWire:
    """End-to-end over both wire surfaces: the ctrl server's async query
    methods and the thrift shim's batched-paths RPC, against a live
    two-daemon fabric (the in-daemon DecisionBatchBackend path)."""

    @pytest.fixture
    def ring2(self):
        from test_system import RingFixture

        ring = RingFixture(2)
        try:

            def linked() -> bool:
                for i, daemon in enumerate(ring.daemons):
                    ls = daemon.decision.area_link_states.get("0")
                    if ls is None or not ls.links_from_node(f"openr-{i}"):
                        return False
                return True

            assert wait_for(linked, 30), "2-ring never formed adjacency"
            yield ring
        finally:
            ring.stop()

    def test_ctrl_async_query_methods(self, ring2):
        from openr_tpu.ctrl import CtrlClient

        d0 = ring2.daemons[0]
        client = CtrlClient(port=d0.ctrl_port)
        try:
            reply = client.call("queryPaths", sources=["openr-0"])
            assert reply["batchSize"] >= 1 and reply["latencyUs"] >= 0
            spf = reply["result"]["openr-0"]
            assert spf["openr-1"]["nextHops"] == ["openr-1"]
            assert spf["openr-1"]["metric"] > 0

            kreply = client.call(
                "queryKsp", sources=["openr-0"], dests=["openr-1"], k=1
            )
            paths = kreply["result"]["openr-1"]
            assert len(paths) == 1 and len(paths[0]) == 1
            assert set(paths[0][0]) == {"openr-0", "openr-1"}

            wreply = client.call(
                "queryWhatIf",
                sources=["openr-0"],
                scenarios=[[["openr-0", "openr-1"]]],
            )
            row = wreply["result"][0]
            assert row["scenario"] == 0
            # failing the only link strands the one other node
            assert row["newly_unreachable_pairs"] == 1
        finally:
            client.close()

    def test_shim_query_paths_batched(self, ring2):
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        d0 = ring2.daemons[0]
        admitted_before = d0.serving.get_counters()["serving.admitted"]
        shim = ThriftBinaryShim(
            d0.kvstore,
            port=0,
            node_name="openr-0",
            serving=d0.serving,
        )
        shim.run()
        try:
            args = tb.encode_struct(
                tb.StructSpec(
                    "queryPathsBatched_args",
                    None,
                    (
                        tb.Field(1, "sources", ("list", tb.T_STRING)),
                        tb.Field(2, "area", tb.T_STRING),
                    ),
                ),
                {"sources": ["openr-0", "openr-1"], "area": "0"},
            )
            dist = _call_ok(
                shim.port,
                "queryPathsBatched",
                9,
                args,
                ("map", tb.T_STRING, ("map", tb.T_STRING, tb.T_I64)),
                dec=lambda m: {
                    k.decode(): {kk.decode(): vv for kk, vv in v.items()}
                    for k, v in m.items()
                },
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        # both sources answered from one RPC, symmetric single-link ring
        assert dist["openr-0"]["openr-1"] > 0
        assert dist["openr-1"]["openr-0"] == dist["openr-0"]["openr-1"]
        # the RPC rode the scheduler (one submit per source)
        admitted_after = d0.serving.get_counters()["serving.admitted"]
        assert admitted_after - admitted_before == 2


@pytest.mark.slow
class TestServingSoak:
    def test_open_loop_paced_soak(self):
        ls, backend, sched = make_scheduler(max_pending=256)
        try:
            gen = OpenLoopLoadGen(
                sched,
                nodes=["1", "2", "3", "4"],
                seed=7,
                clients=8,
                ops=("paths", "what_if", "ksp"),
            )
            report = gen.run_paced(duration_s=3.0, qps_per_client=40)
            assert report.accounted == report.submitted
            assert report.replied > 0 and report.qps > 0
            assert report.mean_batch_occupancy >= 1.0
            assert report.pctl_us(99) >= report.pctl_us(50) > 0
        finally:
            sched.stop()
