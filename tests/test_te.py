"""Differentiable-TE subsystem tests (openr_tpu/te).

Acceptance contract (ISSUE 10):
(a) the soft objective/distances converge to the exact solver's as the
    temperature anneals to 0 on ring/grid/fattree;
(b) gradients are finite and nonzero under jax.grad on a seeded
    wan-shaped topology;
(c) end-to-end optimize on a seeded congested topology strictly
    improves the EXACT max-utilization and beats-or-matches the host
    hill-climb baseline;
(d) every published metric set is integer, within bounds, and
    exactly validated — a structurally always-reject case shows
    te.rejected incrementing and NO publication;
(e) a mid-run epoch flap aborts loudly (EpochMismatchError) with
    counters accounted, at the optimizer and at the serving scheduler
    (which never retries this op).
"""

from __future__ import annotations

import numpy as np
import pytest

from benchmarks import synthetic as syn
from openr_tpu.device.engine import EpochMismatchError
from openr_tpu.te import TE_COUNTER_KEYS, TeOptimizer, TeProblem, hill_climb
from openr_tpu.te import soft
from openr_tpu.te.exact import INF32, ExactEvaluator

pytestmark = pytest.mark.te

# shared sweep budget: deeper than every test topology's diameter, and a
# single value so the jitted soft kernels compile once per array shape
_SWEEPS = 16


def _ring(n: int = 12):
    links = np.array([[i, (i + 1) % n] for i in range(n)])
    mets = np.tile([1, 1], (n, 1))
    return syn.Topology.from_links("ring", n, links, mets)


def _diamond():
    """The seeded congested case: all demand rides the cheap 0-1-3 path
    (exact max-util 8.0); splitting over 0-2-3 halves it — reachable
    only by raising metrics, which descent must discover."""
    links = np.array([[0, 1], [1, 3], [0, 2], [2, 3]])
    mets = np.array([[1, 1], [1, 1], [2, 2], [2, 2]])
    return syn.Topology.from_links("diamond", 4, links, mets)


def _chain():
    """Structurally always-reject: one path 0-1-2, so utilization is
    metric-invariant and no candidate can strictly improve."""
    links = np.array([[0, 1], [1, 2]])
    mets = np.array([[1, 1], [1, 1]])
    return syn.Topology.from_links("chain", 3, links, mets)


def _problem(topo, dest_ids, demand_pairs, lo=1, hi=16):
    """demand_pairs: {(src_id, dest_col): volume}."""
    dest_ids = np.asarray(dest_ids, dtype=np.int32)
    dm = np.zeros((topo.node_capacity, len(dest_ids)), dtype=np.float32)
    for (s, j), v in demand_pairs.items():
        dm[s, j] = v
    return TeProblem.from_topology(
        topo, dest_ids, dm, metric_lo=lo, metric_hi=hi
    )


def _evaluator(problem, engine=None):
    return ExactEvaluator(
        problem.edge_src, problem.edge_dst, problem.edge_up,
        problem.node_overloaded, problem.n_edges, problem.n_nodes,
        problem.dest_ids, problem.demand, problem.capacity, engine=engine,
    )


def _soft_dist(problem, tau):
    import jax.numpy as jnp

    return np.asarray(
        soft.soft_sssp(
            jnp.asarray(problem.edge_src),
            jnp.asarray(problem.edge_dst),
            jnp.asarray(problem.edge_metric, dtype=jnp.float32),
            jnp.asarray(problem.edge_up),
            jnp.asarray(problem.node_overloaded),
            jnp.asarray(problem.dest_ids),
            np.float32(tau),
            n_sweeps=_SWEEPS,
        )
    )


class TestSoftConvergence:
    """(a): soft distances/objective -> exact as tau -> 0."""

    @pytest.mark.parametrize(
        "topo,dests",
        [
            (_ring(), [0, 6]),
            (syn.grid(4), [0, 15]),
            (syn.fat_tree(2, 2, 2, 2), [0, 1]),
        ],
        ids=["ring", "grid", "fattree"],
    )
    def test_soft_distances_anneal_to_exact(self, topo, dests):
        prob = _problem(
            topo, dests, {(1, 0): 1.0, (2, 1): 1.0}
        )
        exact = _evaluator(prob).distances(prob.edge_metric)
        n = prob.n_nodes
        finite = exact[:n] < INF32
        assert finite.any()
        errs = []
        for tau in (1.0, 0.5, 0.1, 0.02):
            d = _soft_dist(prob, tau)
            errs.append(
                float(np.abs(d[:n][finite] - exact[:n][finite]).max())
            )
        # monotone-ish anneal: each temperature at least as close as the
        # hotter one, and the coldest within ECMP-multiplicity tolerance
        # (softmin undershoots min by exactly tau*log(#min paths))
        assert all(a >= b - 1e-3 for a, b in zip(errs, errs[1:])), errs
        assert errs[-1] < 0.5, errs
        # unreachable stays unreachable: soft never invents a path
        if (~finite).any():
            assert (d[:n][~finite] > soft.INF_F * 0.5).all()

    def test_soft_objective_tracks_exact_objective(self):
        prob = _problem(_diamond(), [3], {(0, 0): 8.0}, hi=8)
        ev = _evaluator(prob)
        import jax.numpy as jnp

        args = (
            jnp.asarray(prob.edge_src), jnp.asarray(prob.edge_dst),
            jnp.asarray(prob.edge_up), jnp.asarray(prob.node_overloaded),
            jnp.asarray(prob.dest_ids),
            jnp.asarray(prob.demand, dtype=jnp.float32),
            jnp.asarray(prob.capacity, dtype=jnp.float32),
        )
        for metric, expect in (
            (prob.edge_metric, 8.0),  # all demand on the cheap path
            (np.where(prob.edge_up, 2, 1).astype(np.int32), 4.0),  # split
        ):
            assert ev.evaluate(metric) == pytest.approx(expect)
            got = float(
                soft.soft_objective_value(
                    jnp.asarray(metric, dtype=jnp.float32), *args,
                    np.float32(0.02), np.float32(0.01),
                    n_sweeps=_SWEEPS, flow_sweeps=_SWEEPS,
                )
            )
            assert got == pytest.approx(expect, rel=0.05)


class TestGradients:
    """(b): finite, nonzero gradients on a seeded wan-shaped topology."""

    def test_grad_finite_nonzero_on_wan(self):
        import jax
        import jax.numpy as jnp

        topo = syn.wan(n_nodes=192, chords=2, seed=7)
        rng = np.random.RandomState(7)
        dests = np.array([3, 90], dtype=np.int32)
        dm = np.zeros((topo.node_capacity, 2), dtype=np.float32)
        dm[: topo.n_nodes] = rng.uniform(
            0.0, 1.0, size=(topo.n_nodes, 2)
        ).astype(np.float32)
        prob = TeProblem.from_topology(topo, dests, dm, metric_hi=64)

        def objective(metric_f):
            return soft.soft_objective_value(
                metric_f,
                jnp.asarray(prob.edge_src), jnp.asarray(prob.edge_dst),
                jnp.asarray(prob.edge_up),
                jnp.asarray(prob.node_overloaded),
                jnp.asarray(prob.dest_ids),
                jnp.asarray(prob.demand, dtype=jnp.float32),
                jnp.asarray(prob.capacity, dtype=jnp.float32),
                np.float32(0.5), np.float32(0.1),
                n_sweeps=32, flow_sweeps=32,
            )

        grad = np.asarray(
            jax.grad(objective)(
                jnp.asarray(prob.edge_metric, dtype=jnp.float32)
            )
        )
        assert np.isfinite(grad).all()
        assert np.abs(grad[: prob.n_edges]).max() > 0.0
        # padding edges are dead weight: no gradient may leak into them
        assert (grad[~prob.edge_up] == 0.0).all()


class TestOptimizeEndToEnd:
    """(c)+(d): exact improvement, baseline comparison, publication
    discipline."""

    def test_congested_diamond_improves_and_beats_hill_climb(self):
        prob = _problem(_diamond(), [3], {(0, 0): 8.0}, hi=8)
        ev = _evaluator(prob)
        opt = TeOptimizer()
        published = []
        res = opt.optimize(
            prob, steps=36, round_trips=3, n_sweeps=8, flow_sweeps=8,
            publish=lambda m, o: published.append((m, o)),
        )
        # strict exact improvement, via the exact gate
        assert res.objective_before == pytest.approx(8.0)
        assert res.objective_after < res.objective_before
        assert res.improved and res.accepted >= 1
        # the returned metrics REPRODUCE the claimed exact objective
        assert ev.evaluate(res.metrics) == pytest.approx(
            res.objective_after
        )
        # beats-or-matches the host hill-climb baseline
        _hm, hill_obj, _evals = hill_climb(prob, rounds=24, seed=3)
        assert res.objective_after <= hill_obj + 1e-12
        # exactly one publication, of the validated integer metrics
        assert len(published) == 1
        pm, pobj = published[0]
        assert pobj == pytest.approx(res.objective_after)
        assert pm.dtype == np.int32
        live = pm[: prob.n_edges][prob.edge_up[: prob.n_edges]]
        assert (live >= prob.metric_lo).all()
        assert (live <= prob.metric_hi).all()
        counters = opt.get_counters()
        assert counters["te.accepted"] == res.accepted
        assert counters["te.objective_after_milli"] < counters[
            "te.objective_before_milli"
        ]

    def test_always_reject_case_never_publishes(self):
        # a chain's utilization is metric-invariant: every candidate is
        # rejected by the exact gate and nothing publishes
        prob = _problem(_chain(), [2], {(0, 0): 5.0}, hi=8)
        opt = TeOptimizer()
        published = []
        res = opt.optimize(
            prob, steps=12, round_trips=2, n_sweeps=8, flow_sweeps=8,
            publish=lambda m, o: published.append((m, o)),
        )
        assert published == []
        assert not res.improved
        assert res.accepted == 0 and res.rejected == 2
        counters = opt.get_counters()
        assert counters["te.rejected"] == 2
        assert counters["te.accepted"] == 0
        # the result falls back to the INITIAL metrics: integer, in
        # bounds, and exactly re-validated as the baseline objective
        assert (res.metrics == np.where(
            prob.edge_up, prob.edge_metric, 1
        )).all()
        assert res.objective_after == pytest.approx(res.objective_before)
        assert _evaluator(prob).evaluate(res.metrics) == pytest.approx(
            res.objective_before
        )

    def test_counter_keys_pre_seeded(self):
        opt = TeOptimizer()
        counters = opt.get_counters()
        for key in TE_COUNTER_KEYS:
            assert counters[key] == 0
        pat = __import__("re").compile(
            r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$"
        )
        for key in TE_COUNTER_KEYS:
            assert pat.match(key), key


class TestEpochAbort:
    """(e): a mid-run flap aborts loudly, counters accounted."""

    def test_optimizer_aborts_on_epoch_flip(self):
        prob = _problem(_diamond(), [3], {(0, 0): 8.0}, hi=8)
        opt = TeOptimizer()
        calls = {"n": 0}

        def epoch_fn():
            calls["n"] += 1
            return 5 if calls["n"] <= 3 else 6  # the flap

        published = []
        with pytest.raises(EpochMismatchError) as ei:
            opt.optimize(
                prob, steps=12, round_trips=2, n_sweeps=8, flow_sweeps=8,
                epoch_fn=epoch_fn, expect_epoch=5,
                publish=lambda m, o: published.append(m),
            )
        assert ei.value.expected == 5 and ei.value.actual == 6
        assert published == []
        counters = opt.get_counters()
        assert counters["te.aborted"] == 1
        # the steps taken before the flap are accounted, none after
        assert 0 < counters["te.steps"] < 12

    def test_scheduler_never_retries_optimize_epoch_mismatch(self):
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from openr_tpu.decision.spf_solver import DeviceSpfBackend
        from openr_tpu.serving import EngineBatchBackend, QueryScheduler
        from openr_tpu.types import AdjacencyDatabase
        from test_spf_solver import adj, square

        ls = square()
        backend = EngineBatchBackend(
            {"0": ls},
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        sched = QueryScheduler(backend)

        def flap_on_execute(event, batch):
            if event == "execute_begin" and batch.op == "optimize_metrics":
                # the flap lands after coalescing pinned the epoch
                ls.update_adjacency_database(
                    AdjacencyDatabase(
                        this_node_name="2",
                        adjacencies=[adj("2", "1")],
                        is_overloaded=False,
                        node_label=102,
                        area="0",
                    )
                )

        sched.trace_hook = flap_on_execute
        sched.run()
        try:
            fut = sched.submit(
                "optimize_metrics",
                demand=(("1", "3", 4.0),),
                bounds=(1, 16),
                steps=8,
            )
            with pytest.raises(EpochMismatchError):
                fut.result(60)
            counters = sched.get_counters()
            # invalidation recorded, but NO retry: stale-tuned metrics
            # must never be recomputed against a silently re-pinned epoch
            assert counters["serving.invalidations"] == 1
            assert counters["serving.errors"] == 1
            assert counters["serving.replies"] == 0
        finally:
            sched.trace_hook = None
            sched.stop()


class TestServingSurface:
    """optimizeMetrics rides admission/coalescing like every query op."""

    def test_optimize_metrics_end_to_end_via_scheduler(self):
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from openr_tpu.decision.spf_solver import DeviceSpfBackend
        from openr_tpu.serving import EngineBatchBackend, QueryScheduler
        from test_spf_solver import square

        ls = square()
        backend = EngineBatchBackend(
            {"0": ls},
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        sched = QueryScheduler(backend)
        sched.run()
        try:
            fut = sched.submit(
                "optimize_metrics",
                demand=(("1", "3", 4.0), ("2", "3", 2.0)),
                bounds=(1, 16),
                steps=24,
            )
            res = fut.result(120)
            value = res.value
            assert value["objectiveAfter"] <= value["objectiveBefore"]
            assert res.epoch == int(ls.version)
            for u, v, m in value["proposedMetrics"]:
                assert isinstance(m, int)
                assert 1 <= m <= 16
                assert u in ls.node_names and v in ls.node_names
            # te.* counters accounted on the backend's optimizer
            counters = backend.te.get_counters()
            assert counters["te.runs"] == 1
            assert counters["te.steps"] == 24
        finally:
            sched.stop()


@pytest.mark.slow
class TestOptimizeSoak:
    """Long optimization soak: a seeded wan-shaped instance, full
    anneal, exact gate on every stage."""

    def test_wan_soak_improves_or_holds(self):
        topo = syn.wan(n_nodes=192, chords=2, seed=11)
        rng = np.random.RandomState(11)
        dests = np.array([0, 50, 120], dtype=np.int32)
        dm = np.zeros((topo.node_capacity, 3), dtype=np.float32)
        dm[: topo.n_nodes] = rng.uniform(
            0.0, 2.0, size=(topo.n_nodes, 3)
        ).astype(np.float32)
        prob = TeProblem.from_topology(topo, dests, dm, metric_hi=64)
        opt = TeOptimizer()
        res = opt.optimize(
            prob, steps=96, round_trips=6, n_sweeps=48, flow_sweeps=48
        )
        assert res.objective_after <= res.objective_before
        ev = _evaluator(prob)
        assert ev.evaluate(res.metrics) == pytest.approx(
            res.objective_after
        )
        live = res.metrics[: prob.n_edges][prob.edge_up[: prob.n_edges]]
        assert (live >= 1).all() and (live <= 64).all()
