"""Incremental CSR mirror refresh + DeviceSpfBackend laziness/caching
(VERDICT r1 weak #3: the device path must not rebuild the world per
topology version bump).

Covers: attribute-only in-place refresh (metric / overload / link-down),
shape-stable rebuild on edge-set change, capacity growth, lazy per-source
backend queries, prefetch batching, and result-cache invalidation."""

from __future__ import annotations

import numpy as np

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.decision.spf_solver import DeviceSpfBackend
from openr_tpu.utils.topo import grid_topology, random_topology

from test_link_state import adj, adj_db, build


def _square():
    return [
        adj_db("a", [adj("a", "b"), adj("a", "c")]),
        adj_db("b", [adj("b", "a"), adj("b", "d")]),
        adj_db("c", [adj("c", "a"), adj("c", "d")]),
        adj_db("d", [adj("d", "b"), adj("d", "c")]),
    ]


def _check_matches_oracle(csr: CsrTopology, ls: LinkState):
    results = csr.spf_from(ls.node_names)
    for src in ls.node_names:
        oracle = ls.run_spf(src)
        got = results[src]
        assert {k: v.metric for k, v in oracle.items()} == {
            k: v.metric for k, v in got.items()
        }, src
        for n in oracle:
            assert oracle[n].next_hops == got[n].next_hops, (src, n)


class TestCsrRefresh:
    def test_metric_change_updates_in_place(self):
        dbs = _square()
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        ell_before = csr.ell
        # bump one directed metric
        dbs[0].adjacencies[0].metric = 7  # a->b
        ls.update_adjacency_database(dbs[0])
        assert csr.refresh(ls) is True  # in place
        assert csr.ell is ell_before  # tables untouched
        assert csr.version == ls.version
        _check_matches_oracle(csr, ls)

    def test_overload_and_link_down_in_place(self):
        dbs = grid_topology(4)
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        shapes = (csr.node_capacity, csr.edge_capacity)
        victim = next(d for d in dbs if d.this_node_name == "node-1-1")
        victim.is_overloaded = True
        victim.adjacencies[0].is_overloaded = True  # one link overloaded
        ls.update_adjacency_database(victim)
        assert csr.refresh(ls) is True
        assert (csr.node_capacity, csr.edge_capacity) == shapes
        _check_matches_oracle(csr, ls)

    def test_edge_set_change_rewires_at_same_shapes(self):
        dbs = _square()
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        shapes = (csr.node_capacity, csr.edge_capacity)
        ell_before = csr.ell
        # remove link b<->d (edge-set change, still fits capacity):
        # handled by the slot freelist in place, not a rebuild
        dbs[1].adjacencies = [a for a in dbs[1].adjacencies if a.other_node_name != "d"]
        ls.update_adjacency_database(dbs[1])
        assert csr.refresh(ls) is True  # bounded rewire in place
        assert csr.ell is ell_before  # ELL tables patched, not rebuilt
        assert csr.rewire_seq == 1
        assert len(csr._free_slots) == 2  # both directed slots retired
        assert (csr.node_capacity, csr.edge_capacity) == shapes
        assert csr.version == ls.version
        _check_matches_oracle(csr, ls)

    def test_node_set_change_rebuilds(self):
        dbs = _square()
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        # a brand-new node is out of rewire scope -> full rebuild
        ls.update_adjacency_database(adj_db("e", [adj("e", "a")]))
        ls.update_adjacency_database(
            adj_db("a", [adj("a", "b"), adj("a", "c"), adj("a", "e")])
        )
        assert csr.refresh(ls) is False  # rebuilt
        assert csr.rewire_seq == 0
        _check_matches_oracle(csr, ls)

    def test_rewire_reuses_retired_slots(self):
        dbs = _square()
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        e_before = csr.n_edges
        # drop b<->d, then add a<->d: the two retired slots are reused
        dbs[1].adjacencies = [a for a in dbs[1].adjacencies if a.other_node_name != "d"]
        ls.update_adjacency_database(dbs[1])
        assert csr.refresh(ls) is True
        dbs2 = [
            adj_db("a", [adj("a", "b"), adj("a", "c"), adj("a", "d")]),
            adj_db("b", [adj("b", "a")]),
            adj_db("c", [adj("c", "a"), adj("c", "d")]),
            adj_db("d", [adj("d", "c"), adj("d", "a")]),
        ]
        for db in dbs2:
            ls.update_adjacency_database(db)
        assert csr.refresh(ls) is True
        assert csr.n_edges == e_before  # no tail growth
        assert csr._free_slots == []
        assert csr.rewire_seq == 2
        _check_matches_oracle(csr, ls)

    def test_node_growth_beyond_capacity(self):
        ls = build(_square())
        csr = CsrTopology.from_link_state(ls)
        n_cap = csr.node_capacity
        # add enough nodes to overflow the node capacity bucket
        extra = [
            adj_db(f"x{i}", [adj(f"x{i}", "a")]) for i in range(n_cap)
        ]
        extra_a = adj_db(
            "a",
            [adj("a", "b"), adj("a", "c")]
            + [adj("a", f"x{i}") for i in range(n_cap)],
        )
        for db in extra + [extra_a]:
            ls.update_adjacency_database(db)
        assert csr.refresh(ls) is False
        assert csr.node_capacity > n_cap
        _check_matches_oracle(csr, ls)

    def test_link_removed_and_readded_with_new_metric(self):
        """A link deleted then re-advertised with a different metric is a
        NEW Link object that compares equal by (node, iface) identity —
        refresh must not serve stale values from the retired object."""
        dbs = _square()
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        # remove a<->b entirely
        dbs[0].adjacencies = [a for a in dbs[0].adjacencies if a.other_node_name != "b"]
        dbs[1].adjacencies = [a for a in dbs[1].adjacencies if a.other_node_name != "a"]
        ls.update_adjacency_database(dbs[0])
        ls.update_adjacency_database(dbs[1])
        csr.refresh(ls)
        # re-add with metric 5
        dbs2 = _square()
        dbs2[0].adjacencies[0].metric = 5  # a->b
        dbs2[1].adjacencies[0].metric = 5  # b->a
        ls.update_adjacency_database(dbs2[0])
        ls.update_adjacency_database(dbs2[1])
        csr.refresh(ls)
        _check_matches_oracle(csr, ls)
        res = csr.spf_from(["a"])["a"]
        assert res["b"].metric == 3  # a-c-d-b beats the metric-5 direct link

    def test_noop_refresh(self):
        ls = build(_square())
        csr = CsrTopology.from_link_state(ls)
        v = csr.version
        assert csr.refresh(ls) is True
        assert csr.version == v


class TestDeviceSpfBackendV2:
    def test_lazy_and_cached(self):
        ls = build(random_topology(24, 30, seed=1))
        be = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        r1 = be.get_spf_result(ls, "n0")
        assert be._results[ls][1].keys() == {"n0"}  # only the asked source
        r2 = be.get_spf_result(ls, "n0")
        assert r1 is r2  # cache hit
        oracle = ls.run_spf("n0")
        assert {k: v.metric for k, v in oracle.items()} == {
            k: v.metric for k, v in r1.items()
        }

    def test_cache_invalidated_on_version_bump(self):
        dbs = _square()
        ls = build(dbs)
        be = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        r1 = be.get_spf_result(ls, "a")
        assert r1["d"].metric == 2
        dbs[0].adjacencies[0].metric = 9  # a->b
        dbs[0].adjacencies[1].metric = 9  # a->c
        ls.update_adjacency_database(dbs[0])
        r2 = be.get_spf_result(ls, "a")
        assert r2["d"].metric == 10
        # mirror was refreshed, not rebuilt from scratch
        assert be._mirrors[ls].version == ls.version

    def test_prefetch_batches(self):
        ls = build(random_topology(30, 40, seed=4))
        be = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        be.prefetch(ls, ls.node_names)
        cache = be._results[ls][1]
        assert set(cache.keys()) == set(ls.node_names)
        for src in ls.node_names[:5]:
            oracle = ls.run_spf(src)
            got = be.get_spf_result(ls, src)
            for n in oracle:
                assert oracle[n].next_hops == got[n].next_hops

    def test_small_topology_uses_host(self):
        ls = build(_square())
        be = DeviceSpfBackend(min_device_nodes=64, min_device_sources=1)
        r = be.get_spf_result(ls, "a")
        assert r["d"].metric == 2
        assert ls not in be._mirrors  # device path never touched
