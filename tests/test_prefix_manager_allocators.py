"""PrefixManager + allocator tests (modeled on
openr/prefix-manager/tests/PrefixManagerTest.cpp and
openr/allocators/tests/RangeAllocatorTest.cpp)."""

from __future__ import annotations

import time

import pytest

from openr_tpu.allocators import PrefixAllocator, RangeAllocator
from openr_tpu.decision.rib import DecisionRouteUpdate, RibUnicastEntry
from openr_tpu.kvstore import InProcessTransport, KvStore, KvStoreClientInternal
from openr_tpu.prefix_manager import OriginatedPrefixConfig, PrefixManager
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.serializer import loads
from openr_tpu.types import (
    NextHop,
    PeerSpec,
    PrefixDatabase,
    PrefixEntry,
    PrefixType,
    PrefixUpdateRequest,
    prefix_key,
)


def wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class Node:
    def __init__(self, name: str, fabric: InProcessTransport, areas=("0",)):
        self.name = name
        self.updates: ReplicateQueue = ReplicateQueue()
        self.syncs: ReplicateQueue = ReplicateQueue()
        self.peerq: ReplicateQueue = ReplicateQueue()
        self.kvstore = KvStore(
            name,
            self.updates,
            self.syncs,
            self.peerq.get_reader(),
            transport=fabric.bind(name),
            areas=areas,
        )
        fabric.register(name, self.kvstore)
        self.kvstore.run()
        self.evb = OpenrEventBase(name=f"evb-{name}")
        self.evb.run()
        self.client = KvStoreClientInternal(
            self.evb, name, self.kvstore, self.updates.get_reader(),
            check_persist_interval_s=60,
        )

    def stop(self):
        self.client.stop()
        for q in (self.updates, self.syncs, self.peerq):
            q.close()
        self.evb.stop()
        self.kvstore.stop()
        self.evb.wait_until_stopped(5)
        self.kvstore.wait_until_stopped(5)


@pytest.fixture
def node():
    fabric = InProcessTransport()
    n = Node("node1", fabric)
    yield n
    n.stop()


PFX = "::1:0/112"


class TestPrefixManager:
    def test_advertise_withdraw(self, node):
        pm = PrefixManager("node1", node.client)
        pm.run()
        try:
            pm.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix=PFX)]
            )
            key = prefix_key("node1", PFX, "0")
            raw = node.kvstore.get_key_vals("0", [key]).key_vals.get(key)
            assert raw is not None
            db = loads(raw.value, PrefixDatabase)
            assert db.prefix_entries[0].prefix == PFX
            assert not db.delete_prefix

            pm.withdraw_prefixes(PrefixType.LOOPBACK, [PFX])
            raw = node.kvstore.get_key_vals("0", [key]).key_vals.get(key)
            db = loads(raw.value, PrefixDatabase)
            assert db.delete_prefix  # tombstone
            assert pm.get_prefixes() == []
        finally:
            pm.stop()
            pm.wait_until_stopped(5)

    def test_best_type_wins_single_key(self, node):
        pm = PrefixManager("node1", node.client)
        pm.run()
        try:
            pm.advertise_prefixes(PrefixType.LOOPBACK, [PrefixEntry(prefix=PFX)])
            pm.advertise_prefixes(
                PrefixType.BGP, [PrefixEntry(prefix=PFX, type=PrefixType.BGP)]
            )
            key = prefix_key("node1", PFX, "0")
            raw = node.kvstore.get_key_vals("0", [key]).key_vals[key]
            db = loads(raw.value, PrefixDatabase)
            assert db.prefix_entries[0].type == PrefixType.BGP  # higher prio
            # withdrawing BGP falls back to LOOPBACK
            pm.withdraw_prefixes(PrefixType.BGP, [PFX])
            raw = node.kvstore.get_key_vals("0", [key]).key_vals[key]
            db = loads(raw.value, PrefixDatabase)
            assert db.prefix_entries[0].type == PrefixType.LOOPBACK
        finally:
            pm.stop()
            pm.wait_until_stopped(5)

    def test_sync_by_type(self, node):
        pm = PrefixManager("node1", node.client)
        pm.run()
        try:
            pm.advertise_prefixes(
                PrefixType.CONFIG,
                [PrefixEntry(prefix="::1:0/112"), PrefixEntry(prefix="::2:0/112")],
            )
            pm.sync_prefixes_by_type(
                PrefixType.CONFIG,
                [PrefixEntry(prefix="::2:0/112"), PrefixEntry(prefix="::3:0/112")],
            )
            prefixes = {e.prefix for e in pm.get_prefixes(PrefixType.CONFIG)}
            assert prefixes == {"::2:0/112", "::3:0/112"}
        finally:
            pm.stop()
            pm.wait_until_stopped(5)

    def test_queue_driven_requests(self, node):
        prefixq: ReplicateQueue = ReplicateQueue()
        pm = PrefixManager(
            "node1", node.client, prefix_updates=prefixq.get_reader()
        )
        pm.run()
        try:
            prefixq.push(
                PrefixUpdateRequest(
                    prefixes_to_add=[PrefixEntry(prefix=PFX)],
                    type=PrefixType.LOOPBACK,
                )
            )
            key = prefix_key("node1", PFX, "0")
            assert wait_for(
                lambda: node.kvstore.get_key_vals("0", [key]).key_vals.get(key)
                is not None
            )
        finally:
            prefixq.close()
            pm.stop()
            pm.wait_until_stopped(5)

    def test_redistribution_skips_traversed_areas(self):
        """A route whose area_stack already contains an area must not be
        re-advertised back into it (reference: PrefixManager.cpp:239-247
        areaStack.count(toArea) check) — prevents 3-area advertisement
        loops."""
        fabric = InProcessTransport()
        n = Node("node1", fabric, areas=("a", "b", "c"))
        routeq: ReplicateQueue = ReplicateQueue()
        pm = PrefixManager(
            "node1",
            n.client,
            route_updates=routeq.get_reader(),
            areas=("a", "b", "c"),
        )
        pm.run()
        try:
            pfx = "fd00::/64"
            u = DecisionRouteUpdate()
            u.add_route_to_update(
                RibUnicastEntry(
                    prefix=pfx,
                    nexthops=frozenset({NextHop(address="fe80::1")}),
                    best_prefix_entry=PrefixEntry(
                        prefix=pfx, area_stack=("c",)
                    ),
                    best_area="a",
                )
            )
            routeq.push(u)
            key_b = prefix_key("node1", pfx, "b")
            assert wait_for(
                lambda: n.kvstore.get_key_vals("b", [key_b]).key_vals.get(
                    key_b
                )
                is not None
            )
            # area "c" is already in the stack; area "a" is the source —
            # neither may receive the redistributed route
            for area in ("a", "c"):
                key = prefix_key("node1", pfx, area)
                raw = n.kvstore.get_key_vals(area, [key]).key_vals.get(key)
                assert raw is None, f"route leaked back into area {area}"
        finally:
            routeq.close()
            pm.stop()
            pm.wait_until_stopped(5)
            n.stop()

    def test_originated_prefix_aggregation(self, node):
        routeq: ReplicateQueue = ReplicateQueue()
        pm = PrefixManager(
            "node1",
            node.client,
            route_updates=routeq.get_reader(),
            originated_prefixes=[
                OriginatedPrefixConfig(
                    prefix="fc00::/16", minimum_supporting_routes=2
                )
            ],
        )
        pm.run()
        try:
            def push_routes(*prefixes, delete=()):
                u = DecisionRouteUpdate()
                for p in prefixes:
                    u.add_route_to_update(
                        RibUnicastEntry(
                            prefix=p,
                            nexthops=frozenset({NextHop(address="fe80::1")}),
                        )
                    )
                u.unicast_routes_to_delete.extend(delete)
                routeq.push(u)

            push_routes("fc00:1::/32")
            time.sleep(0.2)
            assert pm.get_originated_prefixes()["fc00::/16"] == (1, False)
            push_routes("fc00:2::/32")
            assert wait_for(
                lambda: pm.get_originated_prefixes()["fc00::/16"] == (2, True)
            )
            key = prefix_key("node1", "fc00::/16", "0")
            raw = node.kvstore.get_key_vals("0", [key]).key_vals.get(key)
            assert raw is not None
            # one supporting route withdrawn -> aggregate withdrawn
            push_routes(delete=["fc00:2::/32"])
            assert wait_for(
                lambda: pm.get_originated_prefixes()["fc00::/16"] == (1, False)
            )
        finally:
            routeq.close()
            pm.stop()
            pm.wait_until_stopped(5)


class TestRangeAllocator:
    def test_unique_election(self):
        """N nodes in a full KvStore mesh elect distinct values."""
        fabric = InProcessTransport()
        n_nodes = 4
        nodes = [Node(f"n{i}", fabric) for i in range(n_nodes)]
        try:
            # full-mesh peering
            for a in nodes:
                a.kvstore.add_peers(
                    "0",
                    {
                        b.name: PeerSpec(peer_addr=b.name)
                        for b in nodes
                        if b is not a
                    },
                )
            allocators = []
            results: dict[str, int | None] = {}
            for n in nodes:
                def cb(value, name=n.name):
                    results[name] = value

                alloc = RangeAllocator(
                    n.evb,
                    n.client,
                    "0",
                    "alloc:",
                    n.name,
                    cb,
                    (0, n_nodes - 1),
                    settle_time_s=0.15,
                )
                allocators.append(alloc)
            for alloc in allocators:
                alloc.start_allocation()
            assert wait_for(
                lambda: len([v for v in results.values() if v is not None])
                == n_nodes
                and len({v for v in results.values()}) == n_nodes,
                timeout=20,
            ), results
        finally:
            for alloc in allocators:
                alloc.stop()
            for n in nodes:
                n.stop()


class TestPrefixAllocator:
    def test_prefix_from_index(self, node, tmp_path):
        from openr_tpu.config_store import PersistentStore

        store = PersistentStore(str(tmp_path / "store.bin"))
        prefixq: ReplicateQueue = ReplicateQueue()
        reader = prefixq.get_reader()
        alloc = PrefixAllocator(
            node.evb,
            "node1",
            node.client,
            "fc00::/16",
            32,
            prefix_updates_queue=prefixq,
            config_store=store,
        )
        alloc.start()
        try:
            req = reader.get(timeout=10)
            assert req.type == PrefixType.PREFIX_ALLOCATOR
            got = req.prefixes_to_add[0].prefix
            assert got.endswith("/32") and got.startswith("fc00:")
            assert alloc.get_my_prefix() == got
            # index persisted for restart
            assert store.load("prefix-allocator-config") is not None
        finally:
            alloc.stop()
            prefixq.close()
            store.close()


class TestDaemonPrefixAllocation:
    def test_daemon_elects_and_advertises_allocation(self):
        """Prefix allocation through the FULL daemon wiring: the
        allocator must get the KvStore CLIENT (not the store), elect a
        subprefix, and the PrefixManager must advertise it (caught live:
        main.py passed the store and the allocator crashed on start)."""
        from openr_tpu.config import PrefixAllocationConf
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import PrefixType
        from tests.test_system import make_config, wait_for

        cfg = make_config("alloc-d0")
        cfg.prefix_allocation_config = PrefixAllocationConf(
            seed_prefix="2001:db8:60::/48", allocate_prefix_len=64
        )
        d = OpenrDaemon(
            cfg,
            io_provider=MockIoProvider().endpoint("alloc-d0"),
            spark_v6_addr="::1",
        )
        d.start()
        try:
            assert wait_for(
                lambda: d.prefix_allocator is not None
                and d.prefix_allocator.get_my_prefix() is not None,
                timeout=20,
            )
            prefix = d.prefix_allocator.get_my_prefix()
            assert prefix.startswith("2001:db8:60:")
            # advertised through PrefixManager under PREFIX_ALLOCATOR
            assert wait_for(
                lambda: any(
                    e.prefix == prefix
                    for e in d.prefix_manager.get_prefixes(
                        PrefixType.PREFIX_ALLOCATOR
                    )
                ),
                timeout=10,
            )
        finally:
            d.stop()
