"""OPENR_TSAN happens-before race detector: engine, seams and static
companion rules (openr_tpu/analysis/race.py, analysis/threads.py).

Dynamic tests run the seeded scenarios in
tests/analysis_fixtures/race_dynamic.py against the real detector —
armed here if the suite is not already running under OPENR_TSAN=1, in
which case the session detector is reused (and never disarmed
mid-suite).  Static tests assert exact (rule, line) pairs on the seeded
lock-order / guarded-by / shutdown-order fixtures, mirroring
tests/test_analysis.py.
"""

import importlib.util
import threading
from pathlib import Path

import pytest

from openr_tpu.analysis import race
from openr_tpu.analysis.core import AnalysisConfig, run_analysis
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import RWQueue

pytestmark = pytest.mark.analysis

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analysis_fixtures"

_DYN_PATH = FIXTURES / "race_dynamic.py"
_spec = importlib.util.spec_from_file_location("race_dynamic", _DYN_PATH)
race_dynamic = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(race_dynamic)

_DYN_LINES = _DYN_PATH.read_text().splitlines()


def _marked_line(marker: str) -> int:
    """1-based line of the unique trailing `# <marker>` comment."""
    hits = [
        i
        for i, line in enumerate(_DYN_LINES, 1)
        if line.rstrip().endswith("# " + marker)
    ]
    assert len(hits) == 1, f"marker {marker} not unique: {hits}"
    return hits[0]


def _site(stack: tuple) -> tuple:
    return stack[0][:2] if stack else ("<unknown>", 0)


def _state_findings(det: race.RaceDetector) -> list[race.RaceFinding]:
    return [f for f in det.drain() if f.cls_name == "State"]


@pytest.fixture
def det():
    """The active detector: the session one when the suite runs armed
    (OPENR_TSAN=1), otherwise armed fresh for this test and disarmed
    after.  Either way the fixture State class is tracked and findings
    are drained on both sides."""
    was_armed = race.TSAN is not None
    d = race.TSAN if was_armed else race.enable(tracked_paths=[])
    race.track_class(race_dynamic.State)
    d.drain()
    try:
        yield d
    finally:
        d.drain()
        if not was_armed:
            race.disable()


# ---------------------------------------------------------------------------
# Dynamic: seeded races are detected with exact sites
# ---------------------------------------------------------------------------


def test_bare_write_race_detected_with_exact_sites(det):
    race_dynamic.bare_write_race()
    findings = _state_findings(det)
    assert len(findings) == 1
    (f,) = findings
    assert f.kind == "write-write"
    assert f.attr == "value"
    assert sorted((_site(f.prior_stack), _site(f.stack))) == sorted(
        (
            (str(_DYN_PATH), _marked_line("RACE-A")),
            (str(_DYN_PATH), _marked_line("RACE-B")),
        )
    )
    assert {f.prior_thread, f.thread} == {"race-a", "race-b"}


def test_bare_read_race_detected(det):
    race_dynamic.bare_read_race()
    findings = _state_findings(det)
    assert len(findings) == 1
    (f,) = findings
    assert f.kind in ("write-read", "read-write")
    assert f.attr == "value"
    assert sorted((_site(f.prior_stack), _site(f.stack))) == sorted(
        (
            (str(_DYN_PATH), _marked_line("RACE-READ")),
            (str(_DYN_PATH), _marked_line("RACE-WRITE")),
        )
    )


def test_same_site_pair_dedups_across_objects(det):
    race_dynamic.dedup_double_race()
    findings = _state_findings(det)
    assert len(findings) == 1
    (f,) = findings
    assert _site(f.prior_stack) == _site(f.stack) == (
        str(_DYN_PATH),
        _marked_line("RACE-DEDUP"),
    )


def test_missing_token_races(det):
    race_dynamic.token_missing_race()
    findings = _state_findings(det)
    assert len(findings) == 1
    (f,) = findings
    assert f.kind == "write-write"
    assert sorted((_site(f.prior_stack), _site(f.stack))) == sorted(
        (
            (str(_DYN_PATH), _marked_line("RACE-TOKEN-A")),
            (str(_DYN_PATH), _marked_line("RACE-TOKEN-B")),
        )
    )


# ---------------------------------------------------------------------------
# Dynamic: happens-before edges silence the same shapes
# ---------------------------------------------------------------------------


def test_queue_handoff_is_clean(det):
    race_dynamic.queue_handoff_clean()
    assert _state_findings(det) == []


def test_transitive_hb_through_two_queue_hops(det):
    race_dynamic.two_hop_relay_clean()
    assert _state_findings(det) == []


def test_lock_release_acquire_edges(det):
    state = race_dynamic.lock_protected_clean()
    assert _state_findings(det) == []
    assert state.value == 100  # the lock actually locked


def test_publish_acquire_token_orders_writes(det):
    race_dynamic.token_ordered_clean(det)
    assert _state_findings(det) == []


# ---------------------------------------------------------------------------
# Engine units
# ---------------------------------------------------------------------------


def test_leq_componentwise():
    assert race._leq({}, {})
    assert race._leq({}, {1: 1})
    assert not race._leq({1: 1}, {})
    assert race._leq({1: 1}, {1: 2, 2: 5})
    assert not race._leq({1: 2, 2: 1}, {1: 2})


def _acc(tid, site_line, name="t"):
    return race._Access(
        tid, {tid: 1}, name, (("f.py", site_line, "fn"),)
    )


def test_report_dedup_is_order_insensitive():
    det = race.RaceDetector()
    a, b = _acc(1, 10), _acc(2, 20)
    det._report("write-write", ("State", "object"), "value", a, b)
    det._report("write-write", ("State", "object"), "value", b, a)
    assert len(det.findings) == 1
    # the same unordered read/write pair spelled both ways is one finding
    det._report("read-write", ("State", "object"), "other", a, b)
    det._report("write-read", ("State", "object"), "other", b, a)
    assert len(det.findings) == 2


def test_suppression_requires_rationale():
    det = race.RaceDetector()
    with pytest.raises(ValueError):
        det.suppress("State", "value", "  ")
    det.suppress("State", "value", "benign: monotonic latch")
    det._report("write-write", ("State", "object"), "value", _acc(1, 1), _acc(2, 2))
    assert det.findings == []
    assert [(f.cls_name, f.attr, why) for f, why in det.suppressed] == [
        ("State", "value", "benign: monotonic latch")
    ]


def test_suppressions_match_through_the_mro():
    det = race.RaceDetector()
    det.suppress("Base", "value", "benign on the base class")
    det._report(
        "write-write", ("Derived", "Base", "object"), "value", _acc(1, 1), _acc(2, 2)
    )
    assert det.findings == []
    assert len(det.suppressed) == 1


def test_default_suppressions_all_carry_rationale():
    assert race.DEFAULT_RUNTIME_SUPPRESSIONS
    for (cls, attr), why in race.DEFAULT_RUNTIME_SUPPRESSIONS.items():
        assert why.strip(), f"({cls}, {attr}) has no rationale"


def test_format_names_both_threads_and_stacks():
    det = race.RaceDetector()
    det._report(
        "write-write",
        ("State", "object"),
        "value",
        _acc(1, 10, "thread-a"),
        _acc(2, 20, "thread-b"),
    )
    text = race.format_findings(det.drain())
    assert "1 unsuppressed race finding" in text
    assert "write-write race on State.value" in text
    assert "'thread-a'" in text and "'thread-b'" in text
    assert "f.py:10" in text and "f.py:20" in text


# ---------------------------------------------------------------------------
# Arming is zero-cost when off, reversible when on
# ---------------------------------------------------------------------------

_ARMED_SESSION = race.TSAN is not None


@pytest.mark.skipif(_ARMED_SESSION, reason="suite is running under OPENR_TSAN=1")
def test_unarmed_runtime_is_untouched():
    assert race.TSAN is None
    assert threading.Lock is race._REAL_LOCK
    assert threading.RLock is race._REAL_RLOCK
    assert "__setattr__" not in OpenrEventBase.__dict__
    q = RWQueue()
    assert q.push(1)
    assert q._tsan_tokens is None  # push never allocated the token deque
    assert q.get(timeout=1) == 1


@pytest.mark.skipif(_ARMED_SESSION, reason="suite is running under OPENR_TSAN=1")
def test_enable_disable_round_trips():
    race.enable(tracked_paths=[])
    try:
        assert race.TSAN is not None
        assert threading.Lock is race.TsanLock
        assert threading.RLock is race.TsanRLock
    finally:
        race.disable()
    assert race.TSAN is None
    assert threading.Lock is race._REAL_LOCK
    assert threading.RLock is race._REAL_RLOCK


# ---------------------------------------------------------------------------
# Static companion rules: seeded fixtures, exact (rule, line) pairs
# ---------------------------------------------------------------------------


def _fixture_findings(*names):
    config = AnalysisConfig(
        jit_paths=["tests/analysis_fixtures"],
        counter_extra_prefixes=["kvstore", "fib", "queue"],
    )
    targets = [FIXTURES / n for n in names]
    return run_analysis(targets, config, REPO_ROOT)


def _pairs(reporter):
    return sorted((f.rule, f.line) for f in reporter.findings)


def test_lock_order_and_guarded_by_fixture():
    rep = _fixture_findings("race_lockorder.py")
    assert _pairs(rep) == [
        ("guarded-by", 57),
        ("lock-order", 19),
        ("lock-order", 24),
    ]
    # each inversion cites the site taking the reverse order
    by_line = {f.line: f.message for f in rep.findings if f.rule == "lock-order"}
    assert "race_lockorder.py:24" in by_line[19]
    assert "race_lockorder.py:19" in by_line[24]
    # the quiesced reset carries a suppression marker
    assert [(s.rule, s.line) for s in rep.suppressed] == [("guarded-by", 61)]


def test_shutdown_order_fixture():
    rep = _fixture_findings("shutdown_order.py")
    assert _pairs(rep) == [
        ("thread-shutdown-order", 21),
        ("thread-shutdown-order", 23),
    ]
    messages = sorted(f.message for f in rep.findings)
    assert "never closed" in messages[1]
    assert "runs before `self.updates` closes" in messages[0]
    assert rep.suppressed == []
