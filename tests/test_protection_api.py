"""Operator surface over the protection kernels (decision/protection_api):
name-level SRLG what-if and TI-LFA reports, plus the ctrl/breeze plumbing.
Semantics checked on hand-analyzable topologies."""

from __future__ import annotations

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.protection_api import ti_lfa, what_if
from openr_tpu.utils.topo import grid_topology, ring_topology


def build_ls(dbs) -> LinkState:
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return ls


class TestWhatIf:
    def test_ring_single_link_degrades_but_keeps_reachability(self):
        # 4-ring: failing one link degrades pairs (longer way around) but
        # disconnects nothing
        ls = build_ls(ring_topology(4))
        nodes = sorted(ls.node_names)
        a, b = nodes[0], nodes[1]
        rows = what_if(ls, [[(a, b)]])
        assert len(rows) == 1
        assert rows[0]["newly_unreachable_pairs"] == 0
        assert rows[0]["degraded_pairs"] > 0
        assert rows[0]["links"] == [[a, b]]
        assert rows[0]["unknown_links"] == []

    def test_srlg_cut_disconnects(self):
        # failing BOTH links of a 4-ring node cuts it off: 2*(n-1) pairs
        # (3 sources can't reach it, it can't reach 3)
        ls = build_ls(ring_topology(4))
        # ring nodes are r0..r3; r0 connects to r1 and r3
        rows = what_if(
            ls, [[("r0", "r1"), ("r0", "r3")]]
        )
        assert rows[0]["newly_unreachable_pairs"] == 6
        assert rows[0]["unknown_links"] == []

    def test_multiple_scenarios_and_unknown_link(self):
        ls = build_ls(ring_topology(4))
        rows = what_if(
            ls,
            [
                [("r0", "r1")],
                [("r0", "nope")],
            ],
        )
        assert len(rows) == 2
        assert rows[0]["degraded_pairs"] > 0
        # unknown link -> no-op scenario
        assert rows[1]["unknown_links"] == [["r0", "nope"]]
        assert rows[1]["newly_unreachable_pairs"] == 0
        assert rows[1]["degraded_pairs"] == 0

    def test_sources_filter(self):
        ls = build_ls(ring_topology(4))
        all_rows = what_if(ls, [[("r0", "r1")]])
        one_rows = what_if(
            ls, [[("r0", "r1")]], sources=["r0"]
        )
        assert (
            0
            < one_rows[0]["degraded_pairs"]
            < all_rows[0]["degraded_pairs"]
        )


class TestTiLfa:
    def test_ring_backups_go_the_other_way(self):
        ls = build_ls(ring_topology(4))
        report = ti_lfa(ls, "r0")
        assert report["node"] == "r0"
        adjs = {a["neighbor"]: a for a in report["adjacencies"]}
        assert set(adjs) == {"r1", "r3"}
        # with (r0,r1) failed, every destination is reached via r3
        via1 = adjs["r1"]
        assert via1["unprotected_destinations"] == []
        assert via1["protected_destinations"] == 3
        assert via1["backup_first_hops"]["r1"] == ["r3"]
        assert via1["backup_first_hops"]["r2"] == ["r3"]

    def test_grid_corner_has_two_adjacencies(self):
        ls = build_ls(grid_topology(3))
        report = ti_lfa(ls, "node-0-0")
        assert len(report["adjacencies"]) == 2
        for adj in report["adjacencies"]:
            # 3x3 grid survives any single link failure
            assert adj["unprotected_destinations"] == []
            assert adj["protected_destinations"] == 8
            # backup first hop avoids the failed neighbor for the
            # destination directly behind the failed link
            failed = adj["neighbor"]
            assert failed not in adj["backup_first_hops"][failed]

    def test_unknown_node(self):
        ls = build_ls(ring_topology(3))
        assert "error" in ti_lfa(ls, "nope")
