"""PersistentStore tests (modeled on openr/config-store/tests/)."""

from __future__ import annotations

import os

from openr_tpu.config_store import PersistentStore
from openr_tpu.config_store.persistent_store import (
    ActionType,
    PersistentObject,
    TLV_MARKER,
    decode_persistent_objects,
    encode_persistent_object,
)


class TestCodec:
    def test_roundtrip(self):
        objs = [
            PersistentObject(ActionType.ADD, "k1", b"\x00\x01binary"),
            PersistentObject(ActionType.DEL, "k1"),
            PersistentObject(ActionType.ADD, "empty", b""),
        ]
        blob = b"".join(encode_persistent_object(o) for o in objs)
        assert decode_persistent_objects(blob) == objs

    def test_truncation_tolerated(self):
        objs = [
            PersistentObject(ActionType.ADD, "k1", b"data1"),
            PersistentObject(ActionType.ADD, "k2", b"data2"),
        ]
        blob = b"".join(encode_persistent_object(o) for o in objs)
        got = decode_persistent_objects(blob[:-3], tolerate_truncation=True)
        assert got == objs[:1]


class TestPersistentStore:
    def test_store_load_erase(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("k1", b"v1")
        store.store("k2", b"v2")
        assert store.load("k1") == b"v1"
        assert store.erase("k1") is True
        assert store.erase("k1") is False
        assert store.load("k1") is None
        store.close()

    def test_survives_restart(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("drain", b"true")
        store.store("gone", b"x")
        store.erase("gone")
        store.store("prefix-index", b"42")
        store.close()

        store2 = PersistentStore(path)
        assert store2.load("drain") == b"true"
        assert store2.load("prefix-index") == b"42"
        assert store2.load("gone") is None
        assert store2.keys() == ["drain", "prefix-index"]
        store2.close()

    def test_full_rewrite_compacts(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        for i in range(50):
            store.store("churn", f"v{i}".encode())
        size_before = os.path.getsize(path)
        assert store.save_database_to_disk()
        assert os.path.getsize(path) < size_before
        store.close()
        store2 = PersistentStore(path)
        assert store2.load("churn") == b"v49"
        store2.close()

    def test_torn_append_recovery(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path)
        store.store("good", b"ok")
        store.close()
        with open(path, "ab") as f:
            f.write(b"\x01\xff\xff")  # torn partial record
        store2 = PersistentStore(path)
        assert store2.load("good") == b"ok"
        store2.close()

    def test_dryrun_writes_nothing(self, tmp_path):
        path = str(tmp_path / "store.bin")
        store = PersistentStore(path, dryrun=True)
        store.store("k", b"v")
        assert store.load("k") == b"v"
        store.close()
        assert not os.path.exists(path)
