"""LinkState graph semantics tests (modeled on the reference's
openr/decision/tests/LinkStateTest.cpp: SPF, ECMP ties, overloads, holds,
k-shortest paths, adjacency DB diffs)."""

import pytest

from openr_tpu.decision import HoldableValue, LinkState
from openr_tpu.decision.link_state import path_a_in_path_b
from openr_tpu.types import Adjacency, AdjacencyDatabase


def adj(me, other, metric=1, overloaded=False, adj_label=0):
    return Adjacency(
        other_node_name=other,
        if_name=f"if_{me}_{other}",
        other_if_name=f"if_{other}_{me}",
        metric=metric,
        is_overloaded=overloaded,
        adj_label=adj_label,
    )


def adj_db(node, adjs, overloaded=False, node_label=0, area="0"):
    return AdjacencyDatabase(
        this_node_name=node,
        adjacencies=adjs,
        is_overloaded=overloaded,
        node_label=node_label,
        area=area,
    )


def build(dbs, area="0"):
    ls = LinkState(area)
    for db in dbs:
        ls.update_adjacency_database(db)
    return ls


def two_node():
    return [
        adj_db("a", [adj("a", "b", metric=5)]),
        adj_db("b", [adj("b", "a", metric=7)]),
    ]


class TestHoldableValue:
    def test_basic(self):
        hv = HoldableValue(10)
        assert hv.value == 10
        assert hv.update_value(5, hold_up_ttl=2, hold_down_ttl=4)  is False
        # bringing-up change (5 < 10) held for 2 ticks
        assert hv.value == 10 and hv.has_hold()
        assert hv.decrement_ttl() is False
        assert hv.decrement_ttl() is True
        assert hv.value == 5 and not hv.has_hold()

    def test_hold_down(self):
        hv = HoldableValue(5)
        hv.update_value(10, hold_up_ttl=2, hold_down_ttl=3)
        assert hv.value == 5
        for expect in (False, False, True):
            assert hv.decrement_ttl() is expect
        assert hv.value == 10

    def test_update_while_held_falls_back_fast(self):
        hv = HoldableValue(10)
        hv.update_value(5, 2, 2)
        assert hv.has_hold()
        # new value while held: hold cancelled, fast update
        assert hv.update_value(7, 2, 2) is True
        assert hv.value == 7 and not hv.has_hold()

    def test_bool_hold_false_value(self):
        """A held value of False must still count as a hold."""
        hv = HoldableValue(False)
        hv.update_value(True, 2, 2)  # overloading is "down" -> hold_down
        assert hv.value is False and hv.has_hold()
        hv.decrement_ttl()
        assert hv.decrement_ttl()
        assert hv.value is True

    def test_no_ttl_no_hold(self):
        hv = HoldableValue(10)
        assert hv.update_value(20, 0, 0) is True
        assert hv.value == 20

    def test_same_value_noop(self):
        hv = HoldableValue(10)
        assert hv.update_value(10, 5, 5) is False
        assert not hv.has_hold()


class TestLinkStateGraph:
    def test_bidirectional_only(self):
        ls = LinkState("0")
        c = ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
        assert not c.topology_changed  # no reverse adjacency yet
        assert ls.num_links() == 0
        c = ls.update_adjacency_database(adj_db("b", [adj("b", "a")]))
        assert c.topology_changed
        assert ls.num_links() == 1
        assert ls.num_nodes() == 2

    def test_mismatched_ifaces_no_link(self):
        ls = LinkState("0")
        a = Adjacency("b", "if1", other_if_name="ifX")
        b = Adjacency("a", "if2", other_if_name="if1")
        ls.update_adjacency_database(adj_db("a", [a]))
        c = ls.update_adjacency_database(adj_db("b", [b]))
        assert not c.topology_changed
        assert ls.num_links() == 0

    def test_spf_two_node_asymmetric(self):
        ls = build(two_node())
        res_a = ls.get_spf_result("a")
        assert res_a["a"].metric == 0
        assert res_a["b"].metric == 5
        assert res_a["b"].next_hops == {"b"}
        res_b = ls.get_spf_result("b")
        assert res_b["a"].metric == 7

    def test_spf_unweighted(self):
        ls = build(two_node())
        assert ls.get_hops_from_a_to_b("a", "b") == 1
        assert ls.get_metric_from_a_to_b("a", "b") == 5
        assert ls.get_metric_from_a_to_b("a", "a") == 0

    def test_metric_change_topology(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(adj_db("a", [adj("a", "b", metric=9)]))
        assert c.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") == 9

    def test_no_change_is_noop(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(adj_db("a", [adj("a", "b", metric=5)]))
        assert c == type(c)()

    def test_link_down(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(adj_db("a", []))
        assert c.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") is None

    def test_delete_adjacency_database(self):
        ls = build(two_node())
        c = ls.delete_adjacency_database("b")
        assert c.topology_changed
        assert ls.num_links() == 0
        assert not ls.delete_adjacency_database("nope").topology_changed

    def test_node_label_change(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(
            adj_db("a", [adj("a", "b", metric=5)], node_label=42)
        )
        assert c.node_label_changed and not c.topology_changed

    def test_adj_label_change_is_attribute_change(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(
            adj_db("a", [adj("a", "b", metric=5, adj_label=999)])
        )
        assert c.link_attributes_changed and not c.topology_changed

    def test_ecmp_square(self):
        #   a --- b
        #   |     |      all metric 1; a->d has two equal-cost paths
        #   c --- d
        ls = build(
            [
                adj_db("a", [adj("a", "b"), adj("a", "c")]),
                adj_db("b", [adj("b", "a"), adj("b", "d")]),
                adj_db("c", [adj("c", "a"), adj("c", "d")]),
                adj_db("d", [adj("d", "b"), adj("d", "c")]),
            ]
        )
        res = ls.get_spf_result("a")
        assert res["d"].metric == 2
        assert res["d"].next_hops == {"b", "c"}
        assert len(res["d"].path_links) == 2

    def test_node_overload_no_transit(self):
        # a - b - c chain; overload b => c unreachable from a
        dbs = [
            adj_db("a", [adj("a", "b")]),
            adj_db("b", [adj("b", "a"), adj("b", "c")]),
            adj_db("c", [adj("c", "b")]),
        ]
        ls = build(dbs)
        assert ls.get_metric_from_a_to_b("a", "c") == 2
        ls.update_adjacency_database(
            adj_db("b", [adj("b", "a"), adj("b", "c")], overloaded=True)
        )
        assert ls.is_node_overloaded("b")
        # b itself still reachable, c is not
        assert ls.get_metric_from_a_to_b("a", "b") == 1
        assert ls.get_metric_from_a_to_b("a", "c") is None
        # overloaded source can still originate traffic
        assert ls.get_metric_from_a_to_b("b", "c") == 1

    def test_link_overload_takes_link_down(self):
        ls = build(two_node())
        c = ls.update_adjacency_database(
            adj_db("a", [adj("a", "b", metric=5, overloaded=True)])
        )
        assert c.topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") is None

    def test_holds_on_new_link(self):
        ls = LinkState("0")
        ls.update_adjacency_database(adj_db("a", [adj("a", "b")]))
        c = ls.update_adjacency_database(
            adj_db("b", [adj("b", "a")]), hold_up_ttl=2, hold_down_ttl=4
        )
        # link exists but held down (not yet up) -> no topology change yet
        assert not c.topology_changed
        assert ls.has_holds()
        assert ls.get_metric_from_a_to_b("a", "b") is None
        assert not ls.decrement_holds().topology_changed
        assert ls.decrement_holds().topology_changed  # ttl 2 expired
        assert ls.get_metric_from_a_to_b("a", "b") == 1

    def test_metric_hold(self):
        ls = build(two_node())
        # bringing-up change (lower metric) held for hold_up ticks
        c = ls.update_adjacency_database(
            adj_db("a", [adj("a", "b", metric=1)]), hold_up_ttl=2, hold_down_ttl=4
        )
        assert not c.topology_changed  # change is held
        assert ls.get_metric_from_a_to_b("a", "b") == 5
        ls.decrement_holds()
        assert ls.decrement_holds().topology_changed
        assert ls.get_metric_from_a_to_b("a", "b") == 1

    def test_memoization_and_invalidation(self):
        ls = build(two_node())
        r1 = ls.get_spf_result("a")
        assert ls.get_spf_result("a") is r1
        v = ls.version
        ls.update_adjacency_database(adj_db("a", [adj("a", "b", metric=6)]))
        assert ls.version != v
        assert ls.get_spf_result("a") is not r1


class TestKthPaths:
    def diamond(self):
        #     b
        #   /   \        a-b-d cost 2, a-c-d cost 2 (disjoint)
        #  a     d       plus direct a-d cost 5
        #   \   /
        #     c
        return build(
            [
                adj_db("a", [adj("a", "b"), adj("a", "c"), adj("a", "d", metric=5)]),
                adj_db("b", [adj("b", "a"), adj("b", "d")]),
                adj_db("c", [adj("c", "a"), adj("c", "d")]),
                adj_db("d", [adj("d", "b"), adj("d", "c"), adj("d", "a", metric=5)]),
            ]
        )

    def test_k1_gets_all_disjoint_shortest(self):
        ls = self.diamond()
        paths = ls.get_kth_paths("a", "d", 1)
        assert len(paths) == 2
        assert all(len(p) == 2 for p in paths)

    def test_k2_uses_remaining_links(self):
        ls = self.diamond()
        paths2 = ls.get_kth_paths("a", "d", 2)
        assert len(paths2) == 1
        assert len(paths2[0]) == 1  # the direct a-d link
        assert paths2[0][0].metric_from_node("a") == 5

    def test_k3_empty(self):
        ls = self.diamond()
        assert ls.get_kth_paths("a", "d", 3) == []

    def test_src_equals_dest(self):
        ls = self.diamond()
        assert ls.get_kth_paths("a", "a", 1) == []

    def test_path_a_in_path_b(self):
        ls = self.diamond()
        p1, p2 = ls.get_kth_paths("a", "d", 1)
        assert path_a_in_path_b(p1, p1)
        assert not path_a_in_path_b(p1, p2)
        assert path_a_in_path_b([p1[0]], p1)
        assert not path_a_in_path_b(p1, [p1[0]])
