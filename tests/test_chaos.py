"""Chaos suite: seeded fault injection + graceful degradation.

Three layers, mirroring the chaos package:

- injector unit tests proving every fault schedule replays bit-for-bit
  from its seed (the foundation the scenario replay assertion rests on);
- degradation-ladder tests on live daemons: device SPF dispatch failure
  falls back to the host oracle, a rebuild failure falls back to a full
  host-only recompute, and in both cases the route publication stream
  is never dropped or duplicated;
- the scripted multi-node scenario (link flap + lossy links + KvStore
  partition/heal + Fib agent crashes + a daemon restart through Spark
  GR) asserting bit-exact convergence to host-oracle routes after heal,
  twice from the same seed with matching event logs.

A failing randomized soak logs its seed (OPENR_CHAOS_SEED) so the exact
run replays locally.
"""

from __future__ import annotations

import os
import random
import time

import pytest

from openr_tpu.chaos import (
    ChaosEventLog,
    ChaosIoProvider,
    ChaosScenario,
    ChaosSpfBackend,
    FibChaosPlan,
    KvChaosInjector,
    fib_unicast_routes,
    oracle_route_dbs,
)
from openr_tpu.chaos.chaos import (
    SCENARIO_STREAM,
    wait_timeout_scale,
    wait_until,
)
from openr_tpu.chaos.scenario import hold_converged
from openr_tpu.ctrl import OpenrCtrlHandler
from openr_tpu.decision.spf_solver import HostSpfBackend
from openr_tpu.fib import MockFibAgent
from openr_tpu.kvstore import InProcessTransport
from openr_tpu.main import OpenrDaemon
from openr_tpu.monitor.watchdog import Watchdog
from openr_tpu.runtime.queue import ReplicateQueue, RWQueue, queue_counters
from openr_tpu.spark import Spark, SparkConfig, SparkNeighState
from openr_tpu.types import (
    InterfaceDatabase,
    InterfaceInfo,
    LinkEvent,
    NeighborEventType,
    PrefixEntry,
    PrefixType,
    normalize_prefix,
)

from test_system import make_config

pytestmark = pytest.mark.chaos

FIB_CLIENT = 786


# -- seeded schedules replay bit-for-bit -------------------------------------


class TestDeterministicSchedules:
    def _link_plans(self, seed: int, n: int = 300):
        fabric = ChaosIoProvider(seed=seed)
        fabric.set_link_profile(
            "a", "b", drop=0.3, dup=0.2, reorder=0.2, jitter_s=0.01
        )
        plans = [fabric._plan_delivery("a", "b") for _ in range(n)]
        return plans, fabric.log.streams()

    def test_link_schedule_replays_from_seed(self):
        plans1, log1 = self._link_plans(7)
        plans2, log2 = self._link_plans(7)
        assert plans1 == plans2
        assert log1 == log2
        plans3, _ = self._link_plans(8)
        assert plans1 != plans3

    def test_unprofiled_traffic_does_not_shift_the_schedule(self):
        # packets sent before the profile attaches (timing-dependent in
        # count) must not consume seeded draws — the k-th PROFILED
        # packet's fate is what replays
        fabric1 = ChaosIoProvider(seed=11)
        fabric2 = ChaosIoProvider(seed=11)
        for _ in range(17):  # pre-profile traffic only on fabric1
            fabric1._plan_delivery("a", "b")
        for fabric in (fabric1, fabric2):
            fabric.set_link_profile("a", "b", drop=0.5)
        plans1 = [fabric1._plan_delivery("a", "b") for _ in range(100)]
        plans2 = [fabric2._plan_delivery("a", "b") for _ in range(100)]
        assert plans1 == plans2

    def test_partition_blocks_without_consuming_draws(self):
        fabric1 = ChaosIoProvider(seed=3)
        fabric2 = ChaosIoProvider(seed=3)
        for fabric in (fabric1, fabric2):
            fabric.set_link_profile("a", "b", drop=0.5)
        fabric1.set_partitioned("a", "b", True)
        assert [fabric1._plan_delivery("a", "b") for _ in range(9)] == [[]] * 9
        fabric1.set_partitioned("a", "b", False)
        plans1 = [fabric1._plan_delivery("a", "b") for _ in range(50)]
        plans2 = [fabric2._plan_delivery("a", "b") for _ in range(50)]
        assert plans1 == plans2

    def test_fib_plan_replays_from_seed(self):
        def verdicts(seed):
            plan = FibChaosPlan(seed, fail_prob=0.2, restart_prob=0.1)
            return [plan.on_call("sync_fib") for _ in range(200)]

        assert verdicts(5) == verdicts(5)
        assert verdicts(5) != verdicts(6)

    def test_kv_injector_replays_from_seed(self):
        def outcomes(seed):
            injector = KvChaosInjector(seed, full_dump_fail=0.4)
            out = []
            for _ in range(100):
                try:
                    injector.check("full_dump", "x", "y")
                    out.append("ok")
                except Exception:
                    out.append("fail")
            return out

        assert outcomes(9) == outcomes(9)
        assert "fail" in outcomes(9)
        assert outcomes(9) != outcomes(10)

    def test_event_log_matching_semantics(self):
        a, b = ChaosEventLog(), ChaosEventLog()
        for log in (a, b):
            log.append(SCENARIO_STREAM, "step-1")
            log.append("link:x->y", "0:drop")
        # one run observed more traffic: common prefix still matches
        a.append("link:x->y", "4:drop")
        assert a.matches(b) and b.matches(a)
        # scenario streams must be identical, not prefix-equal
        a.append(SCENARIO_STREAM, "step-2")
        assert not a.matches(b)
        b.append(SCENARIO_STREAM, "step-2")
        assert a.matches(b)
        # a diverging fault decision breaks the match
        b.append("link:x->y", "4:reorder")
        assert not a.matches(b)


# -- fault hooks on the agent/transport seams --------------------------------


class TestFibAgentChaosHook:
    def test_injected_failures_and_restarts(self):
        agent = MockFibAgent()
        agent.chaos = FibChaosPlan(1, fail_prob=1.0, fail_ops={"sync_fib"})
        agent.add_unicast_routes(FIB_CLIENT, [])  # unlisted op: untouched
        with pytest.raises(RuntimeError, match="injected"):
            agent.sync_fib(FIB_CLIENT, [])
        agent.chaos.disarm()
        agent.sync_fib(FIB_CLIENT, [])

        agent2 = MockFibAgent()
        before = agent2.alive_since()
        agent2.chaos = FibChaosPlan(2, restart_prob=1.0)
        with pytest.raises(RuntimeError, match="restarted"):
            agent2.sync_fib(FIB_CLIENT, [])
        agent2.chaos = None
        assert agent2.alive_since() > before  # restart detected by keepalive
        assert agent2.unicast == {}  # tables wiped by the restart


# -- watchdog: every stall reported, memory always checked -------------------


class _StubEvb:
    def __init__(self, name: str, ts: float, running: bool = True) -> None:
        self.name = name
        self.is_running = running
        self._ts = ts

    def get_timestamp(self) -> float:
        return self._ts


class TestWatchdog:
    def test_reports_every_stall_and_always_checks_memory(self):
        fired: list[str] = []
        wd = Watchdog(
            thread_timeout_s=10.0, max_memory_bytes=1, on_crash=fired.append
        )
        now = time.monotonic()
        wd.add_evb(_StubEvb("alpha", now - 100))
        wd.add_evb(_StubEvb("beta", now))  # healthy
        wd.add_evb(_StubEvb("gamma", now - 50))
        wd.check_once()
        assert len(fired) == 1
        assert "'alpha'" in fired[0] and "'gamma'" in fired[0]
        assert "'beta'" not in fired[0]
        # one wedged thread no longer masks the memory check
        assert "memory limit exceeded" in fired[0]
        counters = wd.get_counters()
        assert counters["watchdog.stall_events"] == 2
        assert counters["watchdog.fired"] == 1

    def test_healthy_modules_do_not_fire(self):
        fired: list[str] = []
        wd = Watchdog(
            thread_timeout_s=300.0,
            max_memory_bytes=1 << 60,
            on_crash=fired.append,
        )
        wd.add_evb(_StubEvb("alpha", time.monotonic()))
        wd.check_once()
        assert not fired
        assert wd.get_counters() == {
            "watchdog.stall_events": 0,
            "watchdog.fired": 0,
        }


# -- bounded queues: overflow counters through the fb303 path ----------------


class TestQueueCounters:
    def test_bounded_rwqueue_sheds_oldest(self):
        q: RWQueue[int] = RWQueue(maxlen=2)
        for i in range(3):
            q.push(i)
        stats = q.stats()
        # `overflows` is the canonical spelling (counter-duplicate rule)
        assert stats["size"] == 2 and stats["overflows"] == 1
        assert q.get(timeout=1) == 1  # 0 was shed, newest state retained

    def test_replicate_queue_stats_aggregate_readers(self):
        rq: ReplicateQueue[int] = ReplicateQueue(maxlen=2)
        rq.get_reader()
        rq.get_reader()
        for i in range(5):
            rq.push(i)
        assert rq.stats() == {
            "depth": 2,
            "writes": 5,
            "overflows": 6,
            "readers": 2,
        }

    def test_counters_surface_through_ctrl_and_shim_source(self):
        rq: ReplicateQueue[int] = ReplicateQueue(maxlen=1)
        rq.get_reader()
        rq.push(1)
        rq.push(2)
        wd = Watchdog(on_crash=lambda reason: None)
        handler = OpenrCtrlHandler(
            "node", watchdog=wd, queues={"route_updates": rq}
        )
        # _all_counters is exactly what the thrift shim's fb303
        # getCounters serves (main.py wires counters_fn=handler._all_counters)
        counters = handler._all_counters()
        assert counters["queue.route_updates.overflows"] == 1
        assert counters["queue.route_updates.depth"] == 1
        assert counters["queue.route_updates.writes"] == 2
        assert counters["queue.route_updates.readers"] == 1
        assert counters["watchdog.stall_events"] == 0
        assert queue_counters({"x": rq})["queue.x.writes"] == 2


# -- multi-daemon fixture over the chaos fabrics -----------------------------


class ChaosRing:
    """RingFixture (tests/test_system.py) over the chaos fabrics: a
    seeded ChaosIoProvider for Spark and an InProcessTransport with a
    seeded KvChaosInjector, all sharing one ChaosEventLog."""

    def __init__(
        self,
        n: int,
        seed: int,
        *,
        kv_full_dump_fail: float = 0.0,
        kv_armed: bool = False,
    ) -> None:
        self.n = n
        self.seed = seed
        self.log = ChaosEventLog()
        self.spark_fabric = ChaosIoProvider(seed=seed, log_=self.log)
        self.kv_fabric = InProcessTransport()
        self.kv_chaos = KvChaosInjector(
            seed, full_dump_fail=kv_full_dump_fail, log_=self.log
        )
        if not kv_armed:
            self.kv_chaos.disarm()
        self.kv_fabric.set_chaos(self.kv_chaos)
        self.daemons: list[OpenrDaemon] = [self._build(i) for i in range(n)]
        for daemon in self.daemons:
            daemon.start()
        for i in range(n):
            j = (i + 1) % n
            if n == 2 and i == 1:
                break  # single link for a 2-ring
            self.spark_fabric.connect(
                f"openr-{i}", f"if-{i}-{j}", f"openr-{j}", f"if-{j}-{i}"
            )
        for i in range(n):
            self._push_link_events(i)

    def _build(self, i: int) -> OpenrDaemon:
        name = f"openr-{i}"
        addr = f"fe80::{name}"
        daemon = OpenrDaemon(
            make_config(name),
            io_provider=self.spark_fabric.endpoint(name),
            kvstore_transport=self.kv_fabric.bind(addr),
            spark_v6_addr=addr,
        )
        self.kv_fabric.register(addr, daemon.kvstore)
        return daemon

    def _push_link_events(self, i: int) -> None:
        j, k = (i + 1) % self.n, (i - 1) % self.n
        daemon = self.daemons[i]
        daemon.netlink_events_queue.push(LinkEvent(f"if-{i}-{j}", 1, True))
        if self.n > 2:
            daemon.netlink_events_queue.push(LinkEvent(f"if-{i}-{k}", 2, True))

    def advertise_loopbacks(self) -> None:
        for i, daemon in enumerate(self.daemons):
            daemon.prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix=f"fc00:{i}::/64")]
            )

    def prefix_exists(self, daemon: OpenrDaemon, prefix: str) -> bool:
        table = daemon.fib_agent.unicast.get(FIB_CLIENT, {})
        return normalize_prefix(prefix) in table

    def full_mesh(self) -> bool:
        for i, daemon in enumerate(self.daemons):
            for j in range(self.n):
                if i != j and not self.prefix_exists(daemon, f"fc00:{j}::/64"):
                    return False
        return True

    def respawn(self, i: int) -> OpenrDaemon:
        """Restart daemon i through Spark graceful restart: announce the
        restart, tear down, rebuild on the SAME fabric endpoints, and
        re-advertise its loopback."""
        old = self.daemons[i]
        for _ in range(3):  # repeat past seeded packet loss
            old.spark.flood_restarting_msg()
        old.stop()
        daemon = self._build(i)
        self.daemons[i] = daemon
        daemon.start()
        self._push_link_events(i)
        daemon.prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix=f"fc00:{i}::/64")]
        )
        return daemon

    def stop(self) -> None:
        for daemon in self.daemons:
            daemon.stop()


def _set_in_decision(daemon: OpenrDaemon, fn) -> None:
    """Mutate decision-thread state from the test thread, safely."""
    daemon.decision.run_in_event_base_thread(fn).result()


# -- degradation ladder on live daemons --------------------------------------


class TestDegradationLadder:
    def test_device_dispatch_failure_falls_back_to_host_oracle(self):
        ring = ChaosRing(2, seed=42)
        try:
            ring.advertise_loopbacks()
            assert wait_until(ring.full_mesh, 20)
            d0 = ring.daemons[0]
            solver = d0.decision.spf_solver
            # every device dispatch now fails; the solver must serve
            # routes from its host oracle instead of dropping the rebuild
            backend = ChaosSpfBackend(
                HostSpfBackend(), seed=1, fail_prob=1.0, log_=ring.log
            )
            _set_in_decision(d0, lambda: setattr(solver, "spf", backend))
            route_queue = d0.route_updates_queue
            writes_before = route_queue.stats()["writes"]
            fallbacks_before = d0.decision.get_counters().get(
                "decision.device_fallbacks", 0
            )
            ring.daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:99::/64")]
            )
            assert wait_until(
                lambda: ring.prefix_exists(d0, "fc00:99::/64"), 20
            )
            counters = d0.decision.get_counters()
            assert (
                counters.get("decision.device_fallbacks", 0) > fallbacks_before
            )
            # zero dropped/duplicated publications: every rebuild pushed,
            # every reader drained every push, nothing shed
            stats = route_queue.stats()
            assert stats["writes"] > writes_before
            assert stats["overflows"] == 0
            assert wait_until(
                lambda: route_queue.stats()["depth"] == 0, 10
            )
            # and the published routes are bit-exact host-oracle routes —
            # hold-based with pinned write counters: a single-instant
            # match can race a rebuild still in flight on a loaded box
            assert hold_converged([d0], 10), (
                fib_unicast_routes(d0),
                oracle_route_dbs(d0),
            )
        finally:
            ring.stop()

    def test_rebuild_failure_never_drops_the_publication(self):
        ring = ChaosRing(2, seed=43)
        try:
            ring.advertise_loopbacks()
            assert wait_until(ring.full_mesh, 20)
            d0 = ring.daemons[0]
            solver = d0.decision.spf_solver
            orig = solver.create_route_for_prefix_or_get_static_route
            state = {"armed": True}

            def flaky(*args, **kwargs):
                if state["armed"]:
                    state["armed"] = False
                    raise RuntimeError("injected rebuild failure")
                return orig(*args, **kwargs)

            _set_in_decision(
                d0,
                lambda: setattr(
                    solver, "create_route_for_prefix_or_get_static_route", flaky
                ),
            )
            ring.daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:9::/64")]
            )
            # the bottom rung recomputes on the host oracle and still
            # publishes: the route lands despite the injected failure
            assert wait_until(
                lambda: ring.prefix_exists(d0, "fc00:9::/64"), 20
            )
            counters = d0.decision.get_counters()
            assert counters.get("decision.route_rebuild_fallbacks", 0) >= 1
            assert counters.get("decision.device_fallbacks", 0) >= 1
            assert isinstance(solver.spf, HostSpfBackend)  # demoted
            # hold-based: the post-fallback product must match the oracle
            # through a quiescence window, not at one lucky instant
            assert hold_converged([d0], 10)
        finally:
            ring.stop()

    def test_fib_sync_retries_with_backoff_then_recovery(self):
        ring = ChaosRing(2, seed=44)
        try:
            ring.advertise_loopbacks()
            assert wait_until(ring.full_mesh, 20)
            d0 = ring.daemons[0]
            # all programming + syncs fail: Fib must retry on backoff and
            # count every retry
            d0.fib_agent.chaos = FibChaosPlan(
                3,
                fail_prob=1.0,
                fail_ops={"add_unicast_routes", "sync_fib"},
                log_=ring.log,
            )
            ring.daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:55::/64")]
            )
            assert wait_until(
                lambda: d0.fib.counters.get("fib.sync_retries", 0) >= 2, 20
            )
            d0.fib_agent.chaos.disarm()
            assert wait_until(
                lambda: ring.prefix_exists(d0, "fc00:55::/64"), 20
            )
        finally:
            ring.stop()

    def test_kvstore_full_sync_retries_then_recovery(self):
        ring = ChaosRing(2, seed=45, kv_full_dump_fail=1.0, kv_armed=True)
        try:
            assert wait_until(
                lambda: ring.daemons[0]
                .kvstore.get_counters()
                .get("kvstore.full_sync_retries", 0)
                >= 1,
                20,
            )
            ring.kv_chaos.disarm()
            ring.advertise_loopbacks()
            assert wait_until(ring.full_mesh, 25)
        finally:
            ring.stop()


# -- Spark graceful restart under seeded packet loss -------------------------

GR_CFG = SparkConfig(
    hello_time_s=0.2,
    fastinit_hello_time_s=0.02,
    keepalive_time_s=0.05,
    hold_time_s=0.5,
    graceful_restart_time_s=3.0,
    negotiate_hold_time_s=0.5,
)


def _spark_node(fabric: ChaosIoProvider, name: str, if_name: str):
    if_queue: ReplicateQueue = ReplicateQueue()
    nbr_queue: ReplicateQueue = ReplicateQueue()
    reader = nbr_queue.get_reader()
    spark = Spark(
        name, if_queue.get_reader(), nbr_queue, fabric.endpoint(name),
        config=GR_CFG,
    )
    spark.run()
    if_queue.push(
        InterfaceDatabase(
            this_node_name=name,
            interfaces={
                if_name: InterfaceInfo(if_name=if_name, is_up=True, if_index=1)
            },
        )
    )
    return spark, if_queue, reader


class TestSparkGrUnderLoss:
    def test_adjacency_survives_restart_through_gr_hold(self):
        fabric = ChaosIoProvider(seed=1234)
        fabric.set_link_profile("node1", "node2", drop=0.2)
        fabric.connect("node1", "if1", "node2", "if2")
        sp1, ifq1, events1 = _spark_node(fabric, "node1", "if1")
        sp2, ifq2, _ = _spark_node(fabric, "node2", "if2")
        sparks = [sp1, sp2]
        try:
            est = SparkNeighState.ESTABLISHED
            assert wait_until(
                lambda: sp1.get_neigh_state("if1", "node2") == est, 15
            )
            assert wait_until(
                lambda: sp2.get_neigh_state("if2", "node1") == est, 15
            )
            for _ in range(4):  # repeat the GR announce past 20% loss
                sp2.flood_restarting_msg()
            ifq2.close()
            sp2.stop()
            sp2.wait_until_stopped(5)
            assert wait_until(
                lambda: sp1.get_neigh_state("if1", "node2")
                == SparkNeighState.RESTART,
                5,
            ), "restarting hello lost: GR never engaged"
            # neighbor comes back on the same fabric endpoints inside the
            # GR hold window
            sp2b, ifq2b, _ = _spark_node(fabric, "node2", "if2")
            sparks.append(sp2b)
            ifq2 = ifq2b
            assert wait_until(
                lambda: sp1.get_neigh_state("if1", "node2") == est, 15
            )
            # the adjacency was HELD: restart events published, never DOWN
            seen = []
            while True:
                try:
                    seen.append(events1.get(timeout=0.1).event_type)
                except TimeoutError:
                    break
            assert NeighborEventType.NEIGHBOR_DOWN not in seen, seen
            assert NeighborEventType.NEIGHBOR_RESTARTING in seen, seen
            assert NeighborEventType.NEIGHBOR_RESTARTED in seen, seen
        finally:
            ifq1.close()
            ifq2.close()
            for spark in sparks:
                spark.stop()
            for spark in sparks:
                spark.wait_until_stopped(5)


# -- the scripted multi-node scenario ----------------------------------------


def run_chaos_scenario(seed: int):
    """One 4-node chaos timeline; returns (log, converged, tables, oracle).

    Ring 0-1-2-3-0.  The timeline: converge clean, then a lossy+flapping
    link 0-1, KvStore sync failures everywhere plus a hard kv partition
    1-2, Fib agent crash/failure bursts on node 2, a TTL storm, prefix
    churn — then daemon 3 restarts through Spark GR, everything heals,
    and every node must converge bit-exactly to its host-oracle routes.
    """
    ring = ChaosRing(4, seed, kv_full_dump_fail=0.25)
    scenario = ChaosScenario(log_=ring.log)
    try:
        scenario.step("advertise-loopbacks", ring.advertise_loopbacks)
        ok = scenario.wait("initial-convergence", ring.full_mesh, 30)

        scenario.step(
            "lossy-link-0-1",
            lambda: ring.spark_fabric.set_link_profile(
                "openr-0", "openr-1",
                drop=0.2, dup=0.1, reorder=0.1, jitter_s=0.005,
            ),
        )
        scenario.step("kv-chaos-on", ring.kv_chaos.arm)
        scenario.step(
            "flap-0-1-down",
            lambda: ring.spark_fabric.disconnect(
                "openr-0", "if-0-1", "openr-1", "if-1-0"
            ),
        )
        def rerouted() -> bool:
            table = ring.daemons[0].fib_agent.unicast.get(FIB_CLIENT, {})
            route = table.get(normalize_prefix("fc00:1::/64"))
            if route is None:
                return False
            names = {nh.neighbor_node_name for nh in route.next_hops}
            return names == {"openr-3"}

        ok &= scenario.wait("rerouted-around-0-1", rerouted, 30)
        scenario.step(
            "kv-partition-1-2",
            lambda: ring.kv_fabric.set_partitioned(
                "fe80::openr-1", "fe80::openr-2", True
            ),
        )
        scenario.step(
            "fib-chaos-node-2",
            lambda: setattr(
                ring.daemons[2].fib_agent,
                "chaos",
                FibChaosPlan(
                    seed,
                    fail_prob=0.25,
                    restart_prob=0.1,
                    log_=ring.log,
                    stream="fib:openr-2",
                ),
            ),
        )
        scenario.step(
            "ttl-storm",
            lambda: ring.kv_chaos.ttl_storm(ring.daemons[1].kvstore),
        )
        scenario.step(
            "prefix-churn",
            lambda: ring.daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:33::/64")]
            ),
        )
        scenario.step(
            "flap-0-1-up",
            lambda: ring.spark_fabric.connect(
                "openr-0", "if-0-1", "openr-1", "if-1-0"
            ),
        )
        scenario.step("restart-daemon-3", lambda: ring.respawn(3))

        def heal() -> None:
            ring.spark_fabric.clear_all_profiles()
            ring.kv_chaos.disarm()
            ring.kv_fabric.set_partitioned(
                "fe80::openr-1", "fe80::openr-2", False
            )
            plan = ring.daemons[2].fib_agent.chaos
            if plan is not None:
                plan.disarm()

        scenario.step("heal", heal)
        ok &= scenario.wait("post-heal-mesh", ring.full_mesh, 45)
        ok &= scenario.wait_converged(ring.daemons, 45)
        tables = {
            daemon.config.node_name: fib_unicast_routes(daemon)
            for daemon in ring.daemons
        }
        oracle = {
            daemon.config.node_name: oracle_route_dbs(daemon)
            for daemon in ring.daemons
        }
        return ring.log, ok, tables, oracle
    finally:
        ring.stop()


class TestChaosScenario:
    def test_scenario_converges_to_oracle_and_replays(self):
        seed = 20260805
        log1, ok1, tables1, oracle1 = run_chaos_scenario(seed)
        assert ok1, log1.scenario()
        assert tables1 == oracle1  # bit-exact host-oracle convergence
        assert len(tables1) == 4 and all(tables1.values())

        log2, ok2, tables2, oracle2 = run_chaos_scenario(seed)
        assert ok2, log2.scenario()
        assert tables2 == oracle2
        # same seed => same scripted timeline and same fault decisions
        assert log1.matches(log2), (log1.streams(), log2.streams())
        assert tables1 == tables2


class TestWaitTimeoutScale:
    """Regression for the replay-determinism flake: under OPENR_TSAN's
    vector-clock instrumentation plus full-suite load, the scripted
    scenario needs ~2-3x the wall clock to reach the identical converged
    state, so the calibrated wait budgets must scale when the detector
    is armed (and ONLY the search budgets — hold/poll semantics are
    pinned by hold_converged itself)."""

    def test_unarmed_default_is_identity(self, monkeypatch):
        from openr_tpu.analysis import race

        monkeypatch.delenv("OPENR_CHAOS_TIMEOUT_SCALE", raising=False)
        monkeypatch.setattr(race, "TSAN", None)
        assert wait_timeout_scale() == 1.0

    def test_armed_detector_scales_the_wait_budget(self, monkeypatch):
        from openr_tpu.analysis import race

        monkeypatch.delenv("OPENR_CHAOS_TIMEOUT_SCALE", raising=False)
        monkeypatch.setattr(race, "TSAN", object())
        assert wait_timeout_scale() == 3.0

        # the flake shape itself: a condition that flips at ~1.8x the
        # nominal budget (instrumentation-slowed convergence) must still
        # be reached by wait_until — unscaled it would time out
        flip_at = time.monotonic() + 0.9
        assert wait_until(lambda: time.monotonic() >= flip_at, timeout_s=0.5)

    def test_env_override_wins_and_is_floored(self, monkeypatch):
        from openr_tpu.analysis import race

        monkeypatch.setattr(race, "TSAN", None)
        monkeypatch.setenv("OPENR_CHAOS_TIMEOUT_SCALE", "5")
        assert wait_timeout_scale() == 5.0
        # a scale below 1 would silently tighten calibrated budgets
        monkeypatch.setenv("OPENR_CHAOS_TIMEOUT_SCALE", "0.25")
        assert wait_timeout_scale() == 1.0


@pytest.mark.slow
class TestChaosSoak:
    def test_randomized_soak(self, cpu_burner):
        # the shared burner (tests/conftest.py) keeps the box loaded so
        # the scenario's hold-based waits are exercised under the
        # contention that used to surface only in full-suite runs
        seed = int(
            os.environ.get(
                "OPENR_CHAOS_SEED", random.SystemRandom().randrange(2**31)
            )
        )
        try:
            log, ok, tables, oracle = run_chaos_scenario(seed)
            assert ok, log.scenario()
            assert tables == oracle
        except AssertionError as exc:
            raise AssertionError(
                f"chaos soak failed; replay with OPENR_CHAOS_SEED={seed}: {exc}"
            ) from exc
