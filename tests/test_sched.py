"""Deterministic schedule exploration (openr_tpu.analysis.sched): DPOR
reduction certificates, bit-identical replay, shrinking, the planted
ordering bug, zero-overhead-off arming, and the auto-collected
sched_corpus regression replays (the concurrency analogue of
tests/chaos_corpus/).
"""

from __future__ import annotations

import concurrent.futures
import glob
import json
import os
import threading
from types import SimpleNamespace

import pytest

from openr_tpu.analysis import sched

pytestmark = pytest.mark.sched

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "sched_corpus")

PLANTED_SCENARIO = "router_hedge_vs_death"


def _corpus_entries() -> list:
    return sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def _load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


class TestScheduleIds:
    def test_format_parse_round_trip(self):
        sid = sched.format_schedule_id("queue_shed_vs_carry", 3, [0, 2, 1])
        assert sid == "queue_shed_vs_carry:s3:0.2.1"
        assert sched.parse_schedule_id(sid) == (
            "queue_shed_vs_carry", False, 3, [0, 2, 1]
        )
        # empty choice string spells "-" so the id stays 3-field
        sid = sched.format_schedule_id(PLANTED_SCENARIO, 0, [], plant=True)
        assert sid == f"{PLANTED_SCENARIO}+plant:s0:-"
        assert sched.parse_schedule_id(sid) == (PLANTED_SCENARIO, True, 0, [])

    @pytest.mark.parametrize(
        "bad",
        ["", "no-colons", "queue_shed_vs_carry:s0", "queue_shed_vs_carry:sX:0",
         "queue_shed_vs_carry:s0:1.x", "unknown_scenario:s0:-"],
    )
    def test_malformed_ids_raise_value_error(self, bad):
        with pytest.raises(ValueError):
            sched.parse_schedule_id(bad)


class TestZeroOverheadUnarmed:
    """The TSAN standard: disarmed, every seam costs one module-constant
    read and the stdlib is untouched."""

    def test_unarmed_state(self):
        assert sched.SCHED is None
        assert not sched.patches_installed()
        # the monkeypatches are strictly scoped to _execute(): outside a
        # run the stdlib methods are the originals, not our wrappers
        assert (concurrent.futures.Future.result
                is not sched._patched_result)
        assert threading.Thread.start is not sched._patched_thread_start

    def test_patches_scoped_to_a_run_and_removed_after(self):
        before = concurrent.futures.Future.result
        run = sched.run_schedule("queue_shed_vs_carry", [])
        assert run.steps > 0
        assert not sched.patches_installed()
        assert concurrent.futures.Future.result is before
        assert sched.SCHED is None


class TestDporReduction:
    @pytest.mark.parametrize("scenario", sched.EXHAUSTIVE_SCENARIOS)
    def test_dpor_explores_fewer_schedules_than_naive(self, scenario):
        d = sched.explore(scenario, seed=0, mode="dpor")
        n = sched.explore(scenario, seed=0, mode="naive")
        # both certificates: the frontier drained, nothing was shed
        assert d.complete and n.complete, scenario
        assert d.schedules < n.schedules, (d.schedules, n.schedules)
        assert d.prunes > 0
        # soundness of the reduction: DPOR may not find a failure naive
        # exploration misses (both must be empty on the unplanted library)
        assert not d.failures and not n.failures
        print(
            f"{scenario}: dpor={d.schedules} naive={n.schedules} "
            f"prunes={d.prunes} "
            f"(ratio {n.schedules / d.schedules:.1f}x fewer)"
        )

    def test_exploration_is_deterministic(self):
        a = sched.explore("queue_shed_vs_carry", seed=0, mode="dpor")
        b = sched.explore("queue_shed_vs_carry", seed=0, mode="dpor")
        assert (a.schedules, a.prunes, a.coverage_tokens) == (
            b.schedules, b.prunes, b.coverage_tokens
        )


class TestPlantedBug:
    """End-to-end proof the checker works: exploration finds the planted
    ordering bug, the find replays bit-identically, and shrinking
    reduces it to a minimal schedule that still fails the same way."""

    def test_explore_finds_replays_and_shrinks_the_plant(self):
        r = sched.explore(PLANTED_SCENARIO, plant=True, seed=0, mode="dpor")
        assert r.complete and r.failures, "planted bug not found"
        found = r.failures[0]
        assert any("ledger-lost-update" in f for f in found.failures)

        # bit-identical replay: same id -> same trace fingerprint twice
        r1 = sched.replay_schedule(found.schedule_id)
        r2 = sched.replay_schedule(found.schedule_id)
        assert r1.trace == r2.trace
        assert r1.trace_fingerprint() == found.trace_fingerprint
        assert r1.failures == found.failures

        # shrink preserves the failure signature and actually reduces
        shrunk, best = sched.shrink_schedule(
            PLANTED_SCENARIO, found.choices, plant=True
        )
        assert len(shrunk) <= 2 < len(found.choices)
        assert sched._failure_signature(best.failures) == (
            sched._failure_signature(found.failures)
        )

    def test_unplanted_scenario_is_clean_everywhere(self):
        r = sched.explore(PLANTED_SCENARIO, plant=False, seed=0, mode="dpor")
        assert r.complete and not r.failures


class TestSchedCorpus:
    def test_corpus_directory_is_nonempty(self):
        assert _corpus_entries(), (
            f"no corpus entries under {CORPUS_DIR} — the planted find's "
            "minimal schedule must stay checked in"
        )

    @pytest.mark.parametrize(
        "path", _corpus_entries(),
        ids=[os.path.basename(p) for p in _corpus_entries()],
    )
    def test_corpus_entry_still_fails_its_oracle(self, path):
        entry = _load(path)
        scenario, plant, _seed, choices = sched.parse_schedule_id(
            entry["schedule_id"]
        )
        # minimality contract: shrunk entries only
        assert len(choices) <= 4, entry["schedule_id"]
        run = sched.replay_schedule(entry["schedule_id"])
        assert entry["oracle"] in sched._failure_signature(run.failures), (
            entry["schedule_id"], run.failures
        )
        if plant:
            # the regression pins the INTERLEAVING: without the planted
            # window the same choices replay clean
            clean = sched.run_schedule(scenario, choices, plant=False)
            assert not clean.failures, clean.failures


class TestTier1Smoke:
    def test_library_sweep_is_clean_with_certificates(self):
        out = sched.tier1_smoke(total_budget_s=60.0)
        assert out["failures"] == []
        assert out["shed"] == [], "healthy box shed scenarios (raise budget)"
        assert set(out["scenarios"]) == set(sched.SCENARIOS)
        for name in sched.EXHAUSTIVE_SCENARIOS:
            row = out["scenarios"][name]
            assert row["mode"] == "dpor" and row["complete"], (name, row)

    def test_budget_sheds_loudly_never_silently(self):
        out = sched.tier1_smoke(total_budget_s=1e-4)
        covered = set(out["scenarios"]) | set(out["shed"])
        assert covered == set(sched.SCENARIOS)
        assert out["shed"], "sub-ms budget must shed at least one scenario"


class TestFuzzFrontierTokens:
    def test_sample_tokens_shape_and_determinism(self):
        a = sched.sample_tokens(7, n_schedules=8)
        b = sched.sample_tokens(7, n_schedules=8)
        assert a and a == b
        for tok in a:
            kind, scenario, fp = tok.split(":")
            assert kind == "sched" and scenario in sched.SCENARIOS
            assert len(fp) == 10 and int(fp, 16) >= 0


class TestCliContract:
    """Exit codes match the analyzer convention: 0 clean, 1 findings,
    2 infra/misuse."""

    @staticmethod
    def ns(**kw):
        base = dict(sched_replay=None, sched_shrink=None, sched_seed=0)
        base.update(kw)
        return SimpleNamespace(**base)

    def test_replay_exit_codes(self, capsys):
        planted = f"{PLANTED_SCENARIO}+plant:s0:1.1"
        assert sched.run_cli(self.ns(sched_replay=planted)) == 1
        assert "ledger-lost-update" in capsys.readouterr().out
        clean = f"{PLANTED_SCENARIO}:s0:1.1"
        assert sched.run_cli(self.ns(sched_replay=clean)) == 0

    def test_malformed_id_is_infra_not_finding(self, capsys):
        assert sched.run_cli(self.ns(sched_replay="bogus:s0:-")) == 2
        assert "infra error" in capsys.readouterr().out

    def test_shrink_mode_prints_minimal_id(self, capsys):
        planted = f"{PLANTED_SCENARIO}+plant:s0:0.0.1.1.0.0"
        assert sched.run_cli(self.ns(sched_shrink=planted)) == 1
        out = capsys.readouterr().out
        assert "shrunk 6 ->" in out and "FAIL ledger-lost-update" in out
