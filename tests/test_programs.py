"""Tier-1 gate for the program-level invariant auditor
(openr_tpu/analysis/programs.py).

Three halves:

- the TREE is clean: the full ``--programs`` audit (every jit root in
  jit_paths + device/engine.py, plus every residency-ladder cell, traced
  on CPU against donation / dtype / callback / constant / op-count
  contracts) reports zero findings and zero coverage gaps.  This is the
  expensive half (~35 s: it compiles the fleet and the engine ladder
  cold) and runs exactly once per module;
- the AUDITOR is correct: each program rule catches a seeded violation
  built from a deliberately broken function (dropped donation, weak
  float promotion, host callback, oversized closed-over constant,
  blown budget);
- the fused fleet product's jaxpr matches a golden per-primitive
  snapshot — a graph-structure change (new gather, extra while-loop,
  lost fusion) fails with a readable per-primitive diff, not a bare
  count.  Regenerate tests/golden/fused_product_jaxpr.json with
  ``python -m openr_tpu.analysis --programs --write-budgets`` review +
  the snippet in TestGoldenJaxpr's docstring after an intentional
  kernel change.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openr_tpu.analysis import (
    AnalysisConfig,
    AnalysisError,
    Reporter,
    load_config,
    run_analysis,
)
from openr_tpu.analysis import programs as P
from openr_tpu.analysis.core import SourceFile

pytestmark = pytest.mark.analysis

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "openr_tpu"
GOLDEN = REPO_ROOT / "tests" / "golden" / "fused_product_jaxpr.json"
FUSED_KEY = ("openr_tpu.ops.allsources", "_fused_progressive_banded")


@pytest.fixture(scope="module")
def audit():
    """One full program audit for the whole module (the expensive half)."""
    config, root = load_config(PACKAGE)
    return run_analysis([PACKAGE], config, root, programs=True)


@pytest.fixture()
def harness():
    """(reporter, audit, sf, loc) wired to a real SourceFile so seeded
    jaxprs can be checked in isolation."""
    config, root = load_config(PACKAGE)
    reporter = Reporter(config)
    sf = SourceFile.parse(PACKAGE / "analysis" / "programs.py", root)
    return reporter, P._ProgramAudit(reporter, config, root), sf, (1, 0)


def _rules(reporter):
    return sorted(f.rule for f in reporter.findings)


class TestTreeIsProgramClean:
    def test_zero_findings_full_audit(self, audit):
        """The acceptance gate: every root traced, every contract holds.
        A coverage gap (a root no driver reaches) fails here too."""
        findings = audit.sorted_findings()
        assert not findings, "\n" + "\n".join(f.format() for f in findings)

    def test_budget_file_covers_every_program(self):
        budgets = json.loads(
            (PACKAGE / "analysis" / "program_budgets.json").read_text()
        )
        assert len(budgets) >= 25
        assert all(isinstance(v, int) and v > 0 for v in budgets.values())
        # both halves of the audit are budgeted: ops roots and ladder cells
        assert any(k.startswith("openr_tpu.ops.") for k in budgets)
        assert any(k.startswith("device.engine._forward_body[") for k in budgets)


class TestSeededViolations:
    def test_dropped_donation_is_caught(self, harness):
        """A transposed output can't alias the donated input; jax drops
        the donation silently (warning only) — the auditor must flag it."""
        reporter, audit, sf, loc = harness

        def transposes(a):
            return a.T

        spec = jax.ShapeDtypeStruct((8, 4), jnp.int32)
        audit.check_donation(sf, loc, "seed", transposes, (spec,), (0,))
        assert _rules(reporter) == ["program-donation"]

    def test_honored_donation_stays_silent(self, harness):
        reporter, audit, sf, loc = harness

        def keeps_layout(a):
            return a + 1

        spec = jax.ShapeDtypeStruct((8, 4), jnp.int32)
        audit.check_donation(sf, loc, "seed", keeps_layout, (spec,), (0,))
        assert _rules(reporter) == []

    def test_weak_float_promotion_is_caught(self, harness):
        reporter, audit, sf, loc = harness

        def promotes(x):
            return x * 2.5  # Python float -> weak f32 promotion

        closed = jax.jit(promotes).trace(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        ).jaxpr
        audit.check_jaxpr(sf, loc, "seed", "promotes", closed)
        assert "program-dtype" in _rules(reporter)

    def test_float_allowlist_spares_loss_kernels(self, harness):
        reporter, audit, sf, loc = harness
        audit.config.program_float_allowed = ["blessed"]

        def blessed(x):
            return x * jnp.float32(2.5)

        closed = jax.jit(blessed).trace(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        ).jaxpr
        audit.check_jaxpr(sf, loc, "seed", "blessed", closed)
        assert _rules(reporter) == []

    def test_host_callback_is_caught(self, harness):
        reporter, audit, sf, loc = harness

        def chatty(x):
            jax.debug.print("x = {}", x)
            return x + 1

        closed = jax.jit(chatty).trace(
            jax.ShapeDtypeStruct((4,), jnp.int32)
        ).jaxpr
        audit.check_jaxpr(sf, loc, "seed", "chatty", closed)
        assert "program-callback" in _rules(reporter)

    def test_large_closed_over_constant_is_caught(self, harness):
        reporter, audit, sf, loc = harness
        embedded = jnp.asarray(np.arange(4096, dtype=np.int32))  # 16 KiB

        def closes_over(x):
            return x + embedded

        closed = jax.jit(closes_over).trace(
            jax.ShapeDtypeStruct((4096,), jnp.int32)
        ).jaxpr
        audit.check_jaxpr(sf, loc, "seed", "closes_over", closed)
        assert "program-constants" in _rules(reporter)

    def test_integer_min_plus_program_stays_silent(self, harness):
        reporter, audit, sf, loc = harness

        def relax(d, m):
            return jnp.minimum(d, d + m)

        closed = jax.jit(relax).trace(
            jax.ShapeDtypeStruct((8,), jnp.int32),
            jax.ShapeDtypeStruct((8,), jnp.int32),
        ).jaxpr
        audit.check_jaxpr(sf, loc, "seed", "relax", closed)
        assert _rules(reporter) == []


class TestBudgetMachinery:
    def test_corrupt_budget_file_is_analyzer_error(self, tmp_path):
        bad = tmp_path / "program_budgets.json"
        bad.write_text("{not json")
        with pytest.raises(AnalysisError, match="unreadable budget file"):
            P._load_budgets(bad)
        bad.write_text("[1, 2]")
        with pytest.raises(AnalysisError, match="JSON object"):
            P._load_budgets(bad)

    def test_missing_budget_file_means_no_budgets(self, tmp_path):
        assert P._load_budgets(tmp_path / "absent.json") == {}

    def test_analyzer_errors_exit_2_findings_exit_1(self, monkeypatch):
        """The CLI's split: a broken auditor (driver/trace/config failure)
        is rc 2, a dirty tree is rc 1 — CI must not confuse the two."""
        from openr_tpu.analysis import cli

        def boom(*a, **kw):
            raise AnalysisError("program auditor driver 'x' failed")

        monkeypatch.setattr(cli, "run_analysis", boom)
        assert cli.main(["openr_tpu", "--programs"]) == 2

        fixture = str(
            REPO_ROOT / "tests" / "analysis_fixtures" / "counter_violations.py"
        )
        monkeypatch.undo()
        assert cli.main([fixture]) == 1


class TestGoldenJaxpr:
    """Golden per-primitive snapshot of the fused fleet product.

    Regenerate after an intentional kernel change::

        python - <<'PY'
        import json, jax
        from openr_tpu.analysis import programs as P
        jax.clear_caches()
        rec = P._Recorder()
        undo, orig = P._patch_roots(
            {("openr_tpu.ops.allsources", "_fused_progressive_banded"): None},
            rec,
        )
        try:
            P._drive_fleet_ring({})
        finally:
            for m, a, o in undo:
                setattr(m, a, o)
        args, kwargs = rec.specs[
            ("openr_tpu.ops.allsources", "_fused_progressive_banded")
        ][0]
        t = orig[
            ("openr_tpu.ops.allsources", "_fused_progressive_banded")
        ].trace(*args, **kwargs)
        c = {}
        for j in P._all_jaxprs(t.jaxpr.jaxpr):
            for e in j.eqns:
                c[e.primitive.name] = c.get(e.primitive.name, 0) + 1
        print(json.dumps(dict(sorted(c.items())), indent=2))
        PY
    """

    def test_fused_product_matches_golden(self):
        jax.clear_caches()  # inner roots must re-trace (see programs.check)
        recorder = P._Recorder()
        undo, originals = P._patch_roots({FUSED_KEY: None}, recorder)
        try:
            P._drive_fleet_ring({})
        finally:
            for mod, attr, orig in undo:
                setattr(mod, attr, orig)
        assert recorder.specs.get(FUSED_KEY), (
            "the ring fleet driver no longer dispatches the fused product"
        )
        # first captured spec == the cold 64-ring build (driver order is
        # deterministic); warm variants carry extra init args
        args, kwargs = recorder.specs[FUSED_KEY][0]
        traced = originals[FUSED_KEY].trace(*args, **kwargs)
        got: dict[str, int] = {}
        for j in P._all_jaxprs(traced.jaxpr.jaxpr):
            for e in j.eqns:
                got[e.primitive.name] = got.get(e.primitive.name, 0) + 1

        golden = json.loads(GOLDEN.read_text())
        if got != golden:
            lines = []
            for prim in sorted(set(golden) | set(got)):
                g, n = golden.get(prim, 0), got.get(prim, 0)
                if g != n:
                    lines.append(f"  {prim}: golden={g} got={n} ({n - g:+d})")
            pytest.fail(
                "fused-product jaxpr drifted from the golden snapshot "
                f"(total {sum(golden.values())} -> {sum(got.values())}):\n"
                + "\n".join(lines)
                + "\nIf intentional, regenerate the snapshot (class "
                "docstring) and justify the graph change in the PR."
            )
