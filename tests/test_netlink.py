"""Netlink library tests (reference test surface: openr/nl/tests/*, which
create real links and watch real events — we do the same with veth pairs
when the environment grants NET_ADMIN, and skip gracefully otherwise)."""

from __future__ import annotations

import socket
import struct
import subprocess
import time
import uuid

import pytest

from openr_tpu.nl.netlink import (
    IFF_UP,
    IFLA_IFNAME,
    NLMSG_DONE,
    RTM_DELLINK,
    RTM_GETLINK,
    RTM_NEWADDR,
    RTM_NEWLINK,
    LinkInfo,
    NetlinkProtocolSocket,
    build_dump_request,
    parse_messages,
)
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.types import AddrEvent, LinkEvent


def _nlmsg(msg_type: int, payload: bytes, flags: int = 0) -> bytes:
    hdr = struct.pack("=IHHII", 16 + len(payload), msg_type, flags, 1, 0)
    return hdr + payload


def _rtattr(atype: int, data: bytes) -> bytes:
    alen = 4 + len(data)
    pad = (-alen) % 4
    return struct.pack("=HH", alen, atype) + data + b"\x00" * pad


class TestCodec:
    def test_dump_request_shape(self):
        req = build_dump_request(RTM_GETLINK, seq=7)
        length, mtype, flags, seq, pid = struct.unpack_from("=IHHII", req)
        assert length == len(req)
        assert mtype == RTM_GETLINK
        assert flags == 0x01 | 0x300  # REQUEST | DUMP
        assert seq == 7

    def test_parse_newlink(self):
        ifinfo = struct.pack("=BxHiII", socket.AF_UNSPEC, 1, 42, IFF_UP, 0)
        payload = ifinfo + _rtattr(IFLA_IFNAME, b"eth-test\x00")
        msgs = list(parse_messages(_nlmsg(RTM_NEWLINK, payload)))
        assert len(msgs) == 1
        link = msgs[0].link
        assert link == LinkInfo(if_index=42, if_name="eth-test", flags=IFF_UP)
        assert link.is_up

    def test_parse_newaddr_v6(self):
        ifaddr = struct.pack("=BBBBi", socket.AF_INET6, 64, 0, 0, 42)
        raw = socket.inet_pton(socket.AF_INET6, "fc99::1")
        payload = ifaddr + _rtattr(1, raw)  # IFA_ADDRESS
        msgs = list(parse_messages(_nlmsg(RTM_NEWADDR, payload)))
        assert msgs[0].addr.prefix == "fc99::1/64"
        assert msgs[0].addr.is_valid

    def test_parse_multipart_and_done(self):
        ifinfo = struct.pack("=BxHiII", 0, 1, 1, IFF_UP, 0)
        data = _nlmsg(RTM_NEWLINK, ifinfo + _rtattr(IFLA_IFNAME, b"lo\x00"))
        data += _nlmsg(NLMSG_DONE, struct.pack("=i", 0))
        msgs = list(parse_messages(data))
        assert [m.msg_type for m in msgs] == [RTM_NEWLINK, NLMSG_DONE]

    def test_truncated_garbage_is_dropped(self):
        assert list(parse_messages(b"\x01\x02\x03")) == []
        # header claiming more bytes than present
        bad = struct.pack("=IHHII", 4096, RTM_NEWLINK, 0, 1, 0)
        assert list(parse_messages(bad)) == []


def _have_net_admin() -> bool:
    probe = f"nltest-{uuid.uuid4().hex[:6]}"
    r = subprocess.run(
        ["ip", "link", "add", probe, "type", "veth",
         "peer", "name", f"{probe}p"],
        capture_output=True,
    )
    if r.returncode != 0:
        return False
    subprocess.run(["ip", "link", "del", probe], capture_output=True)
    return True


NET_ADMIN = _have_net_admin()


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestRealKernel:
    """Reference: openr/nl/tests create real links and assert events."""

    @pytest.fixture
    def veth(self):
        name = f"vt{uuid.uuid4().hex[:8]}"
        peer = f"{name}p"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth", "peer", "name", peer],
            check=True,
        )
        yield name, peer
        subprocess.run(["ip", "link", "del", name], capture_output=True)

    @pytest.fixture
    def nl(self):
        queue: ReplicateQueue = ReplicateQueue()
        reader = queue.get_reader()
        sock = NetlinkProtocolSocket(queue)
        sock.run()
        yield sock, reader
        queue.close()
        sock.stop()
        sock.wait_until_stopped(5)

    @staticmethod
    def _drain_until(reader, pred, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            remaining = max(0.05, deadline - time.monotonic())
            try:
                event = reader.get(timeout=remaining)
            except Exception:
                break
            if pred(event):
                return event
        return None

    def test_initial_dump_includes_loopback(self, nl):
        sock, reader = nl
        event = self._drain_until(
            reader, lambda e: isinstance(e, LinkEvent) and e.if_name == "lo"
        )
        assert event is not None
        assert sock.counters["netlink.links"] >= 1

    def test_link_up_down_events(self, nl, veth):
        sock, reader = nl
        name, peer = veth
        # creation is visible (either via dump-race or the event stream)
        assert self._drain_until(
            reader, lambda e: isinstance(e, LinkEvent) and e.if_name == name
        )
        subprocess.run(["ip", "link", "set", name, "up"], check=True)
        subprocess.run(["ip", "link", "set", peer, "up"], check=True)
        up = self._drain_until(
            reader,
            lambda e: isinstance(e, LinkEvent)
            and e.if_name == name
            and e.is_up,
        )
        assert up is not None
        subprocess.run(["ip", "link", "set", name, "down"], check=True)
        down = self._drain_until(
            reader,
            lambda e: isinstance(e, LinkEvent)
            and e.if_name == name
            and not e.is_up,
        )
        assert down is not None

    def test_addr_events(self, nl, veth):
        sock, reader = nl
        name, peer = veth
        subprocess.run(["ip", "link", "set", name, "up"], check=True)
        subprocess.run(
            ["ip", "addr", "add", "fc98::1/64", "dev", name], check=True
        )
        added = self._drain_until(
            reader,
            lambda e: isinstance(e, AddrEvent)
            and e.if_name == name
            and e.prefix == "fc98::1/64"
            and e.is_valid,
        )
        assert added is not None
        subprocess.run(
            ["ip", "addr", "del", "fc98::1/64", "dev", name], check=True
        )
        removed = self._drain_until(
            reader,
            lambda e: isinstance(e, AddrEvent)
            and e.if_name == name
            and e.prefix == "fc98::1/64"
            and not e.is_valid,
        )
        assert removed is not None

    def test_get_all_links_sync_api(self, nl, veth):
        sock, reader = nl
        name, _peer = veth
        names = {l.if_name for l in sock.get_all_links()}
        assert "lo" in names and name in names


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestDaemonWithNetlink:
    def test_link_monitor_sees_kernel_interfaces(self):
        """enable_netlink: LinkMonitor's interface DB is driven by REAL
        kernel events end-to-end (SURVEY §1 dataflow: netlink ->
        netlinkEventsQueue -> LinkMonitor)."""
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from tests.test_system import make_config, wait_for

        name = f"vd{uuid.uuid4().hex[:8]}"
        peer = f"{name}p"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth", "peer", "name", peer],
            check=True,
        )
        try:
            subprocess.run(["ip", "link", "set", name, "up"], check=True)
            subprocess.run(["ip", "link", "set", peer, "up"], check=True)
            cfg = make_config("nld-0")
            cfg.enable_netlink = True
            cfg.link_monitor_config.include_interface_regexes = [f"^{name}$"]
            daemon = OpenrDaemon(
                cfg,
                io_provider=MockIoProvider().endpoint("nld-0"),
                spark_v6_addr="::1",
            )
            daemon.start()
            try:
                assert wait_for(
                    lambda: any(
                        info.if_name == name and info.is_up
                        for info in daemon.link_monitor.get_interfaces().values()
                    ),
                    timeout=15,
                ), daemon.link_monitor.get_interfaces()
            finally:
                daemon.stop()
        finally:
            subprocess.run(["ip", "link", "del", name], capture_output=True)
