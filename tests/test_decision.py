"""Decision module integration tests.

Publication-driven, modeled on the reference's DecisionTest
(openr/decision/tests/DecisionTest.cpp): drive the module thread with
synthetic Publications and assert on emitted DecisionRouteUpdate deltas.
"""

from __future__ import annotations

import time

import pytest

from openr_tpu.decision.decision import Decision
from openr_tpu.decision.rib_policy import (
    RibPolicyConfig,
    RibPolicyStatementConfig,
    RibRouteActionWeight,
)
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.serializer import dumps
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PerfEvents,
    PrefixDatabase,
    PrefixEntry,
    Publication,
    Value,
    adj_key,
    prefix_key,
)

PFX1 = "::1:0/112"
PFX2 = "::2:0/112"


def adj(me: str, other: str, metric: int = 10) -> Adjacency:
    return Adjacency(
        other_node_name=other,
        if_name=f"{me}/{other}",
        other_if_name=f"{other}/{me}",
        metric=metric,
        next_hop_v6=f"fe80::{other}",
    )


def adj_val(node: str, adjs: list[Adjacency], version=1, label=0, **kw) -> Value:
    db = AdjacencyDatabase(
        this_node_name=node, adjacencies=adjs, node_label=label, **kw
    )
    return Value(version=version, originator_id=node, value=dumps(db))


def prefix_val(
    node: str, prefix: str, version=1, entry: PrefixEntry | None = None, **kw
) -> tuple[str, Value]:
    db = PrefixDatabase(
        this_node_name=node,
        prefix_entries=[entry or PrefixEntry(prefix=prefix)],
        **kw,
    )
    return prefix_key(node, prefix, "0"), Value(
        version=version, originator_id=node, value=dumps(db)
    )


def square_publication() -> Publication:
    kv = {
        adj_key("1"): adj_val("1", [adj("1", "2"), adj("1", "3")], label=101),
        adj_key("2"): adj_val("2", [adj("2", "1"), adj("2", "4")], label=102),
        adj_key("3"): adj_val("3", [adj("3", "1"), adj("3", "4")], label=103),
        adj_key("4"): adj_val("4", [adj("4", "2"), adj("4", "3")], label=104),
    }
    k, v = prefix_val("4", PFX1)
    kv[k] = v
    return Publication(key_vals=kv, area="0")


@pytest.fixture
def harness():
    kvq: ReplicateQueue[Publication] = ReplicateQueue()
    staticq: ReplicateQueue = ReplicateQueue()
    routeq: ReplicateQueue = ReplicateQueue()
    route_reader = routeq.get_reader()
    decision = Decision(
        "1",
        kvq.get_reader(),
        staticq.get_reader(),
        routeq,
        debounce_min_s=0.005,
        debounce_max_s=0.02,
        enable_rib_policy=True,
    )
    decision.run()
    yield kvq, staticq, route_reader, decision
    kvq.close()
    staticq.close()
    routeq.close()
    decision.stop()
    decision.wait_until_stopped(5)


def get_update(reader, timeout=3.0):
    return reader.get(timeout=timeout)


class TestDecision:
    def test_initial_convergence_and_incremental(self, harness):
        kvq, _staticq, route_reader, decision = harness
        kvq.push(square_publication())
        update = get_update(route_reader)
        assert PFX1 in update.unicast_routes_to_update
        route = update.unicast_routes_to_update[PFX1]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2", "3"}
        # node-label MPLS routes programmed too
        assert {e.label for e in update.mpls_routes_to_update} == {
            101,
            102,
            103,
            104,
        }
        # perf events ride with the update
        names = [e.event_name for e in update.perf_events.events]
        assert "DECISION_RECEIVED" in names and "ROUTE_UPDATE" in names

        # incremental: new prefix only
        k, v = prefix_val("2", PFX2)
        kvq.push(Publication(key_vals={k: v}, area="0"))
        update2 = get_update(route_reader)
        assert set(update2.unicast_routes_to_update) == {PFX2}
        assert not update2.mpls_routes_to_update

    def test_prefix_withdrawal_via_expired_key(self, harness):
        kvq, _staticq, route_reader, _decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        kvq.push(
            Publication(
                expired_keys=[prefix_key("4", PFX1, "0")], area="0"
            )
        )
        update = get_update(route_reader)
        assert update.unicast_routes_to_delete == [PFX1]

    def test_adj_expiry_full_rebuild(self, harness):
        kvq, _staticq, route_reader, _decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        # node 2 dies: route to PFX1 now only via 3
        kvq.push(Publication(expired_keys=[adj_key("2")], area="0"))
        update = get_update(route_reader)
        route = update.unicast_routes_to_update[PFX1]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"3"}
        assert 102 in update.mpls_routes_to_delete

    def test_metric_change_reroutes(self, harness):
        kvq, _staticq, route_reader, _decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        # raise metric on 1->2: only 1->3->4 remains shortest
        kvq.push(
            Publication(
                key_vals={
                    adj_key("1"): adj_val(
                        "1",
                        [adj("1", "2", metric=100), adj("1", "3")],
                        version=2,
                        label=101,
                    )
                },
                area="0",
            )
        )
        update = get_update(route_reader)
        route = update.unicast_routes_to_update[PFX1]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"3"}

    def test_rib_policy_reweights(self, harness):
        kvq, _staticq, route_reader, decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        decision.set_rib_policy(
            RibPolicyConfig(
                statements=[
                    RibPolicyStatementConfig(
                        name="t",
                        prefixes=[PFX1],
                        set_weight=RibRouteActionWeight(
                            default_weight=1, neighbor_to_weight={"2": 7}
                        ),
                    )
                ],
                ttl_secs=60,
            )
        )
        update = get_update(route_reader)
        route = update.unicast_routes_to_update[PFX1]
        weights = {nh.neighbor_node_name: nh.weight for nh in route.nexthops}
        assert weights == {"2": 7, "3": 1}
        cfg = decision.get_rib_policy()
        assert cfg.statements[0].prefixes == [PFX1]
        assert 0 < cfg.ttl_secs <= 60
        decision.clear_rib_policy()
        update = get_update(route_reader)
        route = update.unicast_routes_to_update[PFX1]
        assert {nh.weight for nh in route.nexthops} == {0}

    def test_cold_start_holds_updates(self):
        kvq: ReplicateQueue[Publication] = ReplicateQueue()
        routeq: ReplicateQueue = ReplicateQueue()
        route_reader = routeq.get_reader()
        decision = Decision(
            "1",
            kvq.get_reader(),
            None,
            routeq,
            debounce_min_s=0.005,
            debounce_max_s=0.02,
            eor_time_s=0.3,
        )
        decision.run()
        try:
            t0 = time.monotonic()
            kvq.push(square_publication())
            update = route_reader.get(timeout=3.0)
            elapsed = time.monotonic() - t0
            assert elapsed >= 0.25, elapsed  # held until eor expiry
            assert PFX1 in update.unicast_routes_to_update
        finally:
            kvq.close()
            routeq.close()
            decision.stop()
            decision.wait_until_stopped(5)

    def test_get_route_db_source_parameterized(self, harness):
        kvq, _staticq, route_reader, decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        db = decision.get_route_db("3")
        assert {
            nh.neighbor_node_name for nh in db.unicast_routes[PFX1].nexthops
        } == {"4"}
        adj_dbs = decision.get_adjacency_databases()
        assert {db.this_node_name for db in adj_dbs} == {"1", "2", "3", "4"}

    def test_self_redistribution_ignored(self, harness):
        kvq, _staticq, route_reader, decision = harness
        kvq.push(square_publication())
        get_update(route_reader)
        # a reflection of our own redistributed route: area_stack ends in a
        # known area -> ignored
        k, v = prefix_val(
            "1",
            PFX2,
            entry=PrefixEntry(prefix=PFX2, area_stack=("0",)),
        )
        kvq.push(Publication(key_vals={k: v}, area="0"))
        # synchronize on a later, non-reflected prefix reaching the route
        # table so the reflected one above is known to have been processed
        pfx3 = "::3:0/112"
        k3, v3 = prefix_val("2", pfx3)
        kvq.push(Publication(key_vals={k3: v3}, area="0"))
        update = get_update(route_reader)
        assert pfx3 in update.unicast_routes_to_update
        prefixes = decision.run_in_event_base_thread(
            lambda: set(decision.prefix_state.prefixes)
        ).result()
        assert PFX2 not in prefixes


class TestNoOpPublications:
    """Ancestors: DecisionTestFixture.NoSpfOnIrrelevantPublication
    (DecisionTest.cpp:6179) and NoSpfOnDuplicatePublication (:6212)."""

    @staticmethod
    def _assert_no_update_before_sentinel(kvq, reader, decision):
        """Non-vacuous negative check: push a known-relevant sentinel
        prefix AFTER the publication under test; the NEXT update must be
        the sentinel's alone, proving the tested publication was
        processed and produced nothing (the sibling pattern in
        test_self_redistribution_ignored)."""
        k, v = prefix_val("3", PFX2)
        kvq.push(Publication(key_vals={k: v}, area="0"))
        update = get_update(reader)
        # dict[prefix -> RibUnicastEntry]: the sentinel's prefix alone
        assert list(update.unicast_routes_to_update) == [PFX2]

    def test_no_rebuild_on_irrelevant_publication(self, harness):
        kvq, _staticq, reader, decision = harness
        kvq.push(square_publication())
        get_update(reader)  # initial convergence

        # wrong markers: "adj2:" / "adji2:" are NOT the adj/prefix
        # namespaces — the module must ignore them entirely
        kv = {
            "adj2:1": adj_val("1", [adj("1", "2")]),
            "adji2:2": adj_val("2", [adj("2", "1")]),
        }
        before_adj = decision.counters.get("decision.adj_db_update", 0)
        kvq.push(Publication(key_vals=kv, area="0"))
        self._assert_no_update_before_sentinel(kvq, reader, decision)
        assert (
            decision.counters.get("decision.adj_db_update", 0) == before_adj
        )

    def test_no_rebuild_on_duplicate_publication(self, harness):
        kvq, _staticq, reader, decision = harness
        pub = square_publication()
        kvq.push(pub)
        get_update(reader)  # initial convergence

        # byte-identical re-publication: values PARSE (adj counter must
        # increment, proving processing) but nothing changed — no
        # DecisionRouteUpdate may be emitted before the sentinel's
        before_adj = decision.counters.get("decision.adj_db_update", 0)
        kvq.push(square_publication())
        self._assert_no_update_before_sentinel(kvq, reader, decision)
        assert (
            decision.counters.get("decision.adj_db_update", 0)
            == before_adj + 4
        )
