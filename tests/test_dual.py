"""DUAL flood-topology tests (modeled on openr/dual/tests/DualTest.cpp:
state-machine table, message-passing fixtures over synthetic graphs, and
SPT validation after every link flap / cost change)."""

from __future__ import annotations

import heapq
import random
from collections import deque

from openr_tpu.kvstore.dual import (
    INFINITY64,
    DualEvent,
    DualNode,
    DualState,
    DualStateMachine,
)
from openr_tpu.types import DualMessages


class TestStateMachine:
    """Reference: TEST(Dual, StateMachine) — the full transition table
    (Dual.cpp:12-60)."""

    def t(self, start, event, fc, expected):
        sm = DualStateMachine()
        sm.state = start
        sm.process_event(event, fc)
        assert sm.state == expected, (start, event, fc)

    def test_passive(self):
        self.t(DualState.PASSIVE, DualEvent.OTHERS, True, DualState.PASSIVE)
        self.t(DualState.PASSIVE, DualEvent.OTHERS, False, DualState.ACTIVE1)
        self.t(
            DualState.PASSIVE,
            DualEvent.QUERY_FROM_SUCCESSOR,
            False,
            DualState.ACTIVE3,
        )
        self.t(DualState.PASSIVE, DualEvent.INCREASE_D, False, DualState.ACTIVE1)

    def test_active0(self):
        self.t(DualState.ACTIVE0, DualEvent.OTHERS, True, DualState.ACTIVE0)
        self.t(DualState.ACTIVE0, DualEvent.LAST_REPLY, True, DualState.PASSIVE)
        self.t(DualState.ACTIVE0, DualEvent.LAST_REPLY, False, DualState.ACTIVE2)

    def test_active1(self):
        self.t(DualState.ACTIVE1, DualEvent.INCREASE_D, True, DualState.ACTIVE0)
        self.t(DualState.ACTIVE1, DualEvent.LAST_REPLY, True, DualState.PASSIVE)
        self.t(
            DualState.ACTIVE1,
            DualEvent.QUERY_FROM_SUCCESSOR,
            True,
            DualState.ACTIVE2,
        )
        self.t(DualState.ACTIVE1, DualEvent.OTHERS, False, DualState.ACTIVE1)

    def test_active2(self):
        self.t(DualState.ACTIVE2, DualEvent.LAST_REPLY, True, DualState.PASSIVE)
        self.t(DualState.ACTIVE2, DualEvent.LAST_REPLY, False, DualState.ACTIVE3)
        self.t(DualState.ACTIVE2, DualEvent.INCREASE_D, True, DualState.ACTIVE2)

    def test_active3(self):
        self.t(DualState.ACTIVE3, DualEvent.LAST_REPLY, True, DualState.PASSIVE)
        self.t(DualState.ACTIVE3, DualEvent.INCREASE_D, True, DualState.ACTIVE2)
        self.t(DualState.ACTIVE3, DualEvent.OTHERS, True, DualState.ACTIVE3)


class Fabric:
    """In-memory message fabric connecting DualNodes (reference:
    DualBaseFixture, DualTest.cpp:269) — queued delivery, pumped to
    quiescence after each event."""

    def __init__(self):
        self.nodes: dict[str, DualNode] = {}
        self.queue: deque = deque()
        self.links: dict[frozenset, int] = {}  # cost, absent = down

    def add_node(self, node_id: str, is_root: bool = False) -> DualNode:
        def send(neighbor: str, msgs: DualMessages, me=node_id) -> bool:
            self.queue.append((neighbor, msgs))
            return True

        node = DualNode(node_id, is_root, send_dual_messages=send)
        self.nodes[node_id] = node
        return node

    def link_up(self, a: str, b: str, cost: int = 1):
        self.links[frozenset((a, b))] = cost
        self.nodes[a].peer_up(b, cost)
        self.nodes[b].peer_up(a, cost)
        self.pump()

    def link_down(self, a: str, b: str):
        self.links.pop(frozenset((a, b)), None)
        self.nodes[a].peer_down(b)
        self.nodes[b].peer_down(a)
        self.pump()

    def cost_change(self, a: str, b: str, cost: int):
        self.links[frozenset((a, b))] = cost
        self.nodes[a].peer_cost_change(b, cost)
        self.nodes[b].peer_cost_change(a, cost)
        self.pump()

    def pump(self):
        n = 0
        while self.queue:
            dst, msgs = self.queue.popleft()
            self.nodes[dst].process_dual_messages(msgs)
            n += 1
            assert n < 100_000, "dual did not converge"

    # -- validation (reference: DualBaseFixture::validate) -----------------

    def dijkstra(self, src: str) -> dict[str, int]:
        dist = {src: 0}
        heap = [(0, src)]
        while heap:
            d, u = heapq.heappop(heap)
            if d > dist.get(u, INFINITY64):
                continue
            for key, cost in self.links.items():
                if u in key:
                    (v,) = key - {u} or {u}
                    nd = d + cost
                    if nd < dist.get(v, INFINITY64):
                        dist[v] = nd
                        heapq.heappush(heap, (nd, v))
        return dist

    def validate(self):
        roots = {n.node_id for n in self.nodes.values() if n.is_root}
        if not roots:
            for node in self.nodes.values():
                assert node.get_spt_root_id() is None
            return
        for root in roots:
            expected = self.dijkstra(root)
            parent_edges = set()
            for node in self.nodes.values():
                info = node.get_info(root)
                assert info is not None, (root, node.node_id)
                # converged & passive
                assert info.sm.state == DualState.PASSIVE, (
                    root,
                    node.node_id,
                    info,
                )
                exp = expected.get(node.node_id, INFINITY64)
                assert info.distance == exp, (root, node.node_id, info, exp)
                if node.node_id == root:
                    assert info.nexthop == root
                    continue
                if exp == INFINITY64:
                    continue
                # parent relationship is distance-consistent
                parent = info.nexthop
                assert parent is not None
                cost = self.links.get(frozenset((node.node_id, parent)))
                assert cost is not None, (
                    f"{node.node_id} parent {parent} not a live link"
                )
                assert expected[parent] + cost == exp
                parent_edges.add((node.node_id, parent))
            # parent pointers form a tree over reachable nodes (SPT)
            reachable = {
                n for n in self.nodes if expected.get(n, INFINITY64) < INFINITY64
            }
            assert len(parent_edges) == len(reachable) - 1


class TestDualTopologies:
    def test_two_nodes(self):
        f = Fabric()
        f.add_node("n0", is_root=True)
        f.add_node("n1")
        f.link_up("n0", "n1")
        f.validate()
        info = f.nodes["n1"].get_info("n0")
        assert info.nexthop == "n0" and info.distance == 1

    def test_no_root(self):
        f = Fabric()
        f.add_node("n0")
        f.add_node("n1")
        f.link_up("n0", "n1")
        f.validate()

    def test_ring(self):
        """Reference: ring topology case in DualTest."""
        f = Fabric()
        n = 6
        f.add_node("n0", is_root=True)
        for i in range(1, n):
            f.add_node(f"n{i}")
        for i in range(n):
            f.link_up(f"n{i}", f"n{(i + 1) % n}")
        f.validate()
        # flap every edge down/up, validating each time (DualTest flapping)
        for i in range(n):
            a, b = f"n{i}", f"n{(i + 1) % n}"
            f.link_down(a, b)
            f.validate()
            f.link_up(a, b)
            f.validate()

    def test_star(self):
        f = Fabric()
        f.add_node("hub", is_root=True)
        for i in range(5):
            f.add_node(f"leaf{i}")
            f.link_up("hub", f"leaf{i}")
        f.validate()
        f.link_down("hub", "leaf2")
        f.validate()
        assert f.nodes["leaf2"].get_info("hub").distance == INFINITY64

    def test_multiple_roots_smallest_wins(self):
        f = Fabric()
        f.add_node("a", is_root=True)
        f.add_node("b", is_root=True)
        f.add_node("c")
        f.link_up("a", "b")
        f.link_up("b", "c")
        f.validate()
        for node in f.nodes.values():
            assert node.get_spt_root_id() == "a"
        # root a dies: everyone falls back to root b
        f.link_down("a", "b")
        f.validate()
        assert f.nodes["c"].get_spt_root_id() == "b"

    def test_cost_changes(self):
        f = Fabric()
        f.add_node("r", is_root=True)
        for x in ("a", "b"):
            f.add_node(x)
        f.link_up("r", "a", cost=1)
        f.link_up("r", "b", cost=10)
        f.link_up("a", "b", cost=1)
        f.validate()
        assert f.nodes["b"].get_info("r").nexthop == "a"  # r-a-b = 2
        f.cost_change("a", "b", 20)  # now r-b direct = 10
        f.validate()
        assert f.nodes["b"].get_info("r").nexthop == "r"
        f.cost_change("r", "b", 1)
        f.validate()
        assert f.nodes["b"].get_info("r").distance == 1

    def test_random_graphs_with_flaps(self):
        """Reference: DualTest random topology + flap-every-edge sweep."""
        rng = random.Random(7)
        for trial in range(3):
            f = Fabric()
            n = 8
            f.add_node("n0", is_root=True)
            for i in range(1, n):
                f.add_node(f"n{i}")
            edges = []
            # spanning tree + extras
            for i in range(1, n):
                j = rng.randrange(i)
                edges.append((f"n{i}", f"n{j}", rng.randint(1, 5)))
            for _ in range(4):
                a, b = rng.sample(range(n), 2)
                if frozenset((f"n{a}", f"n{b}")) not in {
                    frozenset((x, y)) for x, y, _ in edges
                }:
                    edges.append((f"n{a}", f"n{b}", rng.randint(1, 5)))
            for a, b, c in edges:
                f.link_up(a, b, c)
            f.validate()
            for a, b, c in edges:
                f.link_down(a, b)
                f.validate()
                f.link_up(a, b, c)
                f.validate()

    def test_spt_peers(self):
        """sptPeers = parent + registered children; children mirror the
        KvStore FLOOD_TOPO_SET flow."""
        f = Fabric()
        f.add_node("r", is_root=True)
        f.add_node("a")
        f.add_node("b")
        f.link_up("r", "a")
        f.link_up("a", "b")
        # emulate the KvStore layer: each node registers itself as child
        # of its parent
        for node_id in ("a", "b"):
            info = f.nodes[node_id].get_info("r")
            f.nodes[info.nexthop].get_dual("r").add_child(node_id)
        f.validate()
        assert f.nodes["r"].get_dual("r").spt_peers() == {"a", "r"}
        assert f.nodes["a"].get_dual("r").spt_peers() == {"r", "b"}
        assert f.nodes["b"].get_dual("r").spt_peers() == {"a"}
