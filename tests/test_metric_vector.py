"""BGP MetricVector comparison + best-path selection conformance.

The compare-chain cases are ported from the reference's
MetricVectorUtilsTest (openr/common/tests/UtilTest.cpp:780-838) and the
solver-level cases from DecisionTest's BGP route scenarios
(openr/decision/tests/DecisionTest.cpp:795-870): a strictly-better vector
wins the route, identical vectors TIE and the route is skipped, and
tie-breaker entities keep the looser in the ECMP set while re-pointing
the best entry.
"""

from __future__ import annotations

from openr_tpu.decision.metric_vector import (
    CompareResult,
    compare_metric_vectors,
    compare_metrics,
    is_decisive,
    negate,
    result_for_loner,
)
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.types import (
    CompareType,
    MetricEntity,
    MetricVector,
    PrefixEntry,
    PrefixType,
    normalize_prefix,
)
from tests.test_spf_solver import adj, build_link_state


def ent(
    type: int,
    priority: int,
    metric: tuple[int, ...],
    op: CompareType = CompareType.WIN_IF_PRESENT,
    tie_breaker: bool = False,
) -> MetricEntity:
    return MetricEntity(
        type=type,
        priority=priority,
        op=op,
        is_best_path_tie_breaker=tie_breaker,
        metric=metric,
    )


def five_metrics() -> tuple[MetricVector, MetricVector]:
    """The UtilTest fixture: 5 entities, type==priority==i, metric [i]."""
    mk = lambda: MetricVector(
        version=1, metrics=[ent(i, i, (i,)) for i in range(5)]
    )
    return mk(), mk()


class TestCompareMetricVectors:
    """Ported from MetricVectorUtilsTest.compareMetricVectors
    (UtilTest.cpp:780-838)."""

    def test_empty_vectors_tie(self):
        assert (
            compare_metric_vectors(MetricVector(), MetricVector())
            == CompareResult.TIE
        )

    def test_version_mismatch_error(self):
        assert (
            compare_metric_vectors(
                MetricVector(version=1), MetricVector(version=2)
            )
            == CompareResult.ERROR
        )

    def test_equal_vectors_tie(self):
        l, r = five_metrics()
        assert compare_metric_vectors(l, r) == CompareResult.TIE

    def test_higher_metric_wins(self):
        l, r = five_metrics()
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        assert compare_metric_vectors(l, r) == CompareResult.WINNER
        assert compare_metric_vectors(r, l) == CompareResult.LOOSER

    def test_tie_breaker_flag_mismatch_error(self):
        l, r = five_metrics()
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        r.metrics[3].is_best_path_tie_breaker = True
        assert compare_metric_vectors(l, r) == CompareResult.ERROR

    def test_tie_breaker_produces_tie_winner(self):
        l, r = five_metrics()
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        r.metrics[3].is_best_path_tie_breaker = True
        l.metrics[3].is_best_path_tie_breaker = True
        assert compare_metric_vectors(l, r) == CompareResult.TIE_WINNER
        assert compare_metric_vectors(r, l) == CompareResult.TIE_LOOSER

    def test_loner_win_if_present(self):
        # UtilTest.cpp:818-820: r loses its LOWEST-priority entity (the
        # reference resize() happens after the in-place priority sort),
        # keeping the p3 tie-breaker divergence: l's p0 loner is
        # WIN_IF_PRESENT and decisively overrides the TIE_WINNER
        l, r = five_metrics()
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        r.metrics[3].is_best_path_tie_breaker = True
        l.metrics[3].is_best_path_tie_breaker = True
        r.metrics = r.metrics[1:]  # drop priority-0 entity
        assert compare_metric_vectors(l, r) == CompareResult.WINNER
        assert compare_metric_vectors(r, l) == CompareResult.LOOSER

    def test_same_priority_different_type_error(self):
        # UtilTest.cpp:822-826: the HIGHEST-priority entity's type is
        # changed — same priority, different type is not comparable
        l, r = five_metrics()
        l.metrics[4].type = 99
        assert compare_metric_vectors(l, r) == CompareResult.ERROR
        assert compare_metric_vectors(r, l) == CompareResult.ERROR

    def test_loner_win_if_not_present(self):
        # UtilTest.cpp:828-832: l's lowest-priority loner flips to
        # WIN_IF_NOT_PRESENT — possessing it now LOSES
        l, r = five_metrics()
        r.metrics[3].is_best_path_tie_breaker = True
        l.metrics[3].is_best_path_tie_breaker = True
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        r.metrics = r.metrics[1:]
        l.metrics[0].op = CompareType.WIN_IF_NOT_PRESENT
        assert compare_metric_vectors(l, r) == CompareResult.LOOSER
        assert compare_metric_vectors(r, l) == CompareResult.WINNER

    def test_loner_ignore_falls_through_to_tie_breaker(self):
        # UtilTest.cpp:834-837: an IGNORE_IF_NOT_PRESENT loner is
        # transparent, so the earlier TIE_WINNER from the p3 tie-breaker
        # carries the result
        l, r = five_metrics()
        r.metrics[3].is_best_path_tie_breaker = True
        l.metrics[3].is_best_path_tie_breaker = True
        r.metrics[3].metric = (r.metrics[3].metric[0] - 1,)
        r.metrics = r.metrics[1:]
        l.metrics[0].op = CompareType.IGNORE_IF_NOT_PRESENT
        assert compare_metric_vectors(l, r) == CompareResult.TIE_WINNER
        assert compare_metric_vectors(r, l) == CompareResult.TIE_LOOSER

    def test_metric_length_mismatch_error(self):
        assert (
            compare_metrics((1, 2), (1,), tie_breaker=False)
            == CompareResult.ERROR
        )

    def test_negate_and_decisive(self):
        assert negate(CompareResult.WINNER) == CompareResult.LOOSER
        assert negate(CompareResult.TIE_WINNER) == CompareResult.TIE_LOOSER
        assert negate(CompareResult.TIE) == CompareResult.TIE
        assert negate(CompareResult.ERROR) == CompareResult.ERROR
        assert is_decisive(CompareResult.WINNER)
        assert is_decisive(CompareResult.ERROR)
        assert not is_decisive(CompareResult.TIE_WINNER)
        assert not is_decisive(CompareResult.TIE)

    def test_unsorted_vectors_are_sorted_by_priority(self):
        # entities listed low-priority-first must still compare by
        # decreasing priority (sortMetricVector, Util.cpp:989)
        l = MetricVector(
            version=1,
            metrics=[ent(0, 100, (1,)), ent(1, 900, (7,))],
        )
        r = MetricVector(
            version=1,
            metrics=[ent(1, 900, (7,)), ent(0, 100, (0,))],
        )
        assert compare_metric_vectors(l, r) == CompareResult.WINNER

    def test_result_for_loner(self):
        e = ent(0, 0, (), op=CompareType.WIN_IF_PRESENT)
        assert result_for_loner(e) == CompareResult.WINNER
        e.is_best_path_tie_breaker = True
        assert result_for_loner(e) == CompareResult.TIE_WINNER
        e.op = CompareType.WIN_IF_NOT_PRESENT
        assert result_for_loner(e) == CompareResult.TIE_LOOSER
        e.is_best_path_tie_breaker = False
        assert result_for_loner(e) == CompareResult.LOOSER
        e.op = CompareType.IGNORE_IF_NOT_PRESENT
        assert result_for_loner(e) == CompareResult.TIE


PFX = normalize_prefix("fc00:b::/64")


def line3() -> LinkState:
    """1 -- 2 -- 3 (metric 10)."""
    return build_link_state(
        {
            "1": [adj("1", "2")],
            "2": [adj("2", "1"), adj("2", "3")],
            "3": [adj("3", "2")],
        }
    )


def mv_local_pref(pref: int, tie_break_ip: int = 0) -> MetricVector:
    """LOCAL_PREFERENCE-style entity + optional ROUTER_ID tie-breaker."""
    metrics = [
        ent(0, 9000, (pref,), op=CompareType.WIN_IF_PRESENT)
    ]
    if tie_break_ip:
        metrics.append(
            ent(
                6,
                3000,
                (tie_break_ip,),
                op=CompareType.WIN_IF_PRESENT,
                tie_breaker=True,
            )
        )
    return MetricVector(version=1, metrics=metrics)


def bgp_entry(mv: MetricVector | None) -> PrefixEntry:
    return PrefixEntry(prefix=PFX, type=PrefixType.BGP, mv=mv)


class TestSolverBgpSelection:
    """Solver-level BGP selection (DecisionTest.cpp:795-870 scenarios)."""

    def _routes(self, solver_node: str, entries: dict[str, PrefixEntry]):
        ls = line3()
        ps = PrefixState()
        for node, entry in entries.items():
            ps.update_prefix(node, "0", entry)
        solver = SpfSolver(solver_node)
        rdb = solver.build_route_db({"0": ls}, ps)
        return rdb.unicast_routes

    def test_single_advertiser_wins(self):
        routes = self._routes("2", {"1": bgp_entry(mv_local_pref(100))})
        assert PFX in routes
        assert {nh.address for nh in routes[PFX].nexthops} == {"fe80::1"}

    def test_better_vector_wins(self):
        routes = self._routes(
            "2",
            {
                "1": bgp_entry(mv_local_pref(100)),
                "3": bgp_entry(mv_local_pref(200)),
            },
        )
        assert PFX in routes
        assert {nh.address for nh in routes[PFX].nexthops} == {"fe80::3"}

    def test_identical_vectors_tie_skips_route(self):
        # "both nodes have same metric vector: we can't determine a best
        # path" — the reference drops the route (DecisionTest.cpp:849-861)
        routes = self._routes(
            "2",
            {
                "1": bgp_entry(mv_local_pref(100)),
                "3": bgp_entry(mv_local_pref(100)),
            },
        )
        assert PFX not in routes

    def test_tie_breaker_keeps_ecmp_set(self):
        # equal primary metric, ROUTER_ID tie-breaker: node 3 is best but
        # node 1 stays in the multipath set (TIE_LOOSER semantics)
        routes = self._routes(
            "2",
            {
                "1": bgp_entry(mv_local_pref(100, tie_break_ip=1)),
                "3": bgp_entry(mv_local_pref(100, tie_break_ip=3)),
            },
        )
        assert PFX in routes
        assert {nh.address for nh in routes[PFX].nexthops} == {
            "fe80::1",
            "fe80::3",
        }
        assert routes[PFX].best_prefix_entry is not None

    def test_version_mismatch_skips_route(self):
        worse = mv_local_pref(100)
        worse.version = 2
        routes = self._routes(
            "2",
            {"1": bgp_entry(mv_local_pref(100)), "3": bgp_entry(worse)},
        )
        assert PFX not in routes

    def test_no_vectors_falls_back_to_prefix_metrics(self):
        # our PrefixEntry always carries PrefixMetrics; BGP entries with
        # no mv anywhere use the ordered-metrics compare (documented
        # deviation — the reference would throw on the unset optional)
        routes = self._routes(
            "2", {"1": bgp_entry(None), "3": bgp_entry(None)}
        )
        assert PFX in routes
        assert {nh.address for nh in routes[PFX].nexthops} == {
            "fe80::1",
            "fe80::3",
        }

    def test_mixed_mv_and_no_mv_skips_route(self):
        routes = self._routes(
            "2",
            {"1": bgp_entry(mv_local_pref(100)), "3": bgp_entry(None)},
        )
        assert PFX not in routes

    def test_winner_resets_prior_ties(self):
        # two tied entries joined the set, then a strict winner arrives:
        # the set must collapse to the winner only (WINNER clears
        # allNodeAreas, Decision.cpp:879-880)
        ls = build_link_state(
            {
                "1": [adj("1", "4")],
                "2": [adj("2", "4")],
                "3": [adj("3", "4")],
                "4": [adj("4", "1"), adj("4", "2"), adj("4", "3")],
            }
        )
        ps = PrefixState()
        ps.update_prefix("1", "0", bgp_entry(mv_local_pref(100, 1)))
        ps.update_prefix("2", "0", bgp_entry(mv_local_pref(100, 2)))
        ps.update_prefix("3", "0", bgp_entry(mv_local_pref(200, 3)))
        solver = SpfSolver("4")
        rdb = solver.build_route_db({"0": ls}, ps)
        assert PFX in rdb.unicast_routes
        assert {nh.address for nh in rdb.unicast_routes[PFX].nexthops} == {
            "fe80::3"
        }

    def test_serializer_roundtrip(self):
        from openr_tpu.serializer import dumps, loads

        entry = bgp_entry(mv_local_pref(100, 7))
        raw = dumps(entry)
        back = loads(raw, PrefixEntry)
        assert back == entry
        assert dumps(back) == raw
