"""Replica-fleet chaos acceptance: kill/restart/partition a K-replica
serving fleet under open-loop load and prove the router never lies.

The acceptance bar (mirrors ISSUE/ROADMAP):

- zero silent drops: every submitted query resolves (accounted ==
  submitted) even across a mid-burst replica kill;
- bit-exactness: every answer matches the host Dijkstra oracle at the
  epoch stamped on the reply — a replica may serve a *lagged* epoch,
  never a *wrong* answer for the epoch it claims;
- epoch pinning: per-session pins only move forward, and a stale reply
  re-routes instead of being delivered;
- ledger: serving.router.* counters reconcile exactly against the
  LoadReport (every re-dispatch is a retry, hedge, failover, or
  epoch re-route — nothing dispatches unaccounted);
- replay: the same seed replays a ChaosEventLog-identical scripted
  event stream.

Seed override knob (same pattern as OPENR_OCS_SEED):
`OPENR_REPLICAFLEET_SEED=<n> pytest tests/test_replicafleet.py` replays
a failing seed deterministically.
"""

from __future__ import annotations

import os

import pytest

from openr_tpu.chaos import ChaosEventLog, ReplicaFleetController
from openr_tpu.decision.spf_solver import DeviceSpfBackend

pytestmark = pytest.mark.chaos

_SEED = int(os.environ.get("OPENR_REPLICAFLEET_SEED", "7"))
_DEVICE = dict(min_device_nodes=1, min_device_sources=1)


def _run_fleet(seed: int, log_: ChaosEventLog | None = None):
    # One shared device backend across every replica *and* the truth
    # instance: DeviceSpfBackend mirrors per LinkState object, so the
    # replicas stay isolated while the jit cache is paid once.
    backend = DeviceSpfBackend(**_DEVICE)
    ctl = ReplicaFleetController(
        seed=seed,
        n=12,
        replicas=3,
        rounds=8,
        clients=8,
        per_client=7,
        spf_backend=backend,
        log_=log_,
    )
    return ctl, ctl.run()


class TestReplicaFleetChaos:
    """One fleet run, asserted from every acceptance angle.  The run is
    shared via a class-scoped fixture: the scenario is the expensive
    part, the assertions are free."""

    @pytest.fixture(scope="class")
    def fleet(self, cpu_burner):
        log_ = ChaosEventLog()
        ctl, result = _run_fleet(_SEED, log_=log_)
        return ctl, result, log_

    def test_open_loop_volume_meets_acceptance_floor(self, fleet):
        _, result, _ = fleet
        assert result.submitted >= 400

    def test_zero_silent_drops(self, fleet):
        _, result, _ = fleet
        assert result.accounted == result.submitted, (
            f"silent drops: submitted={result.submitted} "
            f"accounted={result.accounted}"
        )

    def test_every_answer_bit_exact_at_its_pinned_epoch(self, fleet):
        _, result, _ = fleet
        assert result.unknown_epochs == 0
        assert result.bit_exact, result.mismatches[:5]

    def test_lagged_epochs_were_actually_served(self, fleet):
        # the bit-exactness claim is vacuous if every reply came from
        # the head epoch — prove the fleet really served lagged ones
        _, result, _ = fleet
        assert len(result.epochs_served) >= 2, result.epochs_served

    def test_session_pins_monotonic(self, fleet):
        _, result, _ = fleet
        assert result.pin_violations == 0

    def test_counter_ledger_reconciles_with_load_report(self, fleet):
        _, result, _ = fleet
        c = result.counters
        redispatches = (
            c["serving.router.retries"]
            + c["serving.router.hedges"]
            + c["serving.router.failovers"]
            + c["serving.router.epoch_reroutes"]
        )
        assert result.ledger_ok
        assert c["serving.router.dispatches"] == (
            result.submitted - c["serving.router.sheds"]
        ) + redispatches, c

    def test_faults_actually_fired(self, fleet):
        # the run is worthless if the chaos was a no-op: the kill must
        # have produced failovers and a death, the probe path must have
        # seen the downed replica, and the lag segment must have forced
        # at least one stale-reply re-route
        _, result, _ = fleet
        c = result.counters
        assert c["serving.router.failovers"] >= 1
        assert c["serving.router.replica_deaths"] >= 1
        assert c["serving.router.probe_failures"] >= 1
        assert c["serving.router.epoch_reroutes"] >= 1

    def test_same_seed_replays_identical_event_log(self, fleet):
        _, _first, log1 = fleet
        log2 = ChaosEventLog()
        _, second = _run_fleet(_SEED, log_=log2)
        # the scripted event log IS the replay contract; submit/reply
        # totals include the pin segment's march-until-caught-up
        # queries, which are timing-dependent on a loaded box and
        # deliberately not logged (see chaos/replicafleet.py docstring)
        assert log1.matches(log2)
        assert second.accounted == second.submitted
        assert second.bit_exact
        assert second.ledger_ok
        assert second.pin_violations == 0


class TestElasticFleetChaos:
    """Elastic-membership chaos: a snapshot-warm-started replica joins
    mid-burst and the youngest joined replica is removed and killed under
    load, with the scripted log (including the seed-deterministic restore
    mode) replaying identically.  Per-replica device backends on purpose:
    a shared backend would make the warm start vacuous."""

    def _run(self, seed: int, log_: ChaosEventLog):
        ctl = ReplicaFleetController(
            seed=seed,
            n=12,
            replicas=2,
            rounds=4,
            clients=4,
            per_client=5,
            kill_round=-1,
            restart_round=-1,
            partition_round=-1,
            heal_round=-1,
            lag_rounds=(),
            scaleout_round=1,
            scalein_round=3,
            spf_backend=None,
            log_=log_,
        )
        return ctl, ctl.run()

    @pytest.fixture(scope="class")
    def elastic(self, cpu_burner):
        log_ = ChaosEventLog()
        ctl, result = self._run(_SEED, log_)
        return ctl, result, log_

    def test_membership_chaos_keeps_the_acceptance_bar(self, elastic):
        _, result, _ = elastic
        assert result.accounted == result.submitted
        assert result.bit_exact
        assert result.ledger_ok
        assert result.pin_violations == 0

    def test_scale_events_are_in_the_replay_contract(self, elastic):
        _, _, log_ = elastic
        steps = [
            s
            for entries in log_._streams.values()
            for s in entries
            if "fleet:scale" in str(s)
        ]
        # the join really warm-started (install/replay, not a cold or
        # skipped fallback) and the scale-in removed the joined replica
        assert any(
            s.endswith(":install") or s.endswith(":replay") for s in steps
        ), steps
        assert any("fleet:scalein:replica-" in s for s in steps), steps

    def test_same_seed_replays_identical_scale_log(self, elastic):
        _, _, log1 = elastic
        log2 = ChaosEventLog()
        _, second = self._run(_SEED, log2)
        assert log1.matches(log2)
        assert second.accounted == second.submitted
        assert second.bit_exact
        assert second.ledger_ok


class TestServingFleetWiring:
    """End-to-end over real daemons: main.ServingFleet brings up K full
    stacks peered over the KvStore full-mesh, and the front-door ctrl
    handler's query methods ride the router."""

    @pytest.fixture
    def fleet2(self):
        from openr_tpu.main import ServingFleet

        fleet = ServingFleet(2)
        fleet.start()
        try:
            assert fleet.wait_converged(30), "fleet never converged"
            yield fleet
        finally:
            fleet.stop()

    def _call(self, fleet, method, **p):
        import asyncio

        return asyncio.run(fleet.handler.async_methods[method](p))

    def test_front_door_spreads_and_pins(self, fleet2):
        reply = None
        for _ in range(4):
            reply = self._call(
                fleet2, "queryPaths", sources=["fleet-0"], session="cli"
            )
            spf = reply["result"]["fleet-0"]
            assert spf["fleet-1"]["nextHops"] == ["fleet-1"]
        # the wire session id reached the router and pinned the epoch
        assert fleet2.router.session_pin("cli") == reply["epoch"]
        # round-robin: both replicas admitted some of the four queries
        admitted = [
            d.serving.get_counters()["serving.admitted"]
            for d in fleet2.daemons
        ]
        assert all(a >= 1 for a in admitted), admitted
        c = fleet2.router.get_counters()
        assert c["serving.router.dispatches"] >= 4
        # front-door getCounters exposes the router family
        assert "serving.router.dispatches" in fleet2.handler._all_counters()

    def test_front_door_ksp_and_what_if_ride_the_router(self, fleet2):
        kreply = self._call(
            fleet2, "queryKsp", sources=["fleet-0"], dests=["fleet-1"], k=1
        )
        paths = kreply["result"]["fleet-1"]
        assert len(paths) == 1
        assert set(paths[0][0]) == {"fleet-0", "fleet-1"}
        wreply = self._call(
            fleet2,
            "queryWhatIf",
            sources=["fleet-0"],
            scenarios=[[["fleet-0", "fleet-1"]]],
        )
        row = wreply["result"][0]
        assert row["newly_unreachable_pairs"] == 1

    def test_replica_kill_is_transparent_to_the_front_door(self, fleet2):
        assert self._call(fleet2, "queryPaths", sources=["fleet-0"])
        # stop one replica's scheduler: in-daemon queries now shed, the
        # router must re-route without surfacing an error
        fleet2.daemons[1].serving.stop()
        for _ in range(3):
            reply = self._call(fleet2, "queryPaths", sources=["fleet-0"])
            assert reply["result"]["fleet-0"]["fleet-1"]["metric"] == 1
        c = fleet2.router.get_counters()
        assert c["serving.router.retries"] + c["serving.router.failovers"] >= 1


def test_different_seed_diverges_scripted_stream(cpu_burner):
    # tiny fleets are enough to show the log is seed-determined
    log1, log2 = ChaosEventLog(), ChaosEventLog()
    backend = DeviceSpfBackend(**_DEVICE)
    ReplicaFleetController(
        seed=1, n=8, replicas=2, rounds=4, clients=2, per_client=3,
        spf_backend=backend, log_=log1,
    ).run()
    ReplicaFleetController(
        seed=2, n=8, replicas=2, rounds=4, clients=2, per_client=3,
        spf_backend=backend, log_=log2,
    ).run()
    assert not log1.matches(log2)
