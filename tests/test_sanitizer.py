"""Runtime dispatch sanitizer (openr_tpu/device/sanitizer.py).

Three properties, each proven in both directions:

- the engine's REAL dispatch paths (cold compile aside) are clean under
  ``jax.transfer_guard("disallow")`` — every host array reaches the
  device through the engine's explicit, byte-accounted ``device_put``
  staging, including the incremental masked-write sync;
- the guard CATCHES a seeded violation: removing the explicit staging
  (patching ``device_put`` to the identity, the exact refactor accident
  the sanitizer exists for) makes a host array hit a compiled program as
  an implicit transfer, and the block fails as SanitizerViolation;
- after warmup, steady-state queries stay within a zero-compile budget,
  and a query that silently lands on a new bucket key inside the budget
  block is caught.

CPU-CI caveat (see sanitizer docstring): the guard enforces the implicit
host->device direction only; device->host reads are zero-copy on CPU and
pass.  That is exactly the direction the engine's staging discipline
owns, so the check is meaningful on CPU and strictly stronger on real
accelerators.
"""

from __future__ import annotations

import numpy as np
import pytest

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.device import (
    DeviceResidencyEngine,
    EngineSanitizer,
    SanitizerViolation,
)
from openr_tpu.utils.topo import grid_topology

from test_link_state import build


@pytest.fixture()
def warm():
    """Engine warmed on the 1- and 8-source buckets of a 16-node grid,
    with one attribute flap pending so the sanitized query exercises the
    incremental masked-write sync (the dispatch path with the most
    host->device traffic)."""
    dbs = grid_topology(4)
    ls = build(dbs)
    csr = CsrTopology.from_link_state(ls)
    engine = DeviceResidencyEngine()
    names = ls.node_names
    engine.spf_results(csr, names[:1])  # compile bucket 1
    engine.spf_results(csr, names[:3])  # compile bucket 8
    # pending attribute flap: metric write syncs on device at next query
    dbs[0].adjacencies[0].metric = 37
    ls.update_adjacency_database(dbs[0])
    assert csr.refresh(ls) is True
    return engine, csr, ls, names


def _oracle_check(engine, csr, ls, sources):
    got = engine.spf_results(csr, sources)
    for src in sources:
        oracle = ls.run_spf(src)
        assert {k: v.metric for k, v in oracle.items()} == {
            k: v.metric for k, v in got[src].items()
        }, src


class TestTransferGuard:
    def test_real_dispatch_paths_are_clean(self, warm):
        """Incremental sync + warm queries under the guard, bit-exact."""
        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        with san.sanitized():
            _oracle_check(engine, csr, ls, names[:1])  # syncs the flap
            _oracle_check(engine, csr, ls, names[:3])
        c = engine.get_counters()
        assert c["device.engine.incremental_updates"] == 1

    def test_seeded_h2d_violation_is_caught(self, warm, monkeypatch):
        """Drop the explicit device_put staging (the seeded bug): the
        same dispatch now leaks host arrays into compiled programs and
        the guard must fail the block."""
        import openr_tpu.device.engine as engine_mod

        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        monkeypatch.setattr(
            engine_mod.jax, "device_put", lambda x, *a, **kw: x
        )
        with pytest.raises(SanitizerViolation, match="implicit"):
            with san.transfer_guard():
                engine.spf_results(csr, names[:1])

    def test_unrelated_errors_pass_through(self, warm):
        """Only guard trips translate; other failures keep their type."""
        engine, *_ = warm
        san = EngineSanitizer(engine)
        with pytest.raises(ValueError, match="unrelated"):
            with san.transfer_guard():
                raise ValueError("unrelated")


class TestCompileBudget:
    def test_steady_state_is_hit_only(self, warm):
        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        with san.compile_budget(0):
            engine.spf_results(csr, names[:1])
            engine.spf_results(csr, names[:2])  # same 8-bucket, still a hit

    def test_seeded_new_bucket_compile_is_caught(self, warm):
        """A steady-state block that silently crosses into an uncompiled
        bucket (here: 9 sources -> the 64 bucket) must fail the budget."""
        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        with pytest.raises(SanitizerViolation, match="compiled 1 program"):
            with san.compile_budget(0):
                engine.spf_results(csr, names[:9])

    def test_budget_allows_declared_compiles(self, warm):
        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        with san.compile_budget(1):
            engine.spf_results(csr, names[:9])


class TestWiredIntoDispatch:
    def test_sanitized_composes_guard_and_budget(self, warm):
        engine, csr, ls, names = warm
        san = EngineSanitizer(engine)
        with pytest.raises(SanitizerViolation):
            with san.sanitized(allowed_compiles=0):
                engine.spf_results(csr, names[:9])
        # np.asarray-style reads of device results stay allowed (CPU D2H
        # is zero-copy; the guard owns the H2D direction)
        res = engine.spf_results(csr, names[:1])
        with san.transfer_guard():
            arr = np.asarray(
                [r.metric for r in res[names[0]].values()], dtype=np.int64
            )
        assert arr.size > 0
