"""Acceptance gate for the device-residency engine (openr_tpu/device).

The scripted 25-flap sequence drives one LinkState through metric raises
and restores, node-overload set/clear and link-overload set/clear — both
directions of every knob — while querying the engine at source-set sizes
that cross shape-bucket boundaries.  Every step is asserted bit-exact
against the host Dijkstra oracle (LinkState.run_spf), and the counters
must prove the residency contract:

- ``full_restages == 1``: the graph is uploaded once, at first contact;
  all 25 flaps thereafter sync incrementally on device;
- bucket changes force >= 1 recompile of an evicted key, and the small
  ``max_programs`` budget forces >= 1 eviction;
- per-query staged bytes stay O(sources + changed slots), never O(graph)
  (the recorded attribution is the CPU-CI stand-in for the wan-scale
  device_vs_host wall claim; see docs/OPERATIONS.md).
"""

from __future__ import annotations

import pytest

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.device import (
    ENGINE_COUNTER_KEYS,
    S_BUCKETS,
    DeviceResidencyEngine,
    EngineSanitizer,
)
from openr_tpu.utils.topo import grid_topology

from test_link_state import build


def _assert_oracle(engine, csr, ls, sources):
    got = engine.spf_results(csr, sources)
    assert set(got) == set(sources)
    for src in sources:
        oracle = ls.run_spf(src)
        res = got[src]
        assert {k: v.metric for k, v in oracle.items()} == {
            k: v.metric for k, v in res.items()
        }, src
        for n in oracle:
            assert oracle[n].next_hops == res[n].next_hops, (src, n)


def _flap_script(dbs):
    """25 attribute-only mutations: (db, kind, link, value) tuples.

    Attribute-only is load-bearing: none of these change the edge set, so
    csr.refresh stays in place and the engine must absorb every one of
    them as an incremental device update (full_restages frozen at 1).
    """
    muts = []
    # metric raise + restore on six distinct directed links
    for i in range(6):
        db = dbs[2 * i]
        lnk = db.adjacencies[0]
        muts.append((db, "metric", lnk, 40 + 10 * i))
        muts.append((db, "metric", lnk, 10))
    # node overload set + clear on four distinct nodes
    for i in range(4):
        db = dbs[3 * i + 1]
        muts.append((db, "node_overload", None, True))
        muts.append((db, "node_overload", None, False))
    # link overload (soft link-down) set + clear on two links
    for i in range(2):
        db = dbs[5 * i + 2]
        lnk = db.adjacencies[-1]
        muts.append((db, "link_overload", lnk, True))
        muts.append((db, "link_overload", lnk, False))
    # one unrestored metric change so the sequence ends off-baseline
    muts.append((dbs[7], "metric", dbs[7].adjacencies[1], 33))
    assert len(muts) == 25
    return muts


class TestTwentyFiveFlapSequence:
    def test_bit_exact_with_incremental_residency(self):
        dbs = grid_topology(5)  # 25 nodes, node_capacity 32
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        names = ls.node_names
        # max_programs=2 with three source buckets in rotation: the third
        # key always evicts one of the other two, so the next rotation
        # recompiles it — the eviction/recompile half of the acceptance
        engine = DeviceResidencyEngine(max_programs=2)

        # first contact: the one and only full staging
        _assert_oracle(engine, csr, ls, [names[0]])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 1
        initial_bytes = c["device.engine.bytes_staged"]
        assert initial_bytes > 0

        # every post-warmup dispatch runs under the transfer sanitizer:
        # all host->device traffic in the flap loop must go through the
        # engine's explicit device_put staging (sanitizer.py; compiles
        # are legitimate here — the bucket rotation forces evictions)
        san = EngineSanitizer(engine)
        attribution = []  # (flap index, staged bytes, query us)
        for i, (db, kind, lnk, val) in enumerate(_flap_script(dbs)):
            if kind == "metric":
                lnk.metric = val
            elif kind == "node_overload":
                db.is_overloaded = val
            else:
                lnk.is_overloaded = val
            ls.update_adjacency_database(db)
            assert csr.refresh(ls) is True, (i, kind)  # stayed in place
            # rotate source-set sizes across the 1 / 8 / 64 buckets
            size = (1, 5, 25)[i % 3]
            start = i % len(names)
            sources = (names + names)[start : start + size]
            with san.transfer_guard():
                _assert_oracle(engine, csr, ls, sources)
            attribution.append(
                (i, engine.last_query_bytes, engine.last_query_us)
            )

        c = engine.get_counters()
        # the residency contract: one upload, then 25 incremental syncs
        assert c["device.engine.full_restages"] == 1
        assert c["device.engine.incremental_updates"] == 25
        assert c["device.engine.queries"] == 26
        # three bucket keys under a two-program budget
        assert len(engine.cached_program_keys()) <= 2
        assert c["device.engine.evictions"] >= 1
        assert c["device.engine.compiles"] >= 4  # >=1 key compiled twice
        assert c["device.engine.bucket_misses"] == c["device.engine.compiles"]
        assert (
            c["device.engine.bucket_hits"]
            == c["device.engine.queries"] - c["device.engine.compiles"]
        )
        # per-query attribution: every warm query stages O(sources +
        # changed slots) bytes, nowhere near the initial graph upload
        worst = max(b for _, b, _us in attribution)
        assert worst < initial_bytes / 4, (worst, initial_bytes)
        assert all(us >= 0 for _, _b, us in attribution)

    def test_counters_pre_seeded_and_registry_shaped(self):
        engine = DeviceResidencyEngine()
        c = engine.get_counters()
        assert set(ENGINE_COUNTER_KEYS) <= set(c)
        assert all(v == 0 for v in c.values())
        assert all(k.startswith("device.engine.") for k in c)

    def test_bucket_ladder_is_monotone(self):
        assert S_BUCKETS == (1, 8, 64, 512)


class TestResidencyIdentity:
    def test_edge_set_change_forces_restage(self):
        """A rebuild (new ELL identity) is the one legitimate second
        upload; attribute flaps before and after stay incremental."""
        dbs = grid_topology(4)
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        assert engine.has_residency(csr) and engine.is_warm(csr)

        # attribute flap: incremental
        dbs[0].adjacencies[0].metric = 25
        ls.update_adjacency_database(dbs[0])
        assert csr.refresh(ls) is True
        assert engine.has_residency(csr) and not engine.is_warm(csr)
        _assert_oracle(engine, csr, ls, ls.node_names[:2])

        # edge-set change: rebuild -> new ell -> full restage
        dbs[1].adjacencies = [
            a
            for a in dbs[1].adjacencies
            if a.other_node_name != dbs[1].adjacencies[-1].other_node_name
        ]
        ls.update_adjacency_database(dbs[1])
        assert csr.refresh(ls) is False  # rebuilt
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 2
        assert c["device.engine.incremental_updates"] == 1

    def test_drop_releases_residency(self):
        ls = build(grid_topology(3))
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        engine.spf_results(csr, ls.node_names[:1])
        assert engine.has_residency(csr)
        engine.drop(csr)
        assert not engine.has_residency(csr)
        engine.spf_results(csr, ls.node_names[:1])
        assert engine.get_counters()["device.engine.full_restages"] == 2
