"""Acceptance gate for the device-residency engine (openr_tpu/device).

The scripted 25-flap sequence drives one LinkState through metric raises
and restores, node-overload set/clear and link-overload set/clear — both
directions of every knob — while querying the engine at source-set sizes
that cross shape-bucket boundaries.  Every step is asserted bit-exact
against the host Dijkstra oracle (LinkState.run_spf), and the counters
must prove the residency contract:

- ``full_restages == 1``: the graph is uploaded once, at first contact;
  all 25 flaps thereafter sync incrementally on device;
- bucket changes force >= 1 recompile of an evicted key, and the small
  ``max_programs`` budget forces >= 1 eviction;
- per-query staged bytes stay O(sources + changed slots), never O(graph)
  (the recorded attribution is the CPU-CI stand-in for the wan-scale
  device_vs_host wall claim; see docs/OPERATIONS.md).
"""

from __future__ import annotations

import pytest

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.device import (
    ENGINE_COUNTER_KEYS,
    S_BUCKETS,
    DeviceResidencyEngine,
    EngineSanitizer,
)
from openr_tpu.utils.topo import grid_topology

from test_link_state import build


def _assert_oracle(engine, csr, ls, sources):
    got = engine.spf_results(csr, sources)
    assert set(got) == set(sources)
    for src in sources:
        oracle = ls.run_spf(src)
        res = got[src]
        assert {k: v.metric for k, v in oracle.items()} == {
            k: v.metric for k, v in res.items()
        }, src
        for n in oracle:
            assert oracle[n].next_hops == res[n].next_hops, (src, n)


def _flap_script(dbs):
    """25 attribute-only mutations: (db, kind, link, value) tuples.

    Attribute-only is load-bearing: none of these change the edge set, so
    csr.refresh stays in place and the engine must absorb every one of
    them as an incremental device update (full_restages frozen at 1).
    """
    muts = []
    # metric raise + restore on six distinct directed links
    for i in range(6):
        db = dbs[2 * i]
        lnk = db.adjacencies[0]
        muts.append((db, "metric", lnk, 40 + 10 * i))
        muts.append((db, "metric", lnk, 10))
    # node overload set + clear on four distinct nodes
    for i in range(4):
        db = dbs[3 * i + 1]
        muts.append((db, "node_overload", None, True))
        muts.append((db, "node_overload", None, False))
    # link overload (soft link-down) set + clear on two links
    for i in range(2):
        db = dbs[5 * i + 2]
        lnk = db.adjacencies[-1]
        muts.append((db, "link_overload", lnk, True))
        muts.append((db, "link_overload", lnk, False))
    # one unrestored metric change so the sequence ends off-baseline
    muts.append((dbs[7], "metric", dbs[7].adjacencies[1], 33))
    assert len(muts) == 25
    return muts


class TestTwentyFiveFlapSequence:
    def test_bit_exact_with_incremental_residency(self):
        dbs = grid_topology(5)  # 25 nodes, node_capacity 32
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        names = ls.node_names
        # max_programs=2 with three source buckets in rotation: the third
        # key always evicts one of the other two, so the next rotation
        # recompiles it — the eviction/recompile half of the acceptance
        engine = DeviceResidencyEngine(max_programs=2)

        # first contact: the one and only full staging
        _assert_oracle(engine, csr, ls, [names[0]])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 1
        initial_bytes = c["device.engine.bytes_staged"]
        assert initial_bytes > 0

        # every post-warmup dispatch runs under the transfer sanitizer:
        # all host->device traffic in the flap loop must go through the
        # engine's explicit device_put staging (sanitizer.py; compiles
        # are legitimate here — the bucket rotation forces evictions)
        san = EngineSanitizer(engine)
        attribution = []  # (flap index, staged bytes, query us)
        for i, (db, kind, lnk, val) in enumerate(_flap_script(dbs)):
            if kind == "metric":
                lnk.metric = val
            elif kind == "node_overload":
                db.is_overloaded = val
            else:
                lnk.is_overloaded = val
            ls.update_adjacency_database(db)
            assert csr.refresh(ls) is True, (i, kind)  # stayed in place
            # rotate source-set sizes across the 1 / 8 / 64 buckets
            size = (1, 5, 25)[i % 3]
            start = i % len(names)
            sources = (names + names)[start : start + size]
            with san.transfer_guard():
                _assert_oracle(engine, csr, ls, sources)
            attribution.append(
                (i, engine.last_query_bytes, engine.last_query_us)
            )

        c = engine.get_counters()
        # the residency contract: one upload, then 25 incremental syncs
        assert c["device.engine.full_restages"] == 1
        assert c["device.engine.incremental_updates"] == 25
        assert c["device.engine.queries"] == 26
        # three bucket keys under a two-program budget
        assert len(engine.cached_program_keys()) <= 2
        assert c["device.engine.evictions"] >= 1
        assert c["device.engine.compiles"] >= 4  # >=1 key compiled twice
        assert c["device.engine.bucket_misses"] == c["device.engine.compiles"]
        assert (
            c["device.engine.bucket_hits"]
            == c["device.engine.queries"] - c["device.engine.compiles"]
        )
        # per-query attribution: every warm query stages O(sources +
        # changed slots) bytes, nowhere near the initial graph upload
        worst = max(b for _, b, _us in attribution)
        assert worst < initial_bytes / 4, (worst, initial_bytes)
        assert all(us >= 0 for _, _b, us in attribution)

    def test_counters_pre_seeded_and_registry_shaped(self):
        engine = DeviceResidencyEngine()
        c = engine.get_counters()
        assert set(ENGINE_COUNTER_KEYS) <= set(c)
        assert all(v == 0 for v in c.values())
        assert all(k.startswith("device.engine.") for k in c)

    def test_bucket_ladder_is_monotone(self):
        assert S_BUCKETS == (1, 8, 64, 512)


class TestResidencyIdentity:
    def test_edge_set_change_rides_rewire_rung(self):
        """A bounded edge-set change no longer restages: the slot
        freelist keeps the ELL identity and the engine replays the
        rewire delta on device.  Attribute flaps before and after stay
        incremental."""
        dbs = grid_topology(4)
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        assert engine.has_residency(csr) and engine.is_warm(csr)

        # attribute flap: incremental
        dbs[0].adjacencies[0].metric = 25
        ls.update_adjacency_database(dbs[0])
        assert csr.refresh(ls) is True
        assert engine.has_residency(csr) and not engine.is_warm(csr)
        _assert_oracle(engine, csr, ls, ls.node_names[:2])

        # edge-set change within capacity: rewire in place, same ell ->
        # residency survives, no second upload
        dbs[1].adjacencies = [
            a
            for a in dbs[1].adjacencies
            if a.other_node_name != dbs[1].adjacencies[-1].other_node_name
        ]
        ls.update_adjacency_database(dbs[1])
        assert csr.refresh(ls) is True  # rewired in place
        assert engine.has_residency(csr)
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 1
        assert c["device.engine.rewires"] == 1
        assert c["device.engine.rewire_fallbacks"] == 0
        assert c["device.engine.incremental_updates"] == 1

    def test_node_set_change_forces_restage(self):
        """A rebuild (new ELL identity) is the one legitimate second
        upload: a node joining is out of rewire scope."""
        dbs = grid_topology(4)
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])

        from test_link_state import adj, adj_db

        corner = dbs[0]  # node-0-0
        ls.update_adjacency_database(
            adj_db("newbie", [adj("newbie", corner.this_node_name)])
        )
        corner.adjacencies = corner.adjacencies + [
            adj(corner.this_node_name, "newbie")
        ]
        ls.update_adjacency_database(corner)
        assert csr.refresh(ls) is False  # rebuilt
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 2
        assert c["device.engine.rewires"] == 0

    def test_drop_releases_residency(self):
        ls = build(grid_topology(3))
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        engine.spf_results(csr, ls.node_names[:1])
        assert engine.has_residency(csr)
        engine.drop(csr)
        assert not engine.has_residency(csr)
        engine.spf_results(csr, ls.node_names[:1])
        assert engine.get_counters()["device.engine.full_restages"] == 2


# -- OCS rewire acceptance (ISSUE 11) ---------------------------------------


RING_N = 12


def _ring_dbs(chords):
    """RING_N-node ring plus the given chord set (pairs (i, j), i < j).

    Chords model OCS circuits: the ring is the static fabric, the chord
    set is the reprogrammable logical topology."""
    from test_link_state import adj, adj_db

    def nm(i):
        return f"r{i:02d}"

    adjs = {i: [] for i in range(RING_N)}
    for i in range(RING_N):
        j = (i + 1) % RING_N
        adjs[i].append(adj(nm(i), nm(j)))
        adjs[j].append(adj(nm(j), nm(i)))
    for i, j in sorted(chords):
        m = 1 + (i * 7 + j * 3) % 5
        adjs[i].append(adj(nm(i), nm(j), metric=m))
        adjs[j].append(adj(nm(j), nm(i), metric=m))
    return [adj_db(nm(i), adjs[i]) for i in range(RING_N)]


def _chord_candidates(chords):
    """Legal chords to add: not a ring edge, and no endpoint carrying
    two chords already (keeps in-degree within the build-time ELL row
    headroom, so every step stays a bounded rewire)."""
    deg = {}
    for i, j in chords:
        deg[i] = deg.get(i, 0) + 1
        deg[j] = deg.get(j, 0) + 1
    out = []
    for i in range(RING_N):
        for j in range(i + 2, RING_N):
            if i == 0 and j == RING_N - 1:
                continue  # ring edge
            if (i, j) in chords:
                continue
            if deg.get(i, 0) >= 2 or deg.get(j, 0) >= 2:
                continue
            out.append((i, j))
    return out


def _push_ring(ls, chords):
    for db in _ring_dbs(chords):
        ls.update_adjacency_database(db)


class TestOcsRewireAcceptance:
    """ISSUE 11 acceptance: >= 20 seeded bounded rewires (adds, removes,
    swaps within capacity) keep full_restages == 1, bit-exact against a
    cold host rebuild every step; overflow and mid-rewire faults demote
    cleanly to restage with counters accounted."""

    def _rewire_schedule(self, seed, steps):
        """Deterministic (op, chords-after) schedule starting from the
        4-chord baseline: remove / add / swap in rotation."""
        import random

        rng = random.Random(seed)
        chords = {(0, 5), (2, 8), (3, 9), (4, 10)}
        plan = [set(chords)]
        for step in range(steps):
            op = ("remove", "add", "swap")[step % 3]
            if op == "remove":
                chords.discard(rng.choice(sorted(chords)))
            elif op == "add":
                chords.add(rng.choice(_chord_candidates(chords)))
            else:
                chords.discard(rng.choice(sorted(chords)))
                chords.add(rng.choice(_chord_candidates(chords)))
            plan.append(set(chords))
        return plan

    def test_twenty_bounded_rewires_single_restage(self):
        plan = self._rewire_schedule(seed=1107, steps=20)
        ls = build(_ring_dbs(plan[0]))
        csr = CsrTopology.from_link_state(ls)
        assert csr.edge_capacity == 32  # 24 ring + 8 chord slots: tight
        engine = DeviceResidencyEngine()
        names = ls.node_names
        _assert_oracle(engine, csr, ls, names[:2])
        c0 = engine.get_counters()
        assert c0["device.engine.full_restages"] == 1
        initial_bytes = c0["device.engine.bytes_staged"]

        for step, chords in enumerate(plan[1:]):
            _push_ring(ls, chords)
            assert csr.refresh(ls) is True, (step, chords)  # rewired
            sources = [names[(step * 5 + k) % RING_N] for k in range(3)]
            # engine vs the host Dijkstra oracle
            _assert_oracle(engine, csr, ls, sources)
            # and bit-exact vs a COLD rebuild of the mirror itself
            cold = CsrTopology.from_link_state(ls)
            got = engine.spf_results(csr, sources)
            ref = cold.spf_from(sources)
            for s in sources:
                assert {k: v.metric for k, v in ref[s].items()} == {
                    k: v.metric for k, v in got[s].items()
                }, (step, s)
                for n in ref[s]:
                    assert ref[s][n].next_hops == got[s][n].next_hops

        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 1  # the contract
        assert c["device.engine.rewires"] == 20
        assert c["device.engine.rewire_dispatches"] == 20
        assert c["device.engine.rewire_fallbacks"] == 0
        assert c["device.engine.rewire_slots"] >= 40  # >= 2 slots/rewire
        assert c["device.engine.rewire_rows"] >= 20
        assert c["device.engine.rewire_bytes_staged"] > 0
        # each rewire uploads O(touched slots + rows), bounded by the
        # one-time graph staging even on this toy topology (the scale
        # economics — per-rewire bytes vs a wan-sized restage — are the
        # bench row's claim, see bench.py ocs_rewire_wan100k)
        assert c["device.engine.rewire_bytes_staged"] / 20 < initial_bytes

    def test_capacity_overflow_demotes_to_rebuild_restage(self):
        chords = {(0, 5), (2, 8), (3, 9), (4, 10)}
        ls = build(_ring_dbs(chords))
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])

        # 4 more chords do not fit the 32-slot bucket: the freelist
        # refuses, refresh falls back to a (larger-capacity) rebuild and
        # the engine restages — gracefully, never an error
        chords |= {(1, 6), (5, 11), (2, 7), (6, 10)}
        _push_ring(ls, chords)
        assert csr.refresh(ls) is False  # rebuilt
        assert csr.edge_capacity > 32
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 2
        assert c["device.engine.rewires"] == 0
        assert c["device.engine.rewire_fallbacks"] == 0

    def test_mid_rewire_fault_demotes_to_restage(self):
        chords = {(0, 5), (2, 8), (3, 9)}
        ls = build(_ring_dbs(chords))
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])

        armed = {"n": 0}

        def hook(op):
            if op == "rewire" and armed["n"] == 0:
                armed["n"] = 1
                raise RuntimeError("injected mid-rewire device fault")

        engine.fault_hook = hook
        chords.discard((2, 8))
        chords.add((1, 7))
        _push_ring(ls, chords)
        assert csr.refresh(ls) is True  # host-side rewire fine
        _assert_oracle(engine, csr, ls, ls.node_names[:2])  # still exact
        c = engine.get_counters()
        assert c["device.engine.rewire_fallbacks"] == 1
        assert c["device.engine.full_restages"] == 2  # the demotion
        assert c["device.engine.rewires"] == 0
        # next rewire (fault disarmed) rides the rung again
        chords.discard((1, 7))
        chords.add((1, 6))
        _push_ring(ls, chords)
        assert csr.refresh(ls) is True
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.rewires"] == 1
        assert c["device.engine.full_restages"] == 2

    def test_rewire_log_gap_demotes_to_restage(self):
        """A resident that fell behind the bounded delta window cannot
        replay a contiguous chain — it restages instead of erroring."""
        plan = self._rewire_schedule(seed=22, steps=6)
        ls = build(_ring_dbs(plan[0]))
        csr = CsrTopology.from_link_state(ls)
        csr.REWIRE_LOG_DEPTH = 4  # shrink the window for the test
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        # six rewires with no sync in between: the log only retains 4
        for chords in plan[1:]:
            _push_ring(ls, chords)
            assert csr.refresh(ls) is True
        assert len(csr._rewire_log) == 4
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        c = engine.get_counters()
        assert c["device.engine.rewire_fallbacks"] == 1
        assert c["device.engine.full_restages"] == 2
        assert c["device.engine.rewires"] == 0

    def test_rewire_bumps_epoch_like_a_flap(self):
        """Serving epoch invalidation fires for rewires exactly as for
        flaps: a pinned epoch older than the rewire raises before any
        device work."""
        from openr_tpu.device import EpochMismatchError

        chords = {(0, 5), (2, 8), (3, 9)}
        ls = build(_ring_dbs(chords))
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        _assert_oracle(engine, csr, ls, ls.node_names[:2])
        pinned = int(csr.version)

        chords.discard((0, 5))
        chords.add((1, 7))
        _push_ring(ls, chords)
        assert csr.refresh(ls) is True
        with pytest.raises(EpochMismatchError):
            engine.spf_results(
                csr, ls.node_names[:2], expect_epoch=pinned
            )
        c = engine.get_counters()
        assert c["device.engine.epoch_invalidations"] == 1
        assert c["device.engine.rewires"] == 0  # raised pre-sync
        # fresh pin dispatches normally through the rewire rung
        engine.spf_results(
            csr, ls.node_names[:2], expect_epoch=int(csr.version)
        )
        assert engine.get_counters()["device.engine.rewires"] == 1
