"""Tier-1 gate for the static invariant checker (openr_tpu.analysis).

Two halves:
- the analyzer is correct: fixture files under tests/analysis_fixtures/
  carry seeded violations per rule family, asserted by exact rule id and
  line number (positive + suppressed + clean);
- the tree is clean: the full pass over openr_tpu/ reports zero
  unsuppressed findings, so every future PR is gated on the invariants.

Pure AST — no jax import, no device, fast enough for tier-1.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

from openr_tpu.analysis import AnalysisConfig, load_config, run_analysis

pytestmark = pytest.mark.analysis

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analysis_fixtures"
PACKAGE = REPO_ROOT / "openr_tpu"


def _fixture_findings(*names: str):
    config = AnalysisConfig(
        jit_paths=["tests/analysis_fixtures"],
        # stands in for a parsed OpenrCtrlHandler._all_counters surface
        counter_extra_prefixes=["kvstore", "fib", "queue"],
    )
    targets = [FIXTURES / n for n in names]
    reporter = run_analysis(targets, config, REPO_ROOT)
    return reporter


def _pairs(reporter):
    return sorted((f.rule, f.line) for f in reporter.findings)


class TestJitRules:
    def test_seeded_violations_by_rule_and_line(self):
        rep = _fixture_findings("jit_violations.py")
        assert _pairs(rep) == [
            ("jit-dispatch-sync", 73),
            ("jit-dispatch-sync", 74),
            ("jit-host-sync", 18),
            ("jit-host-sync", 19),
            ("jit-host-sync", 20),
            ("jit-host-sync", 21),
            ("jit-static-hygiene", 43),
            ("jit-static-hygiene", 49),
            ("jit-static-hygiene", 87),
            ("jit-tracer-branch", 28),
            ("jit-tracer-branch", 30),
            ("jit-tracer-branch", 55),
        ]

    def test_suppression_is_honored_and_counted(self):
        rep = _fixture_findings("jit_violations.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("jit-host-sync", 68)
        ]

    def test_interprocedural_propagation(self):
        # line 55 lives in a plain function only reached from a jitted
        # caller; flagging it proves call-graph tracedness propagation
        rep = _fixture_findings("jit_violations.py")
        assert ("jit-tracer-branch", 55) in _pairs(rep)

    def test_clean_constructs_not_flagged(self):
        # static-arg branches, is-None checks, shape/dtype reads, lax
        # control flow, and device_get-based dispatch must all be silent
        rep = _fixture_findings("jit_violations.py")
        flagged_lines = {line for _, line in _pairs(rep)}
        # static_ok_branch (34-40), dispatch_explicit_fetch (78-83),
        # clean_kernel (90-99)
        for line in list(range(34, 41)) + list(range(78, 84)) + list(
            range(90, 100)
        ):
            assert line not in flagged_lines


class TestUnbucketedDispatchRule:
    """jit-unbucketed-dispatch spans three fixture layers: kernels
    (jit_paths), the sanctioned front-end (engine_dispatch_paths), and a
    daemon module whose direct jitted calls are the seeded violations."""

    def _findings(self):
        config = AnalysisConfig(
            jit_paths=["tests/analysis_fixtures/unbucketed_ops.py"],
            engine_dispatch_paths=[
                "tests/analysis_fixtures/unbucketed_engine.py"
            ],
        )
        targets = [
            FIXTURES / n
            for n in (
                "unbucketed_ops.py",
                "unbucketed_daemon.py",
                "unbucketed_engine.py",
            )
        ]
        return run_analysis(targets, config, REPO_ROOT)

    def test_seeded_violations_by_rule_and_line(self):
        # 22: decorated @jax.jit root, 23: partial-jit via module alias,
        # 27: ad-hoc jax.jit wrapper assembled inside the daemon module
        rep = self._findings()
        assert _pairs(rep) == [
            ("jit-unbucketed-dispatch", 22),
            ("jit-unbucketed-dispatch", 23),
            ("jit-unbucketed-dispatch", 27),
        ]

    def test_rationale_suppression_is_honored(self):
        rep = self._findings()
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("jit-unbucketed-dispatch", 38)
        ]

    def test_kernel_and_engine_layers_exempt(self):
        # the engine front-end and the kernel layer both dispatch jitted
        # functions legitimately; only the daemon module may be flagged
        rep = self._findings()
        assert all(
            f.path.endswith("unbucketed_daemon.py") for f in rep.findings
        )


class TestThreadRules:
    def test_seeded_violations_by_rule_and_line(self):
        rep = _fixture_findings("thread_violations.py")
        assert _pairs(rep) == [
            ("thread-cross-module-write", 29),
            ("thread-cross-module-write", 49),
            ("thread-queue-registration", 23),
        ]

    def test_suppression_is_honored(self):
        rep = _fixture_findings("thread_violations.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("thread-cross-module-write", 33)
        ]


class TestBlockingInEventbaseRule:
    """blocking-call-in-eventbase: unbounded blocking calls reachable from
    loop-context code (async defs + marshalled callbacks), with intra-file
    call-graph propagation and await/bounded/shadow precision."""

    def test_seeded_violations_by_rule_and_line(self):
        rep = _fixture_findings("blocking_eventbase.py")
        # 21: time.sleep in an async fiber body
        # 28: Future.result() in a run_in_event_base_thread callback
        # 37: bare sleep() two call-graph hops from a schedule_timeout cb
        # 40: Queue.get() inside a lambda handed to call_soon_threadsafe
        assert _pairs(rep) == [
            ("blocking-call-in-eventbase", 21),
            ("blocking-call-in-eventbase", 28),
            ("blocking-call-in-eventbase", 37),
            ("blocking-call-in-eventbase", 40),
        ]

    def test_suppression_is_honored(self):
        rep = _fixture_findings("blocking_eventbase.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("blocking-call-in-eventbase", 45)
        ]

    def test_clean_constructs_not_flagged(self):
        # awaited .get() (49-51), bounded timeouts (53-55), caller-thread
        # blocking incl. the startup-RPC .result(5.0) idiom (59-68), a
        # local import alias shadowing a method name (70-76), and
        # dict.get with a key argument (78-79) must all stay silent
        rep = _fixture_findings("blocking_eventbase.py")
        flagged = {line for _, line in _pairs(rep)}
        assert not flagged & set(range(47, 80))


class TestCounterRules:
    def test_seeded_violations_by_rule_and_line(self):
        rep = _fixture_findings("counter_violations.py")
        assert _pairs(rep) == [
            ("counter-duplicate", 28),
            ("counter-duplicate", 31),
            ("counter-name", 22),
            ("counter-registry", 25),
        ]

    def test_suppression_is_honored(self):
        rep = _fixture_findings("counter_violations.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("counter-name", 34)
        ]


class TestCounterUnbumpedRule:
    """Inverse counter hygiene: a seeded-but-never-bumped registry key
    reads as a permanent zero on the operator surface."""

    def test_seeded_violations_by_rule_and_line(self):
        # 16: dead member of the module-tuple comprehension seed,
        # 24: dead key of the dict-literal seed; the bumped members of
        # both forms stay silent
        rep = _fixture_findings("counter_unbumped.py")
        assert _pairs(rep) == [
            ("counter-unbumped", 16),
            ("counter-unbumped", 24),
        ]

    def test_rationale_suppression_is_honored(self):
        rep = _fixture_findings("counter_unbumped.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("counter-unbumped", 27)
        ]


class TestSuppressionUnusedRule:
    """Dead-marker detection: a '# openr: disable=' declaration whose
    rule never fires on the covered lines is itself a finding."""

    def test_dead_and_idle_markers_flagged(self):
        # 25: marker on a clean line; 26: the idle half of a multi-rule
        # marker (counter-name fires there, counter-registry never does)
        rep = _fixture_findings("suppression_unused.py")
        assert _pairs(rep) == [
            ("suppression-unused", 25),
            ("suppression-unused", 26),
        ]

    def test_used_markers_stay_silent(self):
        rep = _fixture_findings("suppression_unused.py")
        assert [(s.rule, s.line) for s in rep.suppressed] == [
            ("counter-name", 24),
            ("counter-name", 26),
        ]

    def test_program_rule_markers_exempt_in_ast_only_runs(self):
        # the program-dtype marker (line 28) had no chance to fire in an
        # AST-only pass; flagging it would train people to delete
        # suppressions the --programs run still needs
        rep = _fixture_findings("suppression_unused.py")
        assert all(f.line != 28 for f in rep.findings)


class TestChangedOnly:
    """--changed-only reports AST findings only for files git sees as
    touched; analysis still runs whole-tree (cross-file rules), and
    program-* findings always survive the filter."""

    def test_filter_scopes_ast_findings(self, monkeypatch, capsys):
        from openr_tpu.analysis import cli

        fixture = str(FIXTURES / "counter_violations.py")
        monkeypatch.setattr(
            cli, "_changed_files", lambda root: {"some/other_file.py"}
        )
        assert cli.main([fixture, "--changed-only"]) == 0
        monkeypatch.setattr(
            cli,
            "_changed_files",
            lambda root: {"tests/analysis_fixtures/counter_violations.py"},
        )
        assert cli.main([fixture, "--changed-only"]) == 1

    def test_git_failure_is_exit_2(self, tmp_path):
        """Outside a git work tree the flag is a config error (rc 2),
        never a silent 'no changes -> clean' pass."""
        target = tmp_path / "probe.py"
        target.write_text("x = 1\n")
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "openr_tpu.analysis",
                "probe.py",
                "--changed-only",
            ],
            cwd=tmp_path,
            capture_output=True,
            text=True,
            env={
                **__import__("os").environ,
                "PYTHONPATH": str(REPO_ROOT),
            },
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "--changed-only needs" in proc.stderr


class TestTreeIsClean:
    def test_package_has_zero_unsuppressed_findings(self):
        """The acceptance gate: `python -m openr_tpu.analysis openr_tpu/`
        exits 0 on HEAD.  Run in-process for speed; findings are printed
        on failure so the offending line is visible in CI output."""
        config, root = load_config(PACKAGE)
        reporter = run_analysis([PACKAGE], config, root)
        findings = reporter.sorted_findings()
        assert not findings, "\n" + "\n".join(f.format() for f in findings)

    def test_registry_discovery_parsed_ctrl_handler(self):
        """The counter-registry surface comes from _all_counters' own AST
        — spot-check that the modules wired there (including netlink,
        added by this checker's sweep) are discovered."""
        from openr_tpu.analysis.counters import _exported_prefixes
        from openr_tpu.analysis.core import SourceFile

        sf = SourceFile.parse(PACKAGE / "ctrl" / "server.py", REPO_ROOT)
        prefixes = _exported_prefixes([sf])
        assert {
            "kvstore",
            "decision",
            "fib",
            "link_monitor",
            "prefix_manager",
            "spark",
            "monitor",
            "watchdog",
            "netlink",
            "queue",
        } <= prefixes

    def test_cli_exit_codes(self):
        """End-to-end CLI contract: nonzero on findings, zero on a clean
        tree.  The analysis package never imports jax, so the subprocess
        is cheap."""
        dirty = subprocess.run(
            [sys.executable, "-m", "openr_tpu.analysis", str(FIXTURES)],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
        )
        assert dirty.returncode == 1, dirty.stdout + dirty.stderr
        assert "counter-name" in dirty.stdout
        clean = subprocess.run(
            [sys.executable, "-m", "openr_tpu.analysis", "openr_tpu/"],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
        )
        assert clean.returncode == 0, clean.stdout + clean.stderr
