"""Pallas min-plus kernels (openr_tpu/ops/pallas_kernels.py) in
interpreter mode on CPU — the roofline rung's correctness surface.

Covers: bit-exact parity of the fused verify+bitmap epilogue against the
lax epilogue on every banded topology family (ring, grid, wan-shaped
with chords, drained, odd-N padding), unit + engine-integrated parity of
the blocked rank-B outer kernel (fat-tree rides this one — fat-trees are
never banded, so the blocked rung is their Pallas surface), the
OPENR_PALLAS policy knob, the graceful-demotion contract with its
device.engine.pallas_* accounting, the compiled-mode conformance gates,
and a seeded chaos fault at the engine:pallas site.  Real roofline
fractions are device-only and live behind -m slow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.decision.fleet import FleetViewCache, _reverse_runner, _row_i32
from openr_tpu.decision.link_state import LinkState
from openr_tpu.device.engine import ENGINE_COUNTER_KEYS, DeviceResidencyEngine
from openr_tpu.ops import allsources as asrc
from openr_tpu.ops import pallas_kernels as pk
from openr_tpu.parallel import blocked as blk
from openr_tpu.utils.topo import (
    fat_tree_topology,
    grid_topology,
    ring_topology,
)

pytestmark = pytest.mark.pallas

PALLAS_KEYS = sorted(k for k in ENGINE_COUNTER_KEYS if ".pallas_" in k)


def _overload(dbs, name):
    for db in dbs:
        if db.this_node_name == name:
            db.is_overloaded = True
            return dbs
    raise AssertionError(f"no node {name!r} in fixture")


def _csr(dbs) -> CsrTopology:
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return CsrTopology.from_link_state(ls)


def _ls(dbs) -> LinkState:
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return ls


def _out_ell(topo):
    return asrc.build_out_ell(
        topo.edge_src,
        topo.edge_dst,
        int(topo.n_edges),
        int(topo.n_nodes),
        out_slot=getattr(topo, "out_slot", None),
    )


def _fused(topo, dest_ids, mode: str):
    """(dist [N, P] int32-normalized, bitmap, counters) through the
    unblocked fused product with the Pallas policy pinned to `mode` —
    the counters dict proves which path actually served the product."""
    from benchmarks import synthetic

    if isinstance(topo, CsrTopology):
        runner = _reverse_runner(topo)
    else:
        runner = synthetic.reversed_topology(topo).runner
    out = _out_ell(topo)
    maps = (
        asrc.build_epilogue_maps(runner.bg, out)
        if runner.bg is not None
        else None
    )
    counters: dict = {}
    dist, bitmap, ok = asrc.reduced_all_sources(
        np.asarray(dest_ids, dtype=np.int32),
        runner,
        out,
        topo.edge_metric,
        topo.edge_up,
        topo.node_overloaded,
        maps=maps,
        pallas_run=lambda kind, pt, xt: pk.run_with_fallback(
            kind, pt, xt, counters=counters, mode=mode
        ),
    )
    assert ok
    n = int(topo.n_nodes)
    dist = _row_i32(np.asarray(jax.device_get(dist)))[:n]
    bitmap = np.asarray(jax.device_get(bitmap))[:n]
    return dist, bitmap, counters


def _one_device_mesh():
    return blk.make_blocked_mesh(jax.devices("cpu")[:1])


# ---------------------------------------------------------------------------
# Kernel 1: fused verify+bitmap epilogue
# ---------------------------------------------------------------------------


class TestEpilogueParity:
    """Forced-interpret Pallas epilogue vs the forced-XLA lax epilogue,
    bit for bit on dist AND bitmap, on every banded topology family.
    The products counter proves the kernel path engaged (build_banded
    only exists at N >= 64, so sub-64 fixtures would vacuously pass)."""

    def _assert_parity(self, topo, dest_ids):
        dp, bp, cp = _fused(topo, dest_ids, "interpret")
        dx, bx, cx = _fused(topo, dest_ids, "off")
        assert cp.get("device.engine.pallas_products") == 1, cp
        assert "device.engine.pallas_fallbacks" not in cp, cp
        assert cx.get("device.engine.pallas_skips", 0) >= 1, cx
        assert np.array_equal(dp, dx)
        assert np.array_equal(bp, bx)

    def test_ring_odd_n(self):
        csr = _csr(ring_topology(65))  # odd N: padding rows live
        self._assert_parity(csr, [0, 7, 31, 64])

    def test_grid(self):
        csr = _csr(grid_topology(10))
        self._assert_parity(csr, list(range(0, 100, 9)))

    def test_wan_shaped_chords(self):
        from benchmarks import synthetic

        topo = synthetic.wan(96, chords=2, seed=3)
        self._assert_parity(topo, [0, 5, 17, 48, 95])

    def test_ring_drained_node(self):
        csr = _csr(_overload(ring_topology(65), "r7"))
        self._assert_parity(csr, [0, 7, 40])

    def test_grid_drained_node(self):
        dbs = grid_topology(10)
        name = dbs[37].this_node_name
        csr = _csr(_overload(dbs, name))
        self._assert_parity(csr, [0, 37, 99])


# ---------------------------------------------------------------------------
# Kernel 2: blocked rank-B outer update
# ---------------------------------------------------------------------------


class TestBlockedOuterKernel:
    def _random_inputs(self, s=2, t=3, b=16, seed=0):
        rng = np.random.default_rng(seed)
        np_ = t * b
        dist = rng.integers(0, 1 << 20, size=(s, t, b, t, b)).astype(
            np.uint32
        )
        dist[rng.random(dist.shape) < 0.1] = np.uint32(1 << 30)
        row_p = rng.integers(0, 1 << 20, size=(s, b, t, b)).astype(np.uint32)
        col_p = rng.integers(0, 1 << 20, size=(s, t, b, b)).astype(np.uint32)
        ov = rng.random(np_) < 0.2
        return dist, jnp.asarray(row_p), jnp.asarray(col_p), jnp.asarray(ov)

    def test_unit_parity_all_k_with_drain_mask(self):
        dist, row_p, col_p, ov = self._random_inputs()
        mesh = _one_device_mesh()
        for k in range(3):
            got = pk.blocked_outer_pallas(
                jnp.asarray(dist), row_p, col_p, ov, k, interpret=True
            )
            want = blk.blocked_outer(
                jnp.asarray(dist), row_p, col_p, ov, k, mesh=mesh
            )
            assert np.array_equal(
                np.asarray(jax.device_get(got)),
                np.asarray(jax.device_get(want)),
            ), f"k={k}"

    def test_unit_parity_no_mask(self):
        dist, row_p, col_p, ov = self._random_inputs(s=1, t=4, b=8, seed=3)
        ov = jnp.zeros_like(ov)
        mesh = _one_device_mesh()
        got = pk.blocked_outer_pallas(
            jnp.asarray(dist), row_p, col_p, ov, 2, interpret=True
        )
        want = blk.blocked_outer(
            jnp.asarray(dist), row_p, col_p, ov, 2, mesh=mesh
        )
        assert np.array_equal(
            np.asarray(jax.device_get(got)), np.asarray(jax.device_get(want))
        )

    def test_compiled_mode_gates_nonconformant_tiles(self):
        """b=16 tiles can't lower on Mosaic (last dim must be 128s);
        the gate raises at trace time so the demotion path re-runs on
        an intact buffer — never a mid-kernel abort on device."""
        dist, row_p, col_p, ov = self._random_inputs()
        with pytest.raises(ValueError):
            pk.blocked_outer_pallas(
                jnp.asarray(dist), row_p, col_p, ov, 0, interpret=False
            )


# ---------------------------------------------------------------------------
# Policy knob + demotion contract
# ---------------------------------------------------------------------------


class TestPolicyAndFallback:
    def test_mode_parsing(self):
        assert pk.pallas_mode(env="0") == "off"
        assert pk.pallas_mode(env="off") == "off"
        assert pk.pallas_mode(env="interpret") == "interpret"
        assert pk.pallas_mode(env="compiled") == "compiled"
        on_tpu = jax.default_backend() == "tpu"
        assert pk.pallas_mode(env="1") == (
            "compiled" if on_tpu else "interpret"
        )
        # auto: compiled on TPU, off elsewhere (the interpreter is a
        # correctness tool, never an implicit fast path)
        assert pk.pallas_mode(env="") == ("compiled" if on_tpu else "off")
        assert pk.pallas_mode(env="auto") == pk.pallas_mode(env="")
        assert pk.pallas_mode(env="bogus") == pk.pallas_mode(env="auto")

    def test_env_is_the_default_policy(self, monkeypatch):
        monkeypatch.setenv("OPENR_PALLAS", "interpret")
        assert pk.pallas_mode() == "interpret"
        monkeypatch.setenv("OPENR_PALLAS", "0")
        assert pk.pallas_mode() == "off"

    def test_off_mode_skips_and_accounts(self):
        counters: dict = {}
        out = pk.run_with_fallback(
            "product",
            lambda interpret: pytest.fail("pallas thunk must not run"),
            lambda: "xla",
            counters=counters,
            mode="off",
        )
        assert out == "xla"
        assert counters == {"device.engine.pallas_skips": 1}

    def test_failure_demotes_and_accounts(self):
        def boom(interpret):
            raise RuntimeError("tile mismatch")

        counters: dict = {}
        out = pk.run_with_fallback(
            "product", boom, lambda: "xla", counters=counters, mode="interpret"
        )
        assert out == "xla"
        assert counters == {"device.engine.pallas_fallbacks": 1}

    def test_success_accounts_per_kind(self):
        counters: dict = {}
        assert (
            pk.run_with_fallback(
                "product", lambda i: "p", lambda: "x",
                counters=counters, mode="interpret",
            )
            == "p"
        )
        assert (
            pk.run_with_fallback(
                "outer", lambda i: "o", lambda: "x",
                counters=counters, mode="interpret",
            )
            == "o"
        )
        assert counters == {
            "device.engine.pallas_products": 1,
            "device.engine.pallas_outer_updates": 1,
        }

    def test_epilogue_refuses_row_exclusions(self):
        from types import SimpleNamespace

        ops = SimpleNamespace(resid_excl=np.zeros((4, 2), bool))
        with pytest.raises(ValueError, match="row exclusions"):
            pk.fused_epilogue(
                ops, None, jnp.zeros((4, 2), jnp.uint16), None, None, 1,
                interpret=True,
            )


# ---------------------------------------------------------------------------
# Engine-routed integration (the production dispatch path)
# ---------------------------------------------------------------------------


class TestEngineIntegration:
    def test_counters_preseeded_on_engine(self):
        eng = DeviceResidencyEngine()
        c = eng.get_counters()
        assert PALLAS_KEYS and set(PALLAS_KEYS) <= set(c)
        assert all(c[k] == 0 for k in PALLAS_KEYS)

    def test_fused_product_parity_through_view(self):
        ls = _ls(ring_topology(65))
        dests = ["r0", "r7", "r64"]
        ep = DeviceResidencyEngine()
        ep.pallas_mode = "interpret"
        vp = FleetViewCache().view(ls, dests, engine=ep)
        assert vp.converged
        cp = ep.get_counters()
        assert cp["device.engine.pallas_products"] == 1
        assert cp["device.engine.pallas_fallbacks"] == 0
        ex = DeviceResidencyEngine()
        ex.pallas_mode = "off"
        vx = FleetViewCache().view(_ls(ring_topology(65)), dests, engine=ex)
        assert vx.converged
        assert ex.get_counters()["device.engine.pallas_skips"] >= 1
        for node in sorted(ls.node_names):
            assert np.array_equal(vp._row(node), vx._row(node))
        assert np.array_equal(
            np.asarray(jax.device_get(vp._bitmap_dev)),
            np.asarray(jax.device_get(vx._bitmap_dev)),
        )

    def test_blocked_rung_parity_on_fattree(self):
        """Fat-trees are never banded, so the blocked rung is their
        Pallas surface: single-device mesh engages the outer kernel,
        and the view must match the plain XLA blocked closure."""
        dbs = fat_tree_topology(4)
        ls = _ls(dbs)
        nodes = sorted(ls.node_names)
        dests = [nodes[0], nodes[3], nodes[-1]]
        ep = DeviceResidencyEngine()
        ep.pallas_mode = "interpret"
        ep.blocked.node_shard_threshold = 0
        ep.blocked._mesh = _one_device_mesh()
        vp = FleetViewCache().view(ls, dests, engine=ep)
        assert vp.converged and vp.node_sharded
        cp = ep.get_counters()
        assert cp["device.engine.pallas_outer_updates"] > 0
        assert cp["device.engine.pallas_fallbacks"] == 0
        ex = DeviceResidencyEngine()
        ex.pallas_mode = "off"
        ex.blocked.node_shard_threshold = 0
        ex.blocked._mesh = _one_device_mesh()
        vx = FleetViewCache().view(_ls(fat_tree_topology(4)), dests, engine=ex)
        assert vx.converged and vx.node_sharded
        assert ex.get_counters()["device.engine.pallas_skips"] >= 1
        for node in nodes:
            assert np.array_equal(vp._row(node), vx._row(node))

    def test_multi_device_mesh_stays_on_xla(self):
        """The outer kernel owns single-device meshes only: sharded
        meshes keep the collective-aware XLA kernel, no pallas counter
        moves (and no demotion is charged — this is rung placement,
        not a failure)."""
        devices = jax.devices("cpu")
        if len(devices) < 8:
            pytest.skip("needs xla_force_host_platform_device_count=8")
        ls = _ls(grid_topology(4))
        nodes = sorted(ls.node_names)
        eng = DeviceResidencyEngine()
        eng.pallas_mode = "interpret"
        eng.blocked.node_shard_threshold = 0
        view = FleetViewCache().view(ls, [nodes[0], nodes[-1]], engine=eng)
        assert view.converged and view.node_sharded
        c = eng.get_counters()
        assert all(c[k] == 0 for k in PALLAS_KEYS), c


class TestChaosPallas:
    def test_seeded_fault_demotes_with_parity(self):
        """Armed engine:pallas fault fires inside the launch try-block:
        the product demotes through the real failure path — fallback
        counter bumped, failure event logged, view served bit-exactly
        by the XLA epilogue."""
        from types import SimpleNamespace

        from openr_tpu.chaos.chaos import ChaosSpfBackend

        ls = _ls(ring_topology(65))
        dests = ["r0", "r31", "r64"]
        engine = DeviceResidencyEngine()
        engine.pallas_mode = "interpret"
        chaos = ChaosSpfBackend(
            SimpleNamespace(engine=engine),
            seed=7,
            fail_prob=1.0,
            fail_ops={"engine:pallas"},
        )
        view = FleetViewCache().view(ls, dests, engine=engine)
        assert view.converged
        c = engine.get_counters()
        assert c["device.engine.pallas_fallbacks"] == 1
        assert c["device.engine.pallas_products"] == 0
        spf_stream = chaos.log.streams().get("spf", [])
        assert any("engine:pallas:fail" in e for e in spf_stream)
        chaos.disarm()
        vf = FleetViewCache().view(_ls(ring_topology(65)), dests)
        for node in sorted(ls.node_names):
            assert np.array_equal(view._row(node), vf._row(node))


# ---------------------------------------------------------------------------
# Device-only roofline assertions (-m slow; skipped off-TPU)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestRooflineOnDevice:
    """Real achieved-fraction-of-roofline assertions: compiled kernels
    on actual TPU HBM.  Interpreter walls measure the interpreter, so
    these are meaningless off-device — hard skip."""

    @pytest.fixture(autouse=True)
    def _tpu_only(self):
        if jax.default_backend() != "tpu":
            pytest.skip("roofline fractions need a real TPU backend")

    def test_blocked_outer_reaches_roofline_fraction(self):
        import time

        from benchmarks.util import achieved_bw_frac

        rng = np.random.default_rng(14)
        s, t, b = 1, 8, 128
        np_ = t * b
        dist_h = rng.integers(0, 1 << 20, size=(s, t, b, t, b)).astype(
            np.uint32
        )
        row_p = jnp.asarray(
            rng.integers(0, 1 << 20, size=(s, b, t, b)).astype(np.uint32)
        )
        col_p = jnp.asarray(
            rng.integers(0, 1 << 20, size=(s, t, b, b)).astype(np.uint32)
        )
        ov = jnp.zeros(np_, bool)
        staged = [jax.device_put(dist_h) for _ in range(6)]
        jax.block_until_ready(staged)
        pk.blocked_outer_pallas(  # compile + warm
            staged[0], row_p, col_p, ov, 0, interpret=False
        )
        walls = []
        for d in staged[1:]:
            t0 = time.perf_counter()
            jax.block_until_ready(
                pk.blocked_outer_pallas(d, row_p, col_p, ov, 0, interpret=False)
            )
            walls.append((time.perf_counter() - t0) * 1e3)
        bytes_tm = 2 * s * np_ * np_ * 4 + 2 * t * s * np_ * b * 4
        frac = achieved_bw_frac(bytes_tm, min(walls))
        assert frac is not None and frac > 0.2, (frac, walls)
