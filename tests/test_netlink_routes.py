"""rtnetlink route codec + kernel-mode fib agent.

Mirrors the reference's kernel-touching route tests
(openr/platform/tests/NetlinkFibHandlerTest.cpp: add/del/sync, multipath,
scale; openr/nl/tests route message codecs).  Codec tests run everywhere;
kernel tests require NET_ADMIN (veth creation) and program REAL routes
through openr_tpu.nl.netlink, reading them back via protocol-filtered
dumps exactly like getRouteTableByClient.
"""

from __future__ import annotations

import socket
import struct
import subprocess
import uuid

import pytest

from openr_tpu.nl.netlink import (
    NetlinkProtocolSocket,
    NextHopInfo,
    RTM_DELROUTE,
    RTM_NEWROUTE,
    RTPROT_OPENR,
    RouteInfo,
    build_route_request,
    parse_messages,
)
from openr_tpu.platform.fib_agent import (
    CLIENT_ID_TO_PROTOCOL,
    FibAgentServer,
    KernelRouteTable,
)
from openr_tpu.platform import TcpFibAgent
from openr_tpu.types import NextHop, UnicastRoute
from tests.test_netlink import NET_ADMIN


class TestRouteCodec:
    """Encode -> parse round trips (no kernel needed)."""

    def test_single_nexthop_roundtrip(self):
        r = RouteInfo(
            dst="2001:db8:1::/64",
            nexthops=[NextHopInfo(gateway="fe80::1", if_index=7)],
            priority=10,
        )
        raw = build_route_request(RTM_NEWROUTE, 1, r)
        msgs = list(parse_messages(raw))
        assert len(msgs) == 1 and msgs[0].msg_type == RTM_NEWROUTE
        back = msgs[0].route
        assert back.dst == "2001:db8:1::/64"
        assert back.protocol == RTPROT_OPENR
        assert back.priority == 10
        assert [(n.gateway, n.if_index) for n in back.nexthops] == [
            ("fe80::1", 7)
        ]

    def test_multipath_roundtrip(self):
        r = RouteInfo(
            dst="10.1.0.0/16",
            nexthops=[
                NextHopInfo(gateway="10.0.0.1", if_index=3, weight=2),
                NextHopInfo(gateway="10.0.0.2", if_index=4, weight=1),
            ],
        )
        raw = build_route_request(RTM_NEWROUTE, 2, r)
        back = next(parse_messages(raw)).route
        assert back.dst == "10.1.0.0/16"
        assert back.family == socket.AF_INET
        assert [(n.gateway, n.if_index, n.weight) for n in back.nexthops] == [
            ("10.0.0.1", 3, 2),
            ("10.0.0.2", 4, 1),
        ]

    def test_delete_has_no_create_flags(self):
        raw = build_route_request(
            RTM_DELROUTE, 3, RouteInfo(dst="10.2.0.0/16")
        )
        _len, mtype, flags, _seq, _pid = struct.unpack_from("=IHHII", raw, 0)
        assert mtype == RTM_DELROUTE
        assert not flags & 0x400  # NLM_F_CREATE
        assert flags & 0x04  # NLM_F_ACK

    def test_default_route_parse(self):
        raw = build_route_request(RTM_NEWROUTE, 4, RouteInfo(dst="::/0"))
        back = next(parse_messages(raw)).route
        assert back.dst == "::/0"


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestKernelRoutes:
    """Real-kernel programming (reference: NetlinkFibHandlerTest.cpp)."""

    @pytest.fixture
    def veth(self):
        name = f"rt{uuid.uuid4().hex[:8]}"
        peer = f"{name}p"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth", "peer", "name", peer],
            check=True,
        )
        try:
            for dev in (name, peer):
                subprocess.run(["ip", "link", "set", dev, "up"], check=True)
            subprocess.run(
                ["ip", "addr", "add", "2001:db8:fe::1/64", "dev", name],
                check=True,
            )
            yield name
        finally:
            subprocess.run(["ip", "link", "del", name], capture_output=True)

    def _nl_and_ifindex(self, veth):
        nl = NetlinkProtocolSocket()
        links = {l.if_name: l.if_index for l in nl.get_all_links()}
        return nl, links[veth]

    def test_add_read_delete(self, veth):
        nl, idx = self._nl_and_ifindex(veth)
        r = RouteInfo(
            dst="2001:db8:a::/64",
            nexthops=[NextHopInfo(gateway="2001:db8:fe::2", if_index=idx)],
        )
        nl.add_route(r)
        try:
            back = [x for x in nl.get_routes() if x.dst == "2001:db8:a::/64"]
            assert len(back) == 1
            assert back[0].protocol == RTPROT_OPENR
            assert [(n.gateway, n.if_index) for n in back[0].nexthops] == [
                ("2001:db8:fe::2", idx)
            ]
        finally:
            nl.del_route(RouteInfo(dst="2001:db8:a::/64"))
        assert not [x for x in nl.get_routes() if x.dst == "2001:db8:a::/64"]

    def test_multipath_add_readback(self, veth):
        nl, idx = self._nl_and_ifindex(veth)
        r = RouteInfo(
            dst="2001:db8:b::/64",
            nexthops=[
                NextHopInfo(gateway="2001:db8:fe::2", if_index=idx),
                NextHopInfo(gateway="2001:db8:fe::3", if_index=idx),
            ],
        )
        nl.add_route(r)
        try:
            back = [x for x in nl.get_routes() if x.dst == "2001:db8:b::/64"]
            assert sorted(n.gateway for n in back[0].nexthops) == [
                "2001:db8:fe::2",
                "2001:db8:fe::3",
            ]
        finally:
            nl.del_route(RouteInfo(dst="2001:db8:b::/64"))

    def test_kernel_agent_add_sync_delete(self, veth):
        agent = KernelRouteTable()
        client = 786  # openr -> protocol 99
        route = lambda i: UnicastRoute(
            dest=f"2001:db8:{i:x}::/64",
            next_hops=[NextHop(address="2001:db8:fe::2", if_name=veth)],
        )
        try:
            agent.add_unicast_routes(client, [route(0x10), route(0x11)])
            got = agent.get_route_table_by_client(client)
            assert [r.dest for r in got] == [
                "2001:db8:10::/64",
                "2001:db8:11::/64",
            ]
            assert got[0].next_hops[0].if_name == veth
            # syncFib keeps 0x11, drops 0x10, adds 0x12 (diff semantics)
            agent.sync_fib(client, [route(0x11), route(0x12)])
            got = agent.get_route_table_by_client(client)
            assert [r.dest for r in got] == [
                "2001:db8:11::/64",
                "2001:db8:12::/64",
            ]
            # delete is idempotent (reference tolerates double-delete)
            agent.delete_unicast_routes(
                client, ["2001:db8:11::/64", "2001:db8:11::/64"]
            )
            got = agent.get_route_table_by_client(client)
            assert [r.dest for r in got] == ["2001:db8:12::/64"]
        finally:
            agent.sync_fib(client, [])
        assert agent.get_route_table_by_client(client) == []

    def test_kernel_agent_scale_1k(self, veth):
        """1k routes programmed + read back + cleaned (reference runs up
        to 10k, NetlinkFibHandlerTest.cpp:775 / nl/README)."""
        agent = KernelRouteTable()
        client = 786
        routes = [
            UnicastRoute(
                dest=f"2001:db8:{i >> 8:x}:{i & 0xFF:x}::/80",
                next_hops=[
                    NextHop(address="2001:db8:fe::2", if_name=veth)
                ],
            )
            for i in range(1000)
        ]
        try:
            agent.sync_fib(client, routes)
            got = agent.get_route_table_by_client(client)
            assert len(got) == 1000
        finally:
            agent.sync_fib(client, [])
        assert agent.get_route_table_by_client(client) == []

    def test_client_protocol_separation(self, veth):
        """Routes of different FibService clients live under different
        kernel protocol ids (clientIdtoProtocolId, Platform.thrift:58)."""
        assert CLIENT_ID_TO_PROTOCOL[786] == 99
        agent = KernelRouteTable()
        r_openr = UnicastRoute(
            dest="2001:db8:20::/64",
            next_hops=[NextHop(address="2001:db8:fe::2", if_name=veth)],
        )
        r_bgp = UnicastRoute(
            dest="2001:db8:21::/64",
            next_hops=[NextHop(address="2001:db8:fe::2", if_name=veth)],
        )
        try:
            agent.add_unicast_routes(786, [r_openr])
            agent.add_unicast_routes(0, [r_bgp])
            assert [
                r.dest for r in agent.get_route_table_by_client(786)
            ] == ["2001:db8:20::/64"]
            assert [r.dest for r in agent.get_route_table_by_client(0)] == [
                "2001:db8:21::/64"
            ]
        finally:
            agent.sync_fib(786, [])
            agent.sync_fib(0, [])

    def test_kernel_agent_over_wire(self, veth):
        """The full process boundary: TcpFibAgent client -> NDJSON server
        -> KernelRouteTable -> kernel, and back."""
        server = FibAgentServer(table=KernelRouteTable())
        server.start()
        try:
            client = TcpFibAgent(port=server.port)
            route = UnicastRoute(
                dest="2001:db8:30::/64",
                next_hops=[
                    NextHop(address="2001:db8:fe::2", if_name=veth)
                ],
            )
            client.add_unicast_routes(786, [route])
            got = client.get_route_table_by_client(786)
            assert [r.dest for r in got] == ["2001:db8:30::/64"]
            assert got[0].next_hops[0].address == "2001:db8:fe::2"
            assert client.alive_since() > 0
            client.sync_fib(786, [])
            assert client.get_route_table_by_client(786) == []
            client.close()
        finally:
            server.stop()


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestKernelAddresses:
    """Interface-address programming (reference: NetlinkAddrMessage,
    openr/nl/NetlinkRoute.h:214; PrefixAllocator address sync)."""

    @pytest.fixture
    def veth(self):
        name = f"ad{uuid.uuid4().hex[:8]}"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth",
             "peer", "name", f"{name}p"],
            check=True,
        )
        try:
            subprocess.run(["ip", "link", "set", name, "up"], check=True)
            yield name
        finally:
            subprocess.run(["ip", "link", "del", name], capture_output=True)

    def test_add_read_delete_addr(self, veth):
        nl = NetlinkProtocolSocket()
        idx = {l.if_name: l.if_index for l in nl.get_all_links()}[veth]
        nl.add_addr(idx, "2001:db8:41::1/64")
        addrs = [
            a.prefix
            for a in nl.get_all_addresses()
            if a.if_index == idx and a.prefix.startswith("2001:db8:41:")
        ]
        assert addrs == ["2001:db8:41::1/64"]
        nl.del_addr(idx, "2001:db8:41::1/64")
        assert not [
            a
            for a in nl.get_all_addresses()
            if a.if_index == idx and a.prefix.startswith("2001:db8:41:")
        ]

    def test_prefix_allocator_assigns_address(self, veth):
        """The allocator's elected prefix lands on the interface and
        moves when the allocation changes (reference: PrefixAllocator
        syncIfaceAddrs)."""
        import ipaddress
        import threading
        import time

        from openr_tpu.allocators.prefix_allocator import PrefixAllocator

        alloc = PrefixAllocator.__new__(PrefixAllocator)
        alloc.assign_to_interface = veth
        alloc._nl = None
        alloc._addr_sync_lock = threading.Lock()
        alloc._addr_pending = None
        alloc._addr_worker_busy = False
        alloc._addr_stopped = False
        alloc.seed = ipaddress.ip_network("2001:db8:42::/48")
        alloc.node_name = "t"
        nl = NetlinkProtocolSocket()
        idx = {l.if_name: l.if_index for l in nl.get_all_links()}[veth]
        # a STALE address inside the seed (a previous process instance's
        # leftover) must be reconciled away by the first sync
        nl.add_addr(idx, "2001:db8:42:f::1/64")

        def mine():
            return [
                a.prefix
                for a in nl.get_all_addresses()
                if a.if_index == idx and a.prefix.startswith("2001:db8:42:")
            ]

        def sync_wait(prefix, expect):
            alloc._sync_iface_addr(prefix)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if mine() == expect:
                    return
                time.sleep(0.05)
            assert mine() == expect

        sync_wait("2001:db8:42:1::/64", ["2001:db8:42:1::1/64"])
        # allocation moves: old address replaced by the new one
        sync_wait("2001:db8:42:2::/64", ["2001:db8:42:2::1/64"])
        # allocation lost: address withdrawn
        sync_wait(None, [])


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestKernelNeighbors:
    def test_neighbor_dump(self):
        """RTM_GETNEIGH dump decodes real kernel neighbor entries
        (reference: NetlinkNeighborMessage, NetlinkRoute.h:255)."""
        name = f"nb{uuid.uuid4().hex[:8]}"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth",
             "peer", "name", f"{name}p"],
            check=True,
        )
        try:
            subprocess.run(["ip", "link", "set", name, "up"], check=True)
            subprocess.run(
                ["ip", "neigh", "add", "2001:db8:fe::99",
                 "lladdr", "02:00:00:00:00:01", "dev", name],
                check=True,
            )
            nl = NetlinkProtocolSocket()
            idx = {l.if_name: l.if_index for l in nl.get_all_links()}[name]
            mine = [
                n
                for n in nl.get_all_neighbors()
                if n.if_index == idx and n.dst == "2001:db8:fe::99"
            ]
            assert len(mine) == 1
            assert mine[0].lladdr == "02:00:00:00:00:01"
            assert mine[0].family == socket.AF_INET6
        finally:
            subprocess.run(["ip", "link", "del", name], capture_output=True)


class TestMplsCodec:
    """AF_MPLS route + label-stack encode -> parse round trips and MPLS
    push encap on IP routes (no kernel needed).  Reference codec:
    NetlinkRouteMessage MPLS build/parse, openr/nl/NetlinkRoute.h:41-176."""

    def test_label_stack_roundtrip(self):
        from openr_tpu.nl.netlink import pack_label_stack, unpack_label_stack

        for stack in ((100,), (100, 200), (16, 17, 1048575)):
            assert unpack_label_stack(pack_label_stack(stack)) == stack

    def test_swap_route_roundtrip(self):
        from openr_tpu.nl.netlink import (
            MplsRouteInfo,
            build_mpls_route_request,
        )

        r = MplsRouteInfo(
            label=100,
            nexthops=[
                NextHopInfo(
                    gateway="fe80::1", if_index=7, swap_labels=(200,)
                )
            ],
        )
        raw = build_mpls_route_request(RTM_NEWROUTE, 1, r)
        back = next(parse_messages(raw)).mpls_route
        assert back is not None
        assert back.label == 100
        assert back.protocol == RTPROT_OPENR
        assert [(n.gateway, n.if_index, n.swap_labels) for n in back.nexthops] == [
            ("fe80::1", 7, (200,))
        ]

    def test_multipath_mpls_roundtrip(self):
        from openr_tpu.nl.netlink import (
            MplsRouteInfo,
            build_mpls_route_request,
        )

        r = MplsRouteInfo(
            label=300,
            nexthops=[
                NextHopInfo(gateway="fe80::1", if_index=3, swap_labels=(301,)),
                NextHopInfo(gateway="fe80::2", if_index=4),  # PHP: no stack
            ],
        )
        raw = build_mpls_route_request(RTM_NEWROUTE, 2, r)
        back = next(parse_messages(raw)).mpls_route
        assert back.label == 300
        assert [(n.gateway, n.swap_labels) for n in back.nexthops] == [
            ("fe80::1", (301,)),
            ("fe80::2", ()),
        ]

    def test_pop_route_is_oif_only(self):
        from openr_tpu.nl.netlink import (
            MplsRouteInfo,
            build_mpls_route_request,
        )

        r = MplsRouteInfo(
            label=400, nexthops=[NextHopInfo(if_index=1)]  # POP_AND_LOOKUP
        )
        back = next(
            parse_messages(build_mpls_route_request(RTM_NEWROUTE, 3, r))
        ).mpls_route
        assert back.nexthops[0].gateway is None
        assert back.nexthops[0].if_index == 1
        assert back.nexthops[0].swap_labels == ()

    def test_unicast_push_encap_roundtrip(self):
        """Label PUSH on an IP route rides the MPLS lwtunnel encap
        (reference: NetlinkRoute.cpp push path)."""
        r = RouteInfo(
            dst="2001:db8:9::/64",
            nexthops=[
                NextHopInfo(
                    gateway="fe80::9", if_index=5, push_labels=(100, 200)
                )
            ],
        )
        back = next(
            parse_messages(build_route_request(RTM_NEWROUTE, 4, r))
        ).route
        assert back.nexthops[0].push_labels == (100, 200)

    def test_multipath_push_encap_roundtrip(self):
        r = RouteInfo(
            dst="2001:db8:a::/64",
            nexthops=[
                NextHopInfo(gateway="fe80::1", if_index=5, push_labels=(77,)),
                NextHopInfo(gateway="fe80::2", if_index=6),
            ],
        )
        back = next(
            parse_messages(build_route_request(RTM_NEWROUTE, 5, r))
        ).route
        assert [n.push_labels for n in back.nexthops] == [(77,), ()]

    def test_neigh_request_codec(self):
        """Neighbor add/del requests round-trip through the parser
        (reference: NetlinkNeighborMessage build, NetlinkRoute.h:255)."""
        from openr_tpu.nl.netlink import RTM_NEWNEIGH, build_neigh_request

        raw = build_neigh_request(
            RTM_NEWNEIGH, 7, 3, "2001:db8::9", "02:00:00:00:00:02"
        )
        back = next(parse_messages(raw)).neigh
        assert back is not None
        assert (back.if_index, back.dst, back.lladdr) == (
            3,
            "2001:db8::9",
            "02:00:00:00:00:02",
        )
        assert back.state == 0x80  # NUD_PERMANENT


def _mpls_kernel_available() -> bool:
    import os

    return NET_ADMIN and os.path.isdir("/proc/sys/net/mpls")


@pytest.mark.skipif(not NET_ADMIN, reason="needs NET_ADMIN (veth creation)")
class TestKernelNeighborProgramming:
    def test_neighbor_add_del_roundtrip(self):
        """Program a kernel neighbor, read it back, delete it
        (reference: NetlinkRoute.h:255 + NeighborBuilder,
        NetlinkTypes.h:48-285) — the last codec surface delta (r3 #9)."""
        name = f"np{uuid.uuid4().hex[:8]}"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth",
             "peer", "name", f"{name}p"],
            check=True,
        )
        try:
            subprocess.run(["ip", "link", "set", name, "up"], check=True)
            nl = NetlinkProtocolSocket()
            idx = {l.if_name: l.if_index for l in nl.get_all_links()}[name]
            nl.add_neighbor(idx, "2001:db8:fe::77", "02:00:00:00:00:03")

            def mine():
                return [
                    n
                    for n in nl.get_all_neighbors()
                    if n.if_index == idx and n.dst == "2001:db8:fe::77"
                ]

            got = mine()
            assert len(got) == 1
            assert got[0].lladdr == "02:00:00:00:00:03"
            nl.del_neighbor(idx, "2001:db8:fe::77")
            assert mine() == []
        finally:
            subprocess.run(["ip", "link", "del", name], capture_output=True)


@pytest.mark.skipif(
    not _mpls_kernel_available(),
    reason="needs NET_ADMIN + kernel AF_MPLS (mpls_router)",
)
class TestKernelMplsRoutes:
    """Real-kernel MPLS programming + restart readback (r3 gap #1;
    reference: NetlinkFibHandler getMplsRouteTableByClient / syncMplsFib,
    openr/platform/NetlinkFibHandler.cpp)."""

    @pytest.fixture
    def veth(self):
        name = f"mp{uuid.uuid4().hex[:8]}"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth",
             "peer", "name", f"{name}p"],
            check=True,
        )
        # platform_labels: rewriting it FLUSHES every MPLS route on the
        # host, so only grow it when too small and restore the original
        # afterwards (it starts at 0 on a fresh mpls_router load, so the
        # restore is usually a no-op flush of our own deleted routes)
        orig_labels = open("/proc/sys/net/mpls/platform_labels").read().strip()
        try:
            subprocess.run(["ip", "link", "set", name, "up"], check=True)
            if int(orig_labels) < 1000:
                subprocess.run(
                    ["sysctl", "-w", "net.mpls.platform_labels=1000"],
                    check=True,
                )
            subprocess.run(
                ["sysctl", "-w", f"net.mpls.conf.{name}.input=1"], check=True
            )
            yield name
        finally:
            if int(orig_labels) < 1000:
                subprocess.run(
                    ["sysctl", "-w", f"net.mpls.platform_labels={orig_labels}"],
                    capture_output=True,
                )
            subprocess.run(["ip", "link", "del", name], capture_output=True)

    def test_mpls_restart_readback_and_sync(self, veth):
        from openr_tpu.types import MplsAction, MplsActionCode, MplsRoute

        table = KernelRouteTable()
        try:
            route = MplsRoute(
                top_label=100,
                next_hops=[
                    NextHop(
                        address="2001:db8:fe::2",
                        if_name=veth,
                        mpls_action=MplsAction(
                            MplsActionCode.SWAP, swap_label=200
                        ),
                    )
                ],
            )
            stale = MplsRoute(
                top_label=101,
                next_hops=[
                    NextHop(
                        address="2001:db8:fe::2",
                        if_name=veth,
                        mpls_action=MplsAction(MplsActionCode.PHP),
                    )
                ],
            )
            table.add_mpls_routes(786, [route, stale])
            assert table._mpls_kernel is True

            # agent RESTART: a fresh table must read routes back from the
            # KERNEL, not from (lost) in-process state
            table2 = KernelRouteTable()
            try:
                got = table2.get_mpls_route_table_by_client(786)
                assert [r.top_label for r in got] == [100, 101]
                swap = got[0].next_hops[0]
                assert swap.mpls_action.action == MplsActionCode.SWAP
                assert swap.mpls_action.swap_label == 200
                assert swap.if_name == veth

                # sync diffs against kernel truth: label 101 is stale
                table2.sync_mpls_fib(786, [route])
                left = table2.get_mpls_route_table_by_client(786)
                assert [r.top_label for r in left] == [100]
            finally:
                table2.delete_mpls_routes(786, [100])
                table2.nl.close_request_socket()
        finally:
            table.nl.close_request_socket()
