"""KvStore tests: CRDT merge semantics + in-process multi-store mesh.

Modeled on the reference's KvStoreTest.cpp / KvStoreThriftTest.cpp /
KvStoreClientInternalTest.cpp (openr/kvstore/tests/): merge tie-breaks,
full-sync FSM, 3-way sync, flooding, TTL expiry, persist-key ownership.
"""

from __future__ import annotations

import time

import pytest

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreClientInternal,
    KvStoreFilters,
    compare_values,
    generate_hash,
    merge_key_values,
)
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.types import KvStorePeerState, PeerSpec, Publication, Value


def v(
    version=1, originator="node1", value=b"x", ttl_ms=-1, ttl_version=0, hash=None
) -> Value:
    return Value(
        version=version,
        originator_id=originator,
        value=value,
        ttl_ms=ttl_ms,
        ttl_version=ttl_version,
        hash=hash,
    )


class TestMergeKeyValues:
    """Reference: KvStoreTest mergeKeyValues cases."""

    def test_new_key_and_newer_version(self):
        store = {}
        delta = merge_key_values(store, {"k": v(version=1)})
        assert set(delta) == {"k"}
        assert store["k"].version == 1
        assert store["k"].hash is not None  # hash filled in

        delta = merge_key_values(store, {"k": v(version=3, value=b"y")})
        assert set(delta) == {"k"}
        assert store["k"].value == b"y"

    def test_old_version_skipped(self):
        store = {"k": v(version=5)}
        assert merge_key_values(store, {"k": v(version=4, value=b"zzz")}) == {}
        assert store["k"].version == 5

    def test_originator_tiebreak(self):
        store = {"k": v(originator="node1")}
        assert merge_key_values(store, {"k": v(originator="node0")}) == {}
        delta = merge_key_values(store, {"k": v(originator="node2")})
        assert set(delta) == {"k"}
        assert store["k"].originator_id == "node2"

    def test_value_tiebreak_same_version_same_originator(self):
        store = {"k": v(value=b"b")}
        assert merge_key_values(store, {"k": v(value=b"a")}) == {}
        delta = merge_key_values(store, {"k": v(value=b"c")})
        assert set(delta) == {"k"}
        assert store["k"].value == b"c"

    def test_ttl_version_only_update(self):
        store = {"k": v(ttl_ms=-1)}
        # same everything, higher ttlVersion, with value
        delta = merge_key_values(store, {"k": v(ttl_ms=10000, ttl_version=2)})
        assert set(delta) == {"k"}
        assert store["k"].ttl_version == 2
        assert store["k"].ttl_ms == 10000
        # version-only advertisement (value=None) bumps ttl again
        delta = merge_key_values(
            store, {"k": v(value=None, ttl_ms=20000, ttl_version=3)}
        )
        assert set(delta) == {"k"}
        assert store["k"].ttl_version == 3
        assert store["k"].value == b"x"  # value untouched

    def test_invalid_ttl_skipped(self):
        store = {}
        assert merge_key_values(store, {"k": v(ttl_ms=0)}) == {}
        assert merge_key_values(store, {"k": v(ttl_ms=-7)}) == {}
        assert store == {}

    def test_ttl_refresh_for_unknown_key_ignored(self):
        store = {}
        assert merge_key_values(store, {"k": v(value=None, ttl_version=1)}) == {}

    def test_filters(self):
        store = {}
        filters = KvStoreFilters(key_prefixes=["adj:"])
        delta = merge_key_values(
            store, {"adj:a": v(), "prefix:p": v()}, filters
        )
        assert set(delta) == {"adj:a"}


class TestCompareValues:
    def test_chain(self):
        assert compare_values(v(version=2), v(version=1)) == 1
        assert compare_values(v(version=1), v(version=2)) == -1
        assert compare_values(v(originator="b"), v(originator="a")) == 1
        assert compare_values(v(value=b"b"), v(value=b"a")) == 1
        assert compare_values(v(), v()) == 0
        assert (
            compare_values(v(ttl_version=2), v(ttl_version=1)) == 1
        )
        # unknown when a value is missing and hashes don't match
        assert compare_values(v(value=None), v(value=b"a")) == -2

    def test_hash_equality_path(self):
        h = generate_hash(1, "node1", b"x")
        assert compare_values(v(hash=h, value=None), v(hash=h)) == 0


def make_store(name, fabric, areas=("0",), **kw):
    updates: ReplicateQueue[Publication] = ReplicateQueue()
    syncs: ReplicateQueue = ReplicateQueue()
    peerq: ReplicateQueue = ReplicateQueue()
    store = KvStore(
        name,
        updates,
        syncs,
        peerq.get_reader(),
        transport=fabric.bind(name),
        areas=areas,
        **kw,
    )
    fabric.register(name, store)
    store.run()
    return store, updates, syncs, peerq


def spec(addr: str) -> PeerSpec:
    return PeerSpec(peer_addr=addr)


def wait_for(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def fabric():
    fab = InProcessTransport()
    stores = []

    def _make(name, **kw):
        parts = make_store(name, fab, **kw)
        stores.append(parts)
        return parts

    yield fab, _make
    for store, updates, syncs, peerq in stores:
        updates.close()
        syncs.close()
        peerq.close()
        store.stop()
    for store, *_ in stores:
        store.wait_until_stopped(5)


class TestKvStoreMesh:
    def test_full_sync_two_stores(self, fabric):
        fab, make = fabric
        a, _, _, _ = make("a")
        b, _, b_syncs, _ = make("b")
        sync_reader = b_syncs.get_reader()

        a.set_key_vals("0", {"k1": v(originator="a", value=b"v1")})
        b.add_peers("0", {"a": spec("a")})

        event = sync_reader.get(timeout=5)
        assert event.node_name == "a"
        assert b.get_peer_state("0", "a") == KvStorePeerState.INITIALIZED
        assert b.get_key_vals("0", ["k1"]).key_vals["k1"].value == b"v1"

    def test_three_way_sync_sends_back_better_keys(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        b, _, b_syncs, _ = make("b")
        a.set_key_vals("0", {"k1": v(originator="a", value=b"v1")})
        b.set_key_vals("0", {"k2": v(version=7, originator="b", value=b"v2")})
        reader = b_syncs.get_reader()

        # bidirectional peering so the finalize leg can flood onward
        a.add_peers("0", {"b": spec("b")})
        b.add_peers("0", {"a": spec("a")})
        reader.get(timeout=5)

        # b learned k1 from the dump; a learned k2 from the finalize step
        assert b.get_key_vals("0", ["k1"]).key_vals["k1"].value == b"v1"
        assert wait_for(
            lambda: a.get_key_vals("0", ["k2"]).key_vals.get("k2") is not None
        )
        assert a.get_key_vals("0", ["k2"]).key_vals["k2"].version == 7

    def test_flooding_line_topology(self, fabric):
        fab, make = fabric
        a, _, _, _ = make("a")
        b, _, _, _ = make("b")
        c, _, _, _ = make("c")
        # line: a - b - c with bidirectional peering
        a.add_peers("0", {"b": spec("b")})
        b.add_peers("0", {"a": spec("a"), "c": spec("c")})
        c.add_peers("0", {"b": spec("b")})
        assert wait_for(
            lambda: c.get_peer_state("0", "b") == KvStorePeerState.INITIALIZED
            and a.get_peer_state("0", "b") == KvStorePeerState.INITIALIZED
            and b.get_peer_state("0", "a") == KvStorePeerState.INITIALIZED
            and b.get_peer_state("0", "c") == KvStorePeerState.INITIALIZED
        )

        a.set_key_vals("0", {"flood-key": v(originator="a", value=b"fv")})
        assert wait_for(
            lambda: c.get_key_vals("0", ["flood-key"]).key_vals.get("flood-key")
            is not None
        )
        # loop prevention: the publication doesn't bounce forever; nodeIds
        # trail carried the path
        counters = b.get_counters()
        assert counters.get("kvstore.looped_publications", 0) >= 0

    def test_publication_emitted_to_local_subscribers(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        reader = a_updates.get_reader()
        a.set_key_vals("0", {"k": v(originator="a")})
        pub = reader.get(timeout=5)
        assert "k" in pub.key_vals
        assert pub.node_ids == ["a"]

    def test_ttl_expiry(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        reader = a_updates.get_reader()
        # ttl must exceed the 500ms about-to-expire flood threshold or the
        # set is (correctly) never published at all
        a.set_key_vals("0", {"mortal": v(originator="a", ttl_ms=700)})
        pub = reader.get(timeout=5)  # the set itself
        assert "mortal" in pub.key_vals
        pub = reader.get(timeout=5)  # the expiry
        assert pub.expired_keys == ["mortal"]
        assert a.get_key_vals("0", ["mortal"]).key_vals == {}

    def test_ttl_decrement_on_sync(self, fabric):
        fab, make = fabric
        a, _, _, _ = make("a", ttl_decr_ms=100)
        b, _, b_syncs, _ = make("b")
        reader = b_syncs.get_reader()
        a.set_key_vals("0", {"k": v(originator="a", ttl_ms=60000)})
        b.add_peers("0", {"a": spec("a")})
        reader.get(timeout=5)
        got = b.get_key_vals("0", ["k"]).key_vals["k"]
        assert got.ttl_ms < 60000  # decremented in flight

    def test_partition_backoff_and_recovery(self, fabric):
        fab, make = fabric
        a, _, _, _ = make("a")
        b, _, b_syncs, _ = make("b")
        reader = b_syncs.get_reader()
        fab.set_partitioned("a", "b", True)
        a.set_key_vals("0", {"k": v(originator="a")})
        b.add_peers("0", {"a": spec("a")})
        time.sleep(0.3)
        assert b.get_peer_state("0", "a") == KvStorePeerState.IDLE
        fab.set_partitioned("a", "b", False)
        reader.get(timeout=10)  # backoff retry succeeds
        assert b.get_peer_state("0", "a") == KvStorePeerState.INITIALIZED
        assert b.get_key_vals("0", ["k"]).key_vals.get("k") is not None

    def test_areas_are_isolated(self, fabric):
        fab, make = fabric
        a, _, _, _ = make("a", areas=("0", "1"))
        a.set_key_vals("1", {"k": v(originator="a")})
        assert a.get_key_vals("0", ["k"]).key_vals == {}
        assert a.get_key_vals("1", ["k"]).key_vals["k"].value == b"x"


class TestKvStoreClient:
    def test_persist_key_ownership(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        evb = OpenrEventBase(name="client-evb")
        evb.run()
        try:
            client = KvStoreClientInternal(
                evb, "a", a, a_updates.get_reader(), check_persist_interval_s=60
            )
            client.persist_key("0", "my-key", b"mine")
            assert a.get_key_vals("0", ["my-key"]).key_vals["my-key"].value == b"mine"

            # another node overwrites with higher version -> we win it back
            a.set_key_vals(
                "0",
                {"my-key": v(version=5, originator="z", value=b"theirs")},
            )
            assert wait_for(
                lambda: (
                    lambda kv: kv is not None
                    and kv.value == b"mine"
                    and kv.version > 5
                )(a.get_key_vals("0", ["my-key"]).key_vals.get("my-key"))
            )
            client.stop()
        finally:
            evb.stop()
            evb.wait_until_stopped(5)

    def test_ttl_refresh_keeps_key_alive(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        evb = OpenrEventBase(name="client-evb2")
        evb.run()
        try:
            client = KvStoreClientInternal(
                evb, "a", a, a_updates.get_reader(), check_persist_interval_s=60
            )
            client.persist_key("0", "lively", b"val", ttl_ms=300)
            time.sleep(1.0)  # several TTL periods
            got = a.get_key_vals("0", ["lively"]).key_vals.get("lively")
            assert got is not None and got.ttl_version > 0
            client.stop()
        finally:
            evb.stop()
            evb.wait_until_stopped(5)

    def test_set_key_version_bump_and_subscribe(self, fabric):
        fab, make = fabric
        a, a_updates, _, _ = make("a")
        evb = OpenrEventBase(name="client-evb3")
        evb.run()
        try:
            client = KvStoreClientInternal(
                evb, "a", a, a_updates.get_reader(), check_persist_interval_s=60
            )
            seen = []
            client.subscribe_key("0", "s-key", lambda k, val: seen.append(val))
            client.set_key("0", "s-key", b"v1")
            assert wait_for(lambda: len(seen) >= 1)
            val2 = client.set_key("0", "s-key", b"v2")
            assert val2.version == 2  # auto-bumped
            client.stop()
        finally:
            evb.stop()
            evb.wait_until_stopped(5)


class TestCrdtConvergence:
    """Property: once every replica has seen the full update set, merge
    order and any divergent intermediate state must not matter (the
    guarantee the flooding mesh rests on; reference tie-break chain
    documented at KvStore.cpp:317-340)."""

    @staticmethod
    def _random_value(rng) -> Value:
        return v(
            version=rng.randint(1, 4),
            originator=rng.choice(["a", "b", "c"]),
            value=bytes([rng.randint(0, 3)]),
            ttl_version=rng.randint(0, 2),
        )

    @staticmethod
    def _canon(store: dict[str, Value]) -> dict:
        return {
            k: (val.version, val.originator_id, val.value, val.ttl_version)
            for k, val in store.items()
        }

    def test_order_and_start_state_independence(self):
        import random

        rng = random.Random(1234)
        keys = [f"k{i}" for i in range(6)]
        for trial in range(200):
            updates = [
                {
                    k: self._random_value(rng)
                    for k in rng.sample(keys, rng.randint(1, len(keys)))
                }
                for _ in range(rng.randint(2, 6))
            ]
            stores = []
            for _perm in range(3):
                store: dict[str, Value] = {}
                # divergent prefix: each replica first sees a random subset
                # (the pre-full-sync state), then the full set in a random
                # order — modelling anti-entropy catching a replica up.
                # Inputs are shared across replicas: merge_key_values never
                # mutates or retains its input values.
                prefix = rng.sample(updates, rng.randint(0, len(updates)))
                order = updates[:]
                rng.shuffle(order)
                for upd in list(prefix) + order:
                    merge_key_values(store, upd, None)
                stores.append(store)
            canon = [self._canon(s) for s in stores]
            assert canon[0] == canon[1] == canon[2], (trial, canon)
