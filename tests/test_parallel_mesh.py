"""Sharded-mesh SPF tests on the virtual 8-device CPU mesh (conftest forces
JAX_PLATFORMS=cpu with xla_force_host_platform_device_count=8).

Covers openr_tpu/parallel/mesh.py — the multi-chip layout the driver
dry-runs — plus the __graft_entry__ dryrun itself, so a sharding regression
is caught by pytest rather than only by the driver.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.decision.link_state import LinkState
from openr_tpu.parallel.mesh import make_mesh, sharded_spf_forward, spf_step_sharded
from openr_tpu.utils.topo import grid_topology


def _grid_csr(n_side: int) -> CsrTopology:
    ls = LinkState()
    for db in grid_topology(n_side):
        ls.update_adjacency_database(db)
    return CsrTopology.from_link_state(ls)


def _pad_sources(n: int, batch_axis: int) -> np.ndarray:
    sources = np.arange(n, dtype=np.int32)
    per = -(-n // batch_axis)
    pad = batch_axis * per - n
    if pad:
        sources = np.concatenate([sources, np.zeros(pad, dtype=np.int32)])
    return sources


@pytest.fixture(scope="module")
def eight_cpu_devices():
    devices = jax.devices("cpu")
    if len(devices) < 8:
        pytest.skip("needs xla_force_host_platform_device_count=8")
    return devices[:8]


class TestMeshSpf:
    def test_batch_only_mesh_matches_single_device(self, eight_cpu_devices):
        """8x1 mesh (collective-free layout): sharded distances must equal
        the unsharded kernel's output exactly."""
        from openr_tpu.ops.sssp import spf_forward

        csr = _grid_csr(4)
        mesh = make_mesh(eight_cpu_devices)  # all devices on "batch"
        sources = _pad_sources(csr.n_nodes, 8)

        dist_sharded, dag_sharded = sharded_spf_forward(
            mesh,
            sources,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        dist_ref, dag_ref = spf_forward(
            sources,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        np.testing.assert_array_equal(
            np.asarray(dist_sharded), np.asarray(dist_ref)
        )
        np.testing.assert_array_equal(
            np.asarray(dag_sharded), np.asarray(dag_ref)
        )

    def test_2d_mesh_node_axis_collectives(self, eight_cpu_devices):
        """4x2 mesh: the [S, N] distance tensor is sharded over the node
        axis too, forcing cross-shard gathers; results must be unchanged."""
        from openr_tpu.ops.sssp import spf_forward

        csr = _grid_csr(4)
        assert csr.node_capacity % 2 == 0
        mesh = make_mesh(eight_cpu_devices, batch_axis=4)
        sources = _pad_sources(csr.n_nodes, 4)

        step = spf_step_sharded(mesh)
        s_batch = NamedSharding(mesh, P("batch"))
        s_repl = NamedSharding(mesh, P())
        dist, dag = step(
            jax.device_put(sources, s_batch),
            jax.device_put(csr.ell, s_repl),
            jax.device_put(np.asarray(csr.edge_src), s_repl),
            jax.device_put(np.asarray(csr.edge_dst), s_repl),
            jax.device_put(np.asarray(csr.edge_metric), s_repl),
            jax.device_put(np.asarray(csr.edge_up), s_repl),
            jax.device_put(np.asarray(csr.node_overloaded), s_repl),
        )
        jax.block_until_ready((dist, dag))
        # output sharding: dist over ("batch", "node")
        assert dist.sharding.spec == P("batch", "node")

        dist_ref, _ = spf_forward(
            sources,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        np.testing.assert_array_equal(np.asarray(dist), np.asarray(dist_ref))

    def test_distance_values_on_grid(self, eight_cpu_devices):
        """Spot-check actual metrics: corner-to-corner on a unit 4x4 grid."""
        csr = _grid_csr(4)
        mesh = make_mesh(eight_cpu_devices, batch_axis=4)
        sources = _pad_sources(csr.n_nodes, 4)
        dist, _ = sharded_spf_forward(
            mesh,
            sources,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        d = np.asarray(dist)
        a = csr.node_id["node-0-0"]
        b = csr.node_id["node-3-3"]
        assert d[a, b] == 6
        assert d[b, a] == 6
        assert d[a, a] == 0


class TestGraftDryrun:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_dryrun_multichip(self, n, eight_cpu_devices):
        import __graft_entry__ as graft

        graft.dryrun_multichip(n)


class TestMeshWhatIf:
    def test_sharded_whatif_matches_masked_kernel(self, eight_cpu_devices):
        """Failure-scenario fleet sharded over the mesh: each of 16 rows
        fails one link (both directions); distances must equal the
        unsharded masked kernel exactly."""
        from openr_tpu.ops.sssp import spf_forward_ell_masked
        from openr_tpu.parallel.mesh import whatif_step_sharded

        csr = _grid_csr(6)
        n_rows = 16
        rng = np.random.default_rng(3)
        fail = rng.integers(0, csr.n_edges, size=n_rows)
        mask = np.ones((n_rows, csr.edge_capacity), dtype=bool)
        for row, e in enumerate(fail):
            mask[row, e] = False
            # reverse directed edge of the same link
            src, dst = csr.edge_src[e], csr.edge_dst[e]
            for e2 in range(csr.n_edges):
                if csr.edge_src[e2] == dst and csr.edge_dst[e2] == src:
                    mask[row, e2] = False
                    break
        sources = np.zeros(n_rows, dtype=np.int32)

        ref_dist, ref_dag = spf_forward_ell_masked(
            sources,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            mask,
        )

        mesh = make_mesh(eight_cpu_devices, batch_axis=4)  # 4 x 2
        s_batch = NamedSharding(mesh, P("batch"))
        s_mask_t = NamedSharding(mesh, P(None, "batch"))
        s_repl = NamedSharding(mesh, P())
        step = whatif_step_sharded(mesh)
        dist, dag = step(
            jax.device_put(sources, s_batch),
            jax.device_put(csr.ell, s_repl),
            jax.device_put(np.asarray(csr.edge_src), s_repl),
            jax.device_put(np.asarray(csr.edge_dst), s_repl),
            jax.device_put(np.asarray(csr.edge_metric), s_repl),
            jax.device_put(np.asarray(csr.edge_up), s_repl),
            jax.device_put(np.asarray(csr.node_overloaded), s_repl),
            jax.device_put(mask.T.copy(), s_mask_t),
        )
        np.testing.assert_array_equal(np.asarray(dist), np.asarray(ref_dist))
        np.testing.assert_array_equal(np.asarray(dag), np.asarray(ref_dag))


def _fat_tree_link_state(
    pods: int = 8, planes: int = 4, ssw_per_plane: int = 6, rsw_per_pod: int = 64
) -> LinkState:
    """Fat-tree fabric as a LinkState — built from the product generator
    (openr_tpu.utils.topo.fabric_topology) so the test validates the same
    wiring the bench rows use."""
    from openr_tpu.utils.topo import fabric_topology

    ls = LinkState()
    for db in fabric_topology(
        pods, planes=planes, ssw_per_plane=ssw_per_plane, rsw_per_pod=rsw_per_pod
    ):
        ls.update_adjacency_database(db)
    return ls


class TestMeshThroughSolver:
    def test_fat_tree_mesh_prefetch_route_equality(self, eight_cpu_devices):
        """VERDICT r2 #8: a realistically-sized fabric sharded over the
        8-device mesh, driven through DeviceSpfBackend ->
        SpfSolver.build_route_db, must produce route-level equality with
        the host-Dijkstra backend — ECMP sets, MPLS labels and all."""
        from openr_tpu.decision.prefix_state import PrefixState
        from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
        from openr_tpu.types import PrefixEntry

        ls = _fat_tree_link_state()
        nodes = ls.node_names
        assert len(nodes) > 500  # realistic fabric, not a toy
        ps = PrefixState()
        for i in range(0, len(nodes), 16):
            ps.update_prefix(
                nodes[i], "0", PrefixEntry(prefix=f"fc00:{i:x}::/64")
            )

        mesh = make_mesh(eight_cpu_devices)
        backend = DeviceSpfBackend(min_device_nodes=64, min_device_sources=1)
        # prefetch EVERY node's SPF through the sharded mesh step
        backend.prefetch_via_mesh(ls, nodes, mesh)

        for my_node in ("rsw-0-0", "fsw-3-2", "ssw-1-4"):
            dev_solver = SpfSolver(my_node, spf_backend=backend)
            host_solver = SpfSolver(my_node)
            rdb_dev = dev_solver.build_route_db({"0": ls}, ps)
            rdb_host = host_solver.build_route_db({"0": ls}, ps)
            assert rdb_dev.unicast_routes == rdb_host.unicast_routes
            assert rdb_dev.mpls_routes == rdb_host.mpls_routes

    def test_whatif_fleet_1k_variants(self, eight_cpu_devices):
        """A 1k-variant failure fleet sharded over the mesh matches the
        single-device masked kernel row-for-row."""
        import numpy as np

        from openr_tpu.ops.sssp import spf_forward_ell_masked
        from openr_tpu.parallel.mesh import whatif_step_sharded

        csr = _grid_csr(8)  # 64 nodes
        n_variants = 1024
        rng = np.random.default_rng(7)
        fail = rng.integers(0, csr.n_edges, size=n_variants)
        mask = np.ones((n_variants, csr.edge_capacity), dtype=bool)
        mask[np.arange(n_variants), fail] = False
        sources = rng.integers(
            0, csr.n_nodes, size=n_variants
        ).astype(np.int32)

        mesh = make_mesh(eight_cpu_devices)
        step = whatif_step_sharded(mesh)
        dist_m, dag_m = step(
            sources,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            np.ascontiguousarray(mask.T),  # step takes edge-major [E, S]
        )
        dist_1, dag_1 = spf_forward_ell_masked(
            sources,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            mask,
        )
        np.testing.assert_array_equal(np.asarray(dist_m), np.asarray(dist_1))
        np.testing.assert_array_equal(np.asarray(dag_m), np.asarray(dag_1))


class TestShardingLinearity:
    def test_per_device_flops_divide_by_batch_factor(self, eight_cpu_devices):
        """The linear-scaling assumption behind the multi-chip
        projections, validated structurally (r3 next #8): the per-device
        compiled FLOPs of the sharded SPF step must divide by the
        batch-axis factor (no hidden replication), and the batch-only
        layout's collectives must be only the O(1) convergence-verdict
        scalar reductions.  Full artifact: benchmarks/mesh_scaling.py
        (run by bench.py into bench_details.json)."""
        import jax
        import jax.numpy as jnp

        from benchmarks import synthetic
        from benchmarks.mesh_scaling import _collect
        from openr_tpu.parallel import mesh as pmesh

        topo = synthetic.grid(16)  # 256 nodes
        sources = jnp.arange(256, dtype=jnp.int32)
        args = (
            sources,
            topo.ell,
            jnp.asarray(topo.edge_src),
            jnp.asarray(topo.edge_dst),
            jnp.asarray(topo.edge_metric),
            jnp.asarray(topo.edge_up),
            jnp.asarray(topo.node_overloaded),
        )
        rows = {}
        for b in (1, 8):
            mesh = pmesh.make_mesh(eight_cpu_devices[:b], batch_axis=b)
            rows[b] = _collect(
                pmesh.spf_step_sharded(mesh), args, f"batch={b}"
            )
        ratio = rows[8]["flops_per_device"] / rows[1]["flops_per_device"]
        # near 1/8 with slack for the O(1) verdict/bookkeeping terms
        assert 0.1 < ratio < 0.2, ratio
        # only the scalar convergence reductions may appear as collectives
        assert rows[8]["collective_ops"] <= 4, rows[8]["collective_ops"]


class TestShardedFleetProduct:
    """The reduced all-sources product with the DEST axis sharded over
    the mesh batch axis (parallel/mesh.fleet_product_sharded) must equal
    the single-device product bit-for-bit, and stay collective-free in
    the relax/bitmap (only the verdict reduces)."""

    def test_matches_single_device_product(self, eight_cpu_devices):
        from benchmarks.synthetic import reversed_topology, wan
        from openr_tpu.ops import allsources as asrc
        from openr_tpu.parallel.mesh import fleet_product_sharded

        topo = wan(256, chords=2, seed=9)
        rev = reversed_topology(topo)
        runner = rev.runner
        assert runner.bg is not None  # banded path required
        rng = np.random.default_rng(3)
        dests = np.sort(
            rng.choice(topo.n_nodes, size=32, replace=False).astype(
                np.int32
            )
        )
        out = asrc.build_out_ell(
            topo.edge_src, topo.edge_dst, topo.n_edges, topo.n_nodes
        )

        # single-device reference (adaptive: learns the sweep count)
        dist_ref, bitmap_ref, ok = asrc.reduced_all_sources(
            dests,
            runner,
            out,
            topo.edge_metric,
            topo.edge_up,
            topo.node_overloaded,
        )
        assert bool(ok)

        mesh = make_mesh(eight_cpu_devices)  # 8x1, dest axis sharded
        step = fleet_product_sharded(
            mesh,
            n_sweeps=runner.hint,
            n_words=out.n_words,
            depth=runner.depth,
            resid_rounds=runner.resid_rounds,
            small_dist=runner.small_dist,
            chord_mode=runner.chord_mode,
        )
        es, ed, em, eu, ov = runner.arrays
        import jax.numpy as jnp

        dist_sh, bitmap_sh, ok_sh = step(
            dests,
            runner.bg,
            jnp.asarray(es),
            jnp.asarray(ed),
            jnp.asarray(em),
            jnp.asarray(eu),
            jnp.asarray(ov),
            out,
            jnp.asarray(topo.edge_metric),
            jnp.asarray(topo.edge_up),
        )
        assert bool(ok_sh)
        np.testing.assert_array_equal(
            np.asarray(dist_sh), np.asarray(dist_ref)
        )
        np.testing.assert_array_equal(
            np.asarray(bitmap_sh), np.asarray(bitmap_ref)
        )
        # the dest axis really is sharded over the 8 devices
        assert len(dist_sh.sharding.device_set) == 8

    def test_drain_semantics_survive_sharding(self, eight_cpu_devices):
        from benchmarks.synthetic import reversed_topology, wan
        from openr_tpu.ops import allsources as asrc
        from openr_tpu.parallel.mesh import fleet_product_sharded

        topo = wan(128, chords=2, seed=5)
        topo.node_overloaded[[7, 40]] = True
        topo.edge_up[np.arange(0, topo.n_edges, 17)] = False
        rev = reversed_topology(topo)
        runner = rev.runner
        if runner.bg is None:
            pytest.skip("banded decomposition not found at this size")
        rng = np.random.default_rng(4)
        # exactly 16 dests (batch axis 8 requires divisibility), with the
        # two drained nodes among them
        pool = np.setdiff1d(np.arange(topo.n_nodes), [7, 40])
        dests = np.sort(
            np.concatenate(
                [rng.choice(pool, size=14, replace=False), [7, 40]]
            )
        ).astype(np.int32)
        out = asrc.build_out_ell(
            topo.edge_src, topo.edge_dst, topo.n_edges, topo.n_nodes
        )
        dist_ref, bitmap_ref, ok = asrc.reduced_all_sources(
            dests, runner, out, topo.edge_metric, topo.edge_up,
            topo.node_overloaded,
        )
        assert bool(ok)
        mesh = make_mesh(eight_cpu_devices)
        step = fleet_product_sharded(
            mesh,
            n_sweeps=runner.hint,
            n_words=out.n_words,
            depth=runner.depth,
            resid_rounds=runner.resid_rounds,
            small_dist=runner.small_dist,
            chord_mode=runner.chord_mode,
        )
        es, ed, em, eu, ov = runner.arrays
        import jax.numpy as jnp

        dist_sh, bitmap_sh, ok_sh = step(
            dests, runner.bg, jnp.asarray(es), jnp.asarray(ed),
            jnp.asarray(em), jnp.asarray(eu), jnp.asarray(ov), out,
            jnp.asarray(topo.edge_metric), jnp.asarray(topo.edge_up),
        )
        assert bool(ok_sh)
        np.testing.assert_array_equal(
            np.asarray(dist_sh), np.asarray(dist_ref)
        )
        np.testing.assert_array_equal(
            np.asarray(bitmap_sh), np.asarray(bitmap_ref)
        )
