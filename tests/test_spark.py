"""Spark tests: multi-node discovery without a network.

Modeled on the reference's SparkTest.cpp (openr/spark/tests/): each Spark
gets a MockIoProvider endpoint simulating connected interfaces with
configurable latency.
"""

from __future__ import annotations

import time

import pytest

from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.spark import (
    AreaConfig,
    MockIoProvider,
    Spark,
    SparkConfig,
    SparkNeighState,
)
from openr_tpu.types import (
    InterfaceDatabase,
    InterfaceInfo,
    NeighborEvent,
    NeighborEventType,
)

FAST_CFG = SparkConfig(
    hello_time_s=0.2,
    fastinit_hello_time_s=0.02,
    keepalive_time_s=0.05,
    hold_time_s=0.3,
    graceful_restart_time_s=0.6,
    negotiate_hold_time_s=0.5,
)


def if_db(node: str, *ifs: str, up: bool = True) -> InterfaceDatabase:
    return InterfaceDatabase(
        this_node_name=node,
        interfaces={
            name: InterfaceInfo(if_name=name, is_up=up, if_index=i + 1)
            for i, name in enumerate(ifs)
        },
    )


class SparkHarness:
    def __init__(self):
        self.fabric = MockIoProvider()
        self.nodes: dict[str, Spark] = {}
        self.if_queues: dict[str, ReplicateQueue] = {}
        self.event_readers: dict[str, object] = {}

    def add_node(self, name: str, *, areas=None, config=FAST_CFG, domain="openr"):
        ifq: ReplicateQueue = ReplicateQueue()
        nbrq: ReplicateQueue[NeighborEvent] = ReplicateQueue()
        reader = nbrq.get_reader()
        spark = Spark(
            name,
            ifq.get_reader(),
            nbrq,
            self.fabric.endpoint(name),
            config=config,
            areas=areas,
            domain=domain,
        )
        spark.run()
        self.nodes[name] = spark
        self.if_queues[name] = ifq
        self.event_readers[name] = reader
        return spark, reader

    def bring_up(self, node: str, *ifs: str):
        self.if_queues[node].push(if_db(node, *ifs))

    def next_event(self, node: str, timeout=5.0) -> NeighborEvent:
        return self.event_readers[node].get(timeout=timeout)

    def wait_event(self, node: str, event_type, timeout=5.0) -> NeighborEvent:
        deadline = time.monotonic() + timeout
        while True:
            ev = self.event_readers[node].get(
                timeout=max(0.05, deadline - time.monotonic())
            )
            if ev.event_type == event_type:
                return ev

    def stop(self):
        for q in self.if_queues.values():
            q.close()
        for spark in self.nodes.values():
            spark.stop()
        for spark in self.nodes.values():
            spark.wait_until_stopped(5)


@pytest.fixture
def harness():
    h = SparkHarness()
    yield h
    h.stop()


class TestSpark:
    def test_two_nodes_establish(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")

        ev1 = harness.wait_event("node1", NeighborEventType.NEIGHBOR_UP)
        ev2 = harness.wait_event("node2", NeighborEventType.NEIGHBOR_UP)
        assert ev1.node_name == "node2" and ev1.if_name == "if1"
        assert ev2.node_name == "node1" and ev2.if_name == "if2"
        assert ev1.area == "0" and ev2.area == "0"
        assert ev1.neighbor_addr_v6 == "fe80::node2"
        assert (
            harness.nodes["node1"].get_neigh_state("if1", "node2")
            == SparkNeighState.ESTABLISHED
        )

    def test_three_nodes_shared_segment(self, harness):
        for n in ("a", "b", "c"):
            harness.add_node(n)
        harness.fabric.connect("a", "if1", "b", "if1")
        harness.fabric.connect("a", "if1", "c", "if1")
        harness.fabric.connect("b", "if1", "c", "if1")
        for n in ("a", "b", "c"):
            harness.bring_up(n, "if1")
        up_a = {
            harness.wait_event("a", NeighborEventType.NEIGHBOR_UP).node_name
            for _ in range(2)
        }
        assert up_a == {"b", "c"}

    def test_heartbeat_hold_expiry_neighbor_down(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node1", NeighborEventType.NEIGHBOR_UP)

        harness.fabric.disconnect("node1", "if1", "node2", "if2")
        ev = harness.wait_event("node1", NeighborEventType.NEIGHBOR_DOWN)
        assert ev.node_name == "node2"
        assert (
            harness.nodes["node1"].get_neigh_state("if1", "node2")
            == SparkNeighState.IDLE
        )

    def test_interface_down_neighbor_down(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node1", NeighborEventType.NEIGHBOR_UP)
        harness.wait_event("node2", NeighborEventType.NEIGHBOR_UP)

        # node1 takes if1 down
        harness.if_queues["node1"].push(if_db("node1"))
        ev = harness.wait_event("node1", NeighborEventType.NEIGHBOR_DOWN)
        assert ev.node_name == "node2"
        # node2 eventually times out too
        harness.wait_event("node2", NeighborEventType.NEIGHBOR_DOWN)

    def test_graceful_restart(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node2", NeighborEventType.NEIGHBOR_UP)

        harness.nodes["node1"].flood_restarting_msg()
        ev = harness.wait_event("node2", NeighborEventType.NEIGHBOR_RESTARTING)
        assert ev.node_name == "node1"
        assert (
            harness.nodes["node2"].get_neigh_state("if2", "node1")
            == SparkNeighState.RESTART
        )

        # node1 comes back (stop announcing restart) -> RESTARTED
        harness.nodes["node1"].run_in_event_base_thread(
            lambda: setattr(harness.nodes["node1"], "_restarting", False)
        ).result()
        ev = harness.wait_event("node2", NeighborEventType.NEIGHBOR_RESTARTED)
        assert ev.node_name == "node1"
        assert (
            harness.nodes["node2"].get_neigh_state("if2", "node1")
            == SparkNeighState.ESTABLISHED
        )

    def test_gr_expiry_goes_down(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node2", NeighborEventType.NEIGHBOR_UP)

        # node1 announces restart then vanishes entirely
        harness.nodes["node1"].flood_restarting_msg()
        harness.wait_event("node2", NeighborEventType.NEIGHBOR_RESTARTING)
        harness.fabric.disconnect("node1", "if1", "node2", "if2")
        ev = harness.wait_event("node2", NeighborEventType.NEIGHBOR_DOWN)
        assert ev.node_name == "node1"

    def test_area_mismatch_no_adjacency(self, harness):
        harness.add_node(
            "node1", areas=[AreaConfig(area_id="1", neighbor_regexes=["node2"])]
        )
        harness.add_node(
            "node2", areas=[AreaConfig(area_id="2", neighbor_regexes=["node1"])]
        )
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        time.sleep(1.0)
        assert harness.nodes["node1"].get_neigh_state("if1", "node2") in (
            SparkNeighState.WARM,
            SparkNeighState.NEGOTIATE,
        )
        with pytest.raises(TimeoutError):
            harness.event_readers["node1"].get(timeout=0.1)

    def test_domain_mismatch_ignored(self, harness):
        harness.add_node("node1", domain="d1")
        harness.add_node("node2", domain="d2")
        harness.fabric.connect("node1", "if1", "node2", "if2")
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        time.sleep(0.5)
        assert harness.nodes["node1"].get_neigh_state("if1", "node2") is None

    def test_rtt_measured_with_latency(self, harness):
        harness.add_node("node1")
        harness.add_node("node2")
        # 25ms one-way latency -> ~50ms RTT
        harness.fabric.connect("node1", "if1", "node2", "if2", latency_s=0.025)
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node1", NeighborEventType.NEIGHBOR_UP, timeout=10)

        deadline = time.monotonic() + 5
        rtt = 0
        while time.monotonic() < deadline:
            neighbors = harness.nodes["node1"].get_neighbors()
            if neighbors and neighbors[0].rtt_latest_us > 0:
                rtt = neighbors[0].rtt_latest_us
                break
            time.sleep(0.05)
        assert 30_000 <= rtt <= 200_000, rtt

    def test_rtt_stable_under_receiver_load(self, harness):
        """RTTs come from transport-level (kernel-equivalent) receive
        timestamps, so a busy receiver event loop must NOT inflate them
        (reference: SO_TIMESTAMPNS, Spark.cpp:447-448; the fabric stamps
        packets at simulated arrival time, not at callback drain time)."""
        harness.add_node("node1")
        harness.add_node("node2")
        harness.fabric.connect("node1", "if1", "node2", "if2", latency_s=0.01)
        harness.bring_up("node1", "if1")
        harness.bring_up("node2", "if2")
        harness.wait_event("node1", NeighborEventType.NEIGHBOR_UP, timeout=10)

        # induce scheduler load: park blocking work on BOTH spark loops so
        # packet callbacks drain late (each stall >> the 20ms true RTT)
        def stall():
            time.sleep(0.05)

        stop = time.monotonic() + 2.0
        samples: list[int] = []
        while time.monotonic() < stop:
            for node in ("node1", "node2"):
                harness.nodes[node].run_in_event_base_thread(stall)
            neighbors = harness.nodes["node1"].get_neighbors()
            if neighbors and neighbors[0].rtt_latest_us > 0:
                samples.append(neighbors[0].rtt_latest_us)
            time.sleep(0.05)
        assert samples, "no RTT samples under load"
        # true RTT is 20ms; userspace-stamped arrivals would read the
        # ~50ms loop stalls on top (flaky >> 40ms).  Allow modest jitter.
        assert min(samples) < 40_000, samples


class TestRealUdpTransport:
    def test_discovery_over_veth_with_kernel_timestamps(self):
        """Two Sparks over a REAL veth pair + IPv6 link-local multicast:
        discovery must survive the cold-start window where IPv6 DAD makes
        multicast sends fail (a raised send must not kill the hello timer
        chain), and the measured RTT must come from kernel SO_TIMESTAMPNS
        stamps (sane single-digit-ms magnitude)."""
        import subprocess
        import uuid

        from openr_tpu.spark import UdpIoProvider
        from tests.test_netlink import NET_ADMIN

        if not NET_ADMIN:
            pytest.skip("needs NET_ADMIN (veth creation)")

        name = f"su{uuid.uuid4().hex[:8]}"
        peer = f"{name}p"
        subprocess.run(
            ["ip", "link", "add", name, "type", "veth", "peer", "name", peer],
            check=True,
        )
        sparks = []
        queues = []
        try:
            for dev in (name, peer):
                subprocess.run(["ip", "link", "set", dev, "up"], check=True)
            # deliberately NO wait for DAD: the first hellos must fail
            # and the periodic timer must retry through it
            reader = None
            for node, ifn in (("udp-a", name), ("udp-b", peer)):
                ifq: ReplicateQueue = ReplicateQueue()
                nbrq: ReplicateQueue = ReplicateQueue()
                if node == "udp-a":
                    reader = nbrq.get_reader()
                s = Spark(
                    node,
                    ifq.get_reader(),
                    nbrq,
                    io_provider=UdpIoProvider(port=16661),
                    config=FAST_CFG,
                )
                s.run()
                ifq.push(if_db(node, ifn))
                sparks.append(s)
                queues.extend([ifq, nbrq])
            deadline = time.monotonic() + 30
            up = False
            while time.monotonic() < deadline and not up:
                try:
                    ev = reader.get(timeout=1)
                    up = ev.event_type == NeighborEventType.NEIGHBOR_UP
                except Exception:
                    pass
            assert up, "discovery did not converge over real UDP"
            rtt = 0
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and rtt <= 0:
                nb = sparks[0].get_neighbors()
                if nb:
                    rtt = nb[0].rtt_latest_us
                time.sleep(0.1)
            assert 0 < rtt < 100_000, rtt
        finally:
            for q in queues:
                q.close()
            for s in sparks:
                s.stop()
            for s in sparks:
                s.wait_until_stopped(5)
            subprocess.run(["ip", "link", "del", name], capture_output=True)
