"""Conformance: the batched TPU SSSP kernel must reproduce the host
Dijkstra oracle (which itself mirrors the reference runSpf,
openr/decision/LinkState.cpp:809-878) — distances, tie-retaining path links,
and first-hop (ECMP next-hop) sets — on every topology class."""

import numpy as np
import pytest

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.utils.topo import (
    fat_tree_topology,
    grid_topology,
    random_topology,
    ring_topology,
)

from test_link_state import adj, adj_db, build


def assert_spf_equal(oracle, device, src):
    assert set(oracle) == set(device), f"reachable set mismatch from {src}"
    for node, o in oracle.items():
        d = device[node]
        assert o.metric == d.metric, f"{src}->{node} metric {o.metric} != {d.metric}"
        assert o.next_hops == d.next_hops, (
            f"{src}->{node} next_hops {o.next_hops} != {d.next_hops}"
        )
        o_links = {(l, p) for l, p in o.path_links}
        d_links = {(l, p) for l, p in d.path_links}
        assert o_links == d_links, f"{src}->{node} path_links differ"


def check_all_sources(ls: LinkState, use_link_metric=True):
    csr = CsrTopology.from_link_state(ls)
    sources = [n for n in ls.node_names]
    device_results = csr.spf_from(sources, use_link_metric)
    for src in sources:
        oracle = ls.run_spf(src, use_link_metric)
        assert_spf_equal(oracle, device_results[src], src)


class TestKernelParity:
    def test_two_node(self):
        ls = build(
            [
                adj_db("a", [adj("a", "b", metric=5)]),
                adj_db("b", [adj("b", "a", metric=7)]),
            ]
        )
        check_all_sources(ls)

    def test_ecmp_square(self):
        ls = build(
            [
                adj_db("a", [adj("a", "b"), adj("a", "c")]),
                adj_db("b", [adj("b", "a"), adj("b", "d")]),
                adj_db("c", [adj("c", "a"), adj("c", "d")]),
                adj_db("d", [adj("d", "b"), adj("d", "c")]),
            ]
        )
        check_all_sources(ls)

    def test_grid(self):
        ls = build(grid_topology(4))
        check_all_sources(ls)

    def test_grid_weighted(self):
        ls = build(grid_topology(4, metric_fn=lambda r, c, d: (r * 7 + c * 3) % 5 + 1))
        check_all_sources(ls)

    def test_fat_tree(self):
        ls = build(fat_tree_topology(3))
        check_all_sources(ls)

    def test_ring(self):
        ls = build(ring_topology(7))
        check_all_sources(ls)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_asymmetric(self, seed):
        ls = build(random_topology(24, 30, seed=seed))
        check_all_sources(ls)

    def test_random_unweighted_metric(self):
        ls = build(random_topology(16, 20, seed=9))
        check_all_sources(ls, use_link_metric=False)

    def test_node_overload_drain(self):
        dbs = grid_topology(4)
        ls = build(dbs)
        # overload an interior node
        victim = "node-1-1"
        db = next(d for d in dbs if d.this_node_name == victim)
        db.is_overloaded = True
        ls.update_adjacency_database(db)
        check_all_sources(ls)

    def test_link_overload(self):
        dbs = grid_topology(3)
        ls = build(dbs)
        db = next(d for d in dbs if d.this_node_name == "node-0-0")
        db.adjacencies[0].is_overloaded = True
        ls.update_adjacency_database(db)
        check_all_sources(ls)

    def test_disconnected_components(self):
        dbs = ring_topology(4) + [
            adj_db("x", [adj("x", "y")]),
            adj_db("y", [adj("y", "x")]),
        ]
        ls = build(dbs)
        check_all_sources(ls)

    def test_isolated_source(self):
        """Source with no links: result contains only itself."""
        dbs = ring_topology(4)
        ls = build(dbs)
        ls.update_adjacency_database(adj_db("lonely", []))
        oracle = ls.run_spf("lonely")
        assert set(oracle) == {"lonely"}
        csr = CsrTopology.from_link_state(ls)
        res = csr.spf_from(["lonely"])["lonely"]
        assert set(res) == {"lonely"}


class TestDeviceFirstHops:
    """first_hop_matrix on device must agree with oracle next_hops."""

    @pytest.mark.parametrize("seed", [0, 5])
    def test_random(self, seed):
        import jax.numpy as jnp

        from openr_tpu.ops import first_hop_matrix
        from openr_tpu.ops.sssp import (
            batched_sssp,
            make_dist0,
            make_relax_allowed,
            sp_dag_mask,
        )

        ls = build(random_topology(18, 22, seed=seed))
        csr = CsrTopology.from_link_state(ls)
        sources = ls.node_names
        src_ids = jnp.asarray([csr.node_id[s] for s in sources], dtype=jnp.int32)
        e_src = jnp.asarray(csr.edge_src)
        e_dst = jnp.asarray(csr.edge_dst)
        metric = jnp.asarray(csr.edge_metric)
        allowed = make_relax_allowed(
            src_ids, e_src, jnp.asarray(csr.edge_up), jnp.asarray(csr.node_overloaded)
        )
        dist = batched_sssp(
            make_dist0(src_ids, csr.node_capacity), e_src, e_dst, metric, allowed
        )
        dag = sp_dag_mask(dist, e_src, e_dst, metric, allowed)
        edge_slot, slot_names = csr.build_edge_slots(sources)
        n_slots = max(1, csr.max_degree)
        nh = np.asarray(
            first_hop_matrix(
                dag, dist, e_src, e_dst, jnp.asarray(edge_slot), n_slots
            )
        )
        for row, src in enumerate(sources):
            oracle = ls.run_spf(src)
            for node, o in oracle.items():
                if node == src:
                    continue
                nid = csr.node_id[node]
                got = {
                    slot_names[row][j]
                    for j in range(len(slot_names[row]))
                    if nh[row, nid, j]
                }
                assert got == o.next_hops, (src, node, got, o.next_hops)
