"""Conformance: the batched TPU SSSP kernel must reproduce the host
Dijkstra oracle (which itself mirrors the reference runSpf,
openr/decision/LinkState.cpp:809-878) — distances, tie-retaining path links,
and first-hop (ECMP next-hop) sets — on every topology class."""

import numpy as np
import pytest

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.utils.topo import (
    fat_tree_topology,
    grid_topology,
    random_topology,
    ring_topology,
)

from test_link_state import adj, adj_db, build


def assert_spf_equal(oracle, device, src):
    assert set(oracle) == set(device), f"reachable set mismatch from {src}"
    for node, o in oracle.items():
        d = device[node]
        assert o.metric == d.metric, f"{src}->{node} metric {o.metric} != {d.metric}"
        assert o.next_hops == d.next_hops, (
            f"{src}->{node} next_hops {o.next_hops} != {d.next_hops}"
        )
        o_links = {(l, p) for l, p in o.path_links}
        d_links = {(l, p) for l, p in d.path_links}
        assert o_links == d_links, f"{src}->{node} path_links differ"


def check_all_sources(ls: LinkState, use_link_metric=True):
    csr = CsrTopology.from_link_state(ls)
    sources = [n for n in ls.node_names]
    device_results = csr.spf_from(sources, use_link_metric)
    for src in sources:
        oracle = ls.run_spf(src, use_link_metric)
        assert_spf_equal(oracle, device_results[src], src)


class TestKernelParity:
    def test_two_node(self):
        ls = build(
            [
                adj_db("a", [adj("a", "b", metric=5)]),
                adj_db("b", [adj("b", "a", metric=7)]),
            ]
        )
        check_all_sources(ls)

    def test_ecmp_square(self):
        ls = build(
            [
                adj_db("a", [adj("a", "b"), adj("a", "c")]),
                adj_db("b", [adj("b", "a"), adj("b", "d")]),
                adj_db("c", [adj("c", "a"), adj("c", "d")]),
                adj_db("d", [adj("d", "b"), adj("d", "c")]),
            ]
        )
        check_all_sources(ls)

    def test_grid(self):
        ls = build(grid_topology(4))
        check_all_sources(ls)

    def test_grid_weighted(self):
        ls = build(grid_topology(4, metric_fn=lambda r, c, d: (r * 7 + c * 3) % 5 + 1))
        check_all_sources(ls)

    def test_fat_tree(self):
        ls = build(fat_tree_topology(3))
        check_all_sources(ls)

    def test_ring(self):
        ls = build(ring_topology(7))
        check_all_sources(ls)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_asymmetric(self, seed):
        ls = build(random_topology(24, 30, seed=seed))
        check_all_sources(ls)

    def test_random_unweighted_metric(self):
        ls = build(random_topology(16, 20, seed=9))
        check_all_sources(ls, use_link_metric=False)

    def test_node_overload_drain(self):
        dbs = grid_topology(4)
        ls = build(dbs)
        # overload an interior node
        victim = "node-1-1"
        db = next(d for d in dbs if d.this_node_name == victim)
        db.is_overloaded = True
        ls.update_adjacency_database(db)
        check_all_sources(ls)

    def test_link_overload(self):
        dbs = grid_topology(3)
        ls = build(dbs)
        db = next(d for d in dbs if d.this_node_name == "node-0-0")
        db.adjacencies[0].is_overloaded = True
        ls.update_adjacency_database(db)
        check_all_sources(ls)

    def test_disconnected_components(self):
        dbs = ring_topology(4) + [
            adj_db("x", [adj("x", "y")]),
            adj_db("y", [adj("y", "x")]),
        ]
        ls = build(dbs)
        check_all_sources(ls)

    def test_isolated_source(self):
        """Source with no links: result contains only itself."""
        dbs = ring_topology(4)
        ls = build(dbs)
        ls.update_adjacency_database(adj_db("lonely", []))
        oracle = ls.run_spf("lonely")
        assert set(oracle) == {"lonely"}
        csr = CsrTopology.from_link_state(ls)
        res = csr.spf_from(["lonely"])["lonely"]
        assert set(res) == {"lonely"}


class TestDeviceFirstHops:
    """first_hops_ell bitmasks decoded via to_spf_results must agree with
    oracle next_hops — including a wide-degree source crossing the 32-bit
    word boundary."""

    @pytest.mark.parametrize("seed", [0, 5])
    def test_random(self, seed):
        ls = build(random_topology(18, 22, seed=seed))
        csr = CsrTopology.from_link_state(ls)
        results = csr.spf_from(ls.node_names)
        for src in ls.node_names:
            oracle = ls.run_spf(src)
            for node, o in oracle.items():
                assert results[src][node].next_hops == o.next_hops, (
                    src,
                    node,
                )

    def test_multiword_bitmask(self):
        """Hub with 70 spokes: 3 uint32 words of first-hop slots."""
        from test_link_state import adj, adj_db

        n_leaves = 70
        dbs = [
            adj_db("hub", [adj("hub", f"leaf{i:02d}") for i in range(n_leaves)])
        ]
        for i in range(n_leaves):
            adjs = [adj(f"leaf{i:02d}", "hub")]
            # chain leaves into a cycle so leaf->leaf has 2 equal paths
            j = (i + 1) % n_leaves
            adjs.append(adj(f"leaf{i:02d}", f"leaf{j:02d}"))
            k = (i - 1) % n_leaves
            adjs.append(adj(f"leaf{i:02d}", f"leaf{k:02d}"))
            dbs.append(adj_db(f"leaf{i:02d}", adjs))
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        results = csr.spf_from(["hub", "leaf00"])
        for src in ("hub", "leaf00"):
            oracle = ls.run_spf(src)
            for node, o in oracle.items():
                assert results[src][node].next_hops == o.next_hops, (
                    src,
                    node,
                    results[src][node].next_hops,
                    o.next_hops,
                )
