"""Unit tests for the bucketed-ELL SSSP kernel internals (ops/sssp.py):
bucket construction invariants, multi-bucket skewed-degree graphs, masked
(per-row exclusion) runs, and parity with the edge-list kernel.

The oracle-parity of the full pipeline is covered by
test_sssp_conformance.py (CsrTopology.spf_from routes through ELL)."""

from __future__ import annotations

import numpy as np
import pytest

from openr_tpu.decision import LinkState
from openr_tpu.decision.csr import CsrTopology
from openr_tpu.ops import sssp as ops
from openr_tpu.utils.topo import fat_tree_topology, random_topology

from test_link_state import adj, adj_db, build


def star_plus_chain(n_leaves: int, chain: int):
    """Hub with n_leaves spokes + a chain hanging off leaf 0: produces a
    strongly skewed in-degree distribution (hub deg n_leaves, rest <= 2)."""
    dbs = [adj_db("hub", [adj("hub", f"leaf{i}") for i in range(n_leaves)])]
    for i in range(n_leaves):
        adjs = [adj(f"leaf{i}", "hub")]
        if i == 0 and chain:
            adjs.append(adj("leaf0", "c0"))
        dbs.append(adj_db(f"leaf{i}", adjs))
    for j in range(chain):
        adjs = [adj(f"c{j}", f"c{j-1}" if j else "leaf0")]
        if j + 1 < chain:
            adjs.append(adj(f"c{j}", f"c{j+1}"))
        dbs.append(adj_db(f"c{j}", adjs))
    return dbs


class TestBuildEll:
    def test_buckets_cover_capacity_in_order(self):
        ls = build(star_plus_chain(40, 10))
        csr = CsrTopology.from_link_state(ls)
        ell = csr.ell
        rows = sum(b.nbr.shape[0] for b in ell.buckets)
        assert rows == csr.node_capacity
        # descending K, at least 2 buckets for this skew
        ks = [b.nbr.shape[1] for b in ell.buckets]
        assert ks == sorted(ks, reverse=True)
        assert len(ks) >= 2
        # K is a power of two >= the max in-degree in the bucket
        deg = np.bincount(
            csr.edge_dst[: csr.n_edges], minlength=csr.node_capacity
        )
        lo = 0
        for b in ell.buckets:
            r, k = b.nbr.shape
            bucket_deg = deg[ell.old_of_new[lo : lo + r]]
            assert bucket_deg.max(initial=0) <= k
            assert (k & (k - 1)) == 0
            lo += r

    def test_permutation_is_bijective(self):
        ls = build(random_topology(30, 40, seed=3))
        csr = CsrTopology.from_link_state(ls)
        ell = csr.ell
        assert sorted(ell.old_of_new.tolist()) == list(range(csr.node_capacity))
        np.testing.assert_array_equal(
            ell.new_of_old[ell.old_of_new], np.arange(csr.node_capacity)
        )

    def test_slots_match_edges(self):
        ls = build(star_plus_chain(12, 4))
        csr = CsrTopology.from_link_state(ls)
        ell = csr.ell
        seen_edges = set()
        lo = 0
        for b in ell.buckets:
            r, k = b.nbr.shape
            for i in range(r):
                v_old = int(ell.old_of_new[lo + i])
                for j in range(k):
                    e = int(b.edge_id[i, j])
                    if e < 0:
                        assert not b.ok[i, j]
                        continue
                    seen_edges.add(e)
                    assert int(csr.edge_dst[e]) == v_old
                    assert int(ell.new_of_old[csr.edge_src[e]]) == int(
                        b.nbr[i, j]
                    )
                    assert int(csr.edge_metric[e]) == int(b.w[i, j])
                    assert bool(csr.edge_up[e]) == bool(b.ok[i, j])
            lo += r
        assert seen_edges == set(range(csr.n_edges))


class TestEllKernelParity:
    """ELL kernel vs the edge-list kernel on identical inputs."""

    def _both(self, csr, sources, extra_mask=None):
        import jax.numpy as jnp

        src_ids = np.asarray(
            [csr.node_id[s] for s in sources], dtype=np.int32
        )
        if extra_mask is None:
            dist_ell, dag_ell = ops.spf_forward_ell(
                src_ids,
                csr.ell,
                csr.edge_src,
                csr.edge_dst,
                csr.edge_metric,
                csr.edge_up,
                csr.node_overloaded,
            )
        else:
            dist_ell, dag_ell = ops.spf_forward_ell_masked(
                src_ids,
                csr.ell,
                csr.edge_src,
                csr.edge_dst,
                csr.edge_metric,
                csr.edge_up,
                csr.node_overloaded,
                extra_mask,
            )
        allowed = ops.make_relax_allowed(
            jnp.asarray(src_ids),
            jnp.asarray(csr.edge_src),
            jnp.asarray(csr.edge_up),
            jnp.asarray(csr.node_overloaded),
            None if extra_mask is None else jnp.asarray(extra_mask),
        )
        dist_edge = ops.batched_sssp(
            ops.make_dist0(jnp.asarray(src_ids), csr.node_capacity),
            jnp.asarray(csr.edge_src),
            jnp.asarray(csr.edge_dst),
            jnp.asarray(csr.edge_metric),
            allowed,
        )
        dag_edge = ops.sp_dag_mask(
            dist_edge,
            jnp.asarray(csr.edge_src),
            jnp.asarray(csr.edge_dst),
            jnp.asarray(csr.edge_metric),
            allowed,
        )
        return (
            np.asarray(dist_ell),
            np.asarray(dag_ell),
            np.asarray(dist_edge),
            np.asarray(dag_edge),
        )

    @pytest.mark.parametrize(
        "dbs",
        [
            star_plus_chain(40, 10),
            fat_tree_topology(4),
            random_topology(40, 80, seed=7),
        ],
        ids=["star-chain", "fat-tree", "random"],
    )
    def test_dist_and_dag_match(self, dbs):
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        sources = ls.node_names
        d1, g1, d2, g2 = self._both(csr, sources)
        np.testing.assert_array_equal(d1[:, : csr.n_nodes], d2[:, : csr.n_nodes])
        np.testing.assert_array_equal(g1[:, : csr.n_edges], g2[:, : csr.n_edges])

    def test_overloaded_hub(self):
        """Drained hub: still reachable, no transit — the d_u == 0 source
        exception must let the hub itself still originate."""
        dbs = star_plus_chain(8, 0)
        ls = build(dbs)
        hub_db = next(d for d in dbs if d.this_node_name == "hub")
        hub_db.is_overloaded = True
        ls.update_adjacency_database(hub_db)
        csr = CsrTopology.from_link_state(ls)
        sources = ls.node_names
        d1, g1, d2, g2 = self._both(csr, sources)
        np.testing.assert_array_equal(d1[:, : csr.n_nodes], d2[:, : csr.n_nodes])
        # leaf -> leaf must be unreachable (only path transits drained hub)
        r = sources.index("leaf1")
        c = csr.node_id["leaf2"]
        assert d1[r, c] == int(ops.INF32)
        # hub itself still reaches all leaves
        r = sources.index("hub")
        assert d1[r, csr.node_id["leaf2"]] == 1

    def test_masked_rows(self):
        """Per-row edge exclusions (the KSP/what-if capability)."""
        ls = build(random_topology(20, 26, seed=11))
        csr = CsrTopology.from_link_state(ls)
        sources = ls.node_names[:8]
        rng = np.random.RandomState(5)
        mask = np.ones((len(sources), csr.edge_capacity), dtype=bool)
        for row in range(len(sources)):
            kill = rng.choice(csr.n_edges, size=3, replace=False)
            mask[row, kill] = False
        d1, g1, d2, g2 = self._both(csr, sources, extra_mask=mask)
        np.testing.assert_array_equal(d1[:, : csr.n_nodes], d2[:, : csr.n_nodes])
        np.testing.assert_array_equal(g1[:, : csr.n_edges], g2[:, : csr.n_edges])

    def test_runtime_edge_state_overrides_build_snapshot(self):
        """edge_up / node_overloaded passed at call time must win over the
        snapshots baked into the ELL tables — a link flap after build may
        not route through the dead link."""
        ls = build(
            [
                adj_db("a", [adj("a", "b"), adj("a", "c", metric=10)]),
                adj_db("b", [adj("b", "a"), adj("b", "c")]),
                adj_db("c", [adj("c", "b"), adj("c", "a", metric=10)]),
            ]
        )
        csr = CsrTopology.from_link_state(ls)
        src = np.asarray([csr.node_id["a"]], dtype=np.int32)
        dist, _ = ops.spf_forward_ell(
            src,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        assert np.asarray(dist)[0, csr.node_id["c"]] == 2  # a-b-c

        # kill the a<->b link in the runtime arrays only (ELL not rebuilt)
        up = csr.edge_up.copy()
        for e in range(csr.n_edges):
            uv = {int(csr.edge_src[e]), int(csr.edge_dst[e])}
            if uv == {csr.node_id["a"], csr.node_id["b"]}:
                up[e] = False
        dist2, _ = ops.spf_forward_ell(
            src,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            up,
            csr.node_overloaded,
        )
        assert np.asarray(dist2)[0, csr.node_id["c"]] == 10  # direct a-c

        # drain b in the runtime arrays only: a-b-c transit must die too
        over = csr.node_overloaded.copy()
        over[csr.node_id["b"]] = True
        dist3, _ = ops.spf_forward_ell(
            src,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            over,
        )
        assert np.asarray(dist3)[0, csr.node_id["c"]] == 10
        assert np.asarray(dist3)[0, csr.node_id["b"]] == 1  # still reachable

    def test_uint16_mode_matches_int32(self):
        """Round-5 uint16 ELL mode (half the gather bytes): distances,
        DAG, raw_u16 output, and the saturation fallback must line up
        with the int32 path (ops.sssp spf_forward_ell_sweeps)."""
        ls = build(fat_tree_topology(4))
        csr = CsrTopology.from_link_state(ls)
        src_ids = np.arange(csr.n_nodes, dtype=np.int32)
        kw = dict(
            ell=csr.ell,
            edge_src=csr.edge_src,
            edge_dst=csr.edge_dst,
            edge_metric=csr.edge_metric,
            edge_up=csr.edge_up,
            node_overloaded=csr.node_overloaded,
            n_sweeps=16,
        )
        d32, g32, ok32 = ops.spf_forward_ell_sweeps(src_ids, **kw)
        d16, g16, ok16 = ops.spf_forward_ell_sweeps(
            src_ids, small_dist=True, **kw
        )
        assert bool(ok32) and bool(ok16)
        np.testing.assert_array_equal(np.asarray(d16), np.asarray(d32))
        np.testing.assert_array_equal(np.asarray(g16), np.asarray(g32))
        # raw_u16: uint16 dtype out, INF16 sentinel for padding rows
        draw, _, okr = ops.spf_forward_ell_sweeps(
            src_ids, small_dist=True, raw_u16=True, want_dag=False, **kw
        )
        assert np.asarray(draw).dtype == np.uint16
        np.testing.assert_array_equal(
            np.where(
                np.asarray(draw) >= 40000,
                np.int32(ops.INF32),
                np.asarray(draw).astype(np.int32),
            ),
            np.asarray(d32),
        )
        # runner integration: fat-tree (no bands) engages uint16 via the
        # ELL branch, and the saturation guard falls back on big metrics
        assert csr.banded is None
        assert csr.runner.small_dist
        csr.edge_metric[: csr.n_edges] = 10_000
        assert not csr.runner.small_dist

    def test_uint16_saturation_falls_back_to_int32(self):
        """A topology that passes the pick_small_dist gate (all metrics
        < WBIG16/4) but whose true distances exceed WBIG16 must trip the
        ELL saturation verdict, latch small_allowed off through the
        runner's adapt loop, and still return exact int32 distances."""
        # 7-node chain (< 64 nodes -> no bands -> ELL path), metric 4000:
        # far-end distance 24000 > WBIG16=20000, every metric < 5000
        n = 7
        dbs = []
        for i in range(n):
            adjs = []
            if i > 0:
                adjs.append(adj(f"c{i}", f"c{i-1}", metric=4000))
            if i + 1 < n:
                adjs.append(adj(f"c{i}", f"c{i+1}", metric=4000))
            dbs.append(adj_db(f"c{i}", adjs))
        ls = build(dbs)
        csr = CsrTopology.from_link_state(ls)
        assert csr.banded is None
        r = csr.runner
        assert r.small_dist  # eligible by the metric gate...
        src = np.asarray([csr.node_id["c0"]], dtype=np.int32)
        # ...but the direct uint16 run must FAIL the saturation verdict
        _, _, ok16 = ops.spf_forward_ell_sweeps(
            src,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            n_sweeps=16,
            small_dist=True,
            want_dag=False,
        )
        assert not bool(ok16)
        # the adaptive runner falls back to int32 and gets exact results
        dist, _ = r.forward(src, want_dag=False)
        assert not r.small_allowed  # latched off by the saturation retry
        far = csr.node_id[f"c{n-1}"]
        assert int(dist[0, far]) == 4000 * (n - 1)

    def test_check_every_batching(self):
        """check_every > 1 must not change the fixed point."""
        import jax.numpy as jnp

        ls = build(random_topology(25, 30, seed=2))
        csr = CsrTopology.from_link_state(ls)
        src_ids = jnp.arange(csr.n_nodes, dtype=jnp.int32)
        d0 = ops.make_dist0_T(
            src_ids, jnp.asarray(csr.ell.new_of_old), csr.node_capacity
        )
        ref = np.asarray(ops.batched_sssp_ell(d0, csr.ell))
        for ce in (2, 5, 16):
            got = np.asarray(ops.batched_sssp_ell(d0, csr.ell, check_every=ce))
            np.testing.assert_array_equal(ref, got)
