"""Tier-1 acceptance for openr_tpu/snapshot: engine checkpoints, the
three restore rungs, program-manifest prewarm, elastic fleet scale under
live load, and the autoscaling policy.

The acceptance bar (mirrors ISSUE/ROADMAP):

- the serialized artifact roundtrips byte-identically and any corruption
  is caught by the integrity digest at load, never at use;
- a snapshot-restored replica answers bit-exact against its donor at the
  pinned epoch (and against the host Dijkstra oracle);
- staleness demotes to an accounted cold build (`snapshot.replay_fallbacks`)
  — never an error and never a wrong answer;
- `ServingFleet.scale(k -> k+1)` under open-loop load closes the
  router's dispatch ledger exactly with zero silent drops.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.device import DeviceResidencyEngine
from openr_tpu.snapshot import (
    SNAPSHOT_COUNTER_KEYS,
    SNAPSHOT_COUNTERS,
    AutoscalePolicy,
    EngineSnapshot,
    SnapshotFormatError,
)
from openr_tpu.utils.topo import grid_topology

from test_link_state import build


def _results_view(engine, csr, sources):
    got = engine.spf_results(csr, sources)
    return {
        src: {
            dest: (entry.metric, frozenset(entry.next_hops))
            for dest, entry in res.items()
        }
        for src, res in got.items()
    }


def _oracle_view(ls, sources):
    return {
        src: {
            dest: (entry.metric, frozenset(entry.next_hops))
            for dest, entry in ls.run_spf(src).items()
        }
        for src in sources
    }


def _world(n: int = 4):
    dbs = grid_topology(n)
    ls = build(dbs)
    csr = CsrTopology.from_link_state(ls)
    return dbs, ls, csr


class TestSerialFormat:
    def test_roundtrip_is_byte_identical(self):
        _, ls, csr = _world()
        engine = DeviceResidencyEngine()
        snap = EngineSnapshot.take(engine, csr)
        blob = snap.to_bytes()
        back = EngineSnapshot.from_bytes(blob)
        assert back.to_bytes() == blob
        assert back.epoch == snap.epoch
        assert back.rewire_seq == snap.rewire_seq
        assert back.topo_key == snap.topo_key
        assert back.node_names == snap.node_names
        assert back.manifest == snap.manifest
        for name in snap.arrays:
            assert np.array_equal(back.arrays[name], snap.arrays[name])
        # lineage pins are same-process facts and never serialized
        assert back.donor_csr_id is None and back.donor_ell_ref is None

    def test_corruption_is_caught_by_the_digest(self):
        _, ls, csr = _world()
        engine = DeviceResidencyEngine()
        blob = bytearray(EngineSnapshot.take(engine, csr).to_bytes())
        before = SNAPSHOT_COUNTERS.get_counters()["snapshot.digest_failures"]
        blob[-3] ^= 0xFF  # bit rot in the array payload
        with pytest.raises(SnapshotFormatError, match="digest"):
            EngineSnapshot.from_bytes(bytes(blob))
        after = SNAPSHOT_COUNTERS.get_counters()["snapshot.digest_failures"]
        assert after == before + 1

    def test_bad_magic_and_format_skew_refuse_loudly(self):
        _, ls, csr = _world()
        engine = DeviceResidencyEngine()
        blob = EngineSnapshot.take(engine, csr).to_bytes()
        with pytest.raises(SnapshotFormatError, match="magic"):
            EngineSnapshot.from_bytes(b"NOTASNAP" + blob[8:])
        import json as _json
        import struct as _struct

        (hlen,) = _struct.unpack_from("<I", blob, 8)
        header = _json.loads(blob[12 : 12 + hlen].decode())
        header["format"] = 99
        hdr = _json.dumps(header, sort_keys=True).encode()
        skew = blob[:8] + _struct.pack("<I", len(hdr)) + hdr + blob[12 + hlen :]
        with pytest.raises(SnapshotFormatError, match="format"):
            EngineSnapshot.from_bytes(skew)


class TestRestoreRungs:
    def test_donor_replay_after_drift_is_bit_exact(self):
        dbs, ls, csr = _world()
        engine = DeviceResidencyEngine()
        sources = ls.node_names[:3]
        assert _results_view(engine, csr, sources) == _oracle_view(
            ls, sources
        )
        snap = EngineSnapshot.take(engine, csr)
        # attribute drift after the checkpoint: the replay rung must
        # carry the mirror forward through the engine's own ladder
        dbs[0].adjacencies[0].metric = 41
        ls.update_adjacency_database(dbs[0])
        assert csr.refresh(ls) is True
        before = SNAPSHOT_COUNTERS.get_counters()
        assert snap.restore(engine, csr) == "replay"
        after = SNAPSHOT_COUNTERS.get_counters()
        assert after["snapshot.replayed_events"] > before[
            "snapshot.replayed_events"
        ]
        assert _results_view(engine, csr, sources) == _oracle_view(
            ls, sources
        )

    def test_fresh_replica_install_is_bit_exact_at_the_pinned_epoch(self):
        # the ISSUE acceptance: a snapshot-restored replica answers
        # bit-exact vs its donor at the pinned epoch, without paying the
        # donor's cold build
        dbs, ls, csr = _world()
        donor = DeviceResidencyEngine()
        sources = ls.node_names[:3]
        donor_answers = _results_view(donor, csr, sources)
        snap = EngineSnapshot.take(donor, csr)
        blob = snap.to_bytes()  # across the wire, pins stripped

        joiner_ls = build(grid_topology(4))
        joiner_csr = CsrTopology.from_link_state(joiner_ls)
        joiner = DeviceResidencyEngine()
        mode = EngineSnapshot.from_bytes(blob).restore(joiner, joiner_csr)
        assert mode == "install"
        assert int(joiner_csr.version) == snap.epoch
        assert joiner.has_residency(joiner_csr)
        assert (
            _results_view(joiner, joiner_csr, sources) == donor_answers
        )
        # the warm start really skipped the cold build: installing is
        # not a restage, and the first query found residency
        c = joiner.get_counters()
        assert c["device.engine.full_restages"] == 0

    def test_stale_snapshot_demotes_to_accounted_cold(self):
        dbs, ls, csr = _world()
        donor = DeviceResidencyEngine()
        snap = EngineSnapshot.take(donor, csr)

        joiner_dbs = grid_topology(4)
        joiner_ls = build(joiner_dbs)
        # the joiner's truth drifted past the checkpoint: content
        # equality must fail and the restore must demote, not mis-install
        joiner_dbs[0].adjacencies[0].metric = 57
        joiner_ls.update_adjacency_database(joiner_dbs[0])
        joiner_csr = CsrTopology.from_link_state(joiner_ls)
        joiner = DeviceResidencyEngine()
        before = SNAPSHOT_COUNTERS.get_counters()["snapshot.replay_fallbacks"]
        assert snap.restore(joiner, joiner_csr) == "cold"
        after = SNAPSHOT_COUNTERS.get_counters()["snapshot.replay_fallbacks"]
        assert after == before + 1
        sources = joiner_ls.node_names[:2]
        assert _results_view(joiner, joiner_csr, sources) == _oracle_view(
            joiner_ls, sources
        )

    def test_rewire_chain_gap_demotes_inside_replay(self):
        # run the donor mirror far past the rewire log depth after the
        # checkpoint: the replay rung hits a chain gap inside sync() and
        # demotes to the accounted cold build — never an error
        dbs, ls, csr = _world()
        engine = DeviceResidencyEngine()
        engine.sync(csr)
        snap = EngineSnapshot.take(engine, csr)
        corner = dbs[0]
        for _ in range(CsrTopology.REWIRE_LOG_DEPTH // 2 + 2):
            gone = corner.adjacencies.pop(0)
            ls.update_adjacency_database(corner)
            csr.refresh(ls)
            corner.adjacencies.insert(0, gone)
            ls.update_adjacency_database(corner)
            csr.refresh(ls)
        before = SNAPSHOT_COUNTERS.get_counters()["snapshot.replay_fallbacks"]
        assert snap.restore(engine, csr) == "cold"
        after = SNAPSHOT_COUNTERS.get_counters()["snapshot.replay_fallbacks"]
        assert after == before + 1
        sources = ls.node_names[:2]
        assert _results_view(engine, csr, sources) == _oracle_view(
            ls, sources
        )


class TestPrewarm:
    def test_manifest_prewarms_the_program_cache(self):
        dbs, ls, csr = _world()
        donor = DeviceResidencyEngine()
        sources = ls.node_names[:3]
        donor.spf_results(csr, sources)  # compile the donor's ladder key
        snap = EngineSnapshot.take(donor, csr)
        assert snap.manifest, "donor served queries; manifest must not be empty"

        joiner_ls = build(grid_topology(4))
        joiner_csr = CsrTopology.from_link_state(joiner_ls)
        joiner = DeviceResidencyEngine()
        assert snap.restore(joiner, joiner_csr) == "install"
        c = joiner.get_counters()
        assert c["device.engine.compiles"] == len(snap.manifest)
        assert set(joiner.cached_program_keys()) == set(snap.manifest)
        # the first real query rides the prewarmed program: no compile
        joiner.spf_results(joiner_csr, sources)
        assert (
            joiner.get_counters()["device.engine.compiles"]
            == c["device.engine.compiles"]
        )


class TestFleetScaleUnderLoad:
    def test_scale_out_and_in_closes_the_ledger_exactly(self, cpu_burner):
        from openr_tpu.main import ServingFleet
        from openr_tpu.serving.router import dispatch_ledger_closes

        fleet = ServingFleet(2, hedge_after_s=None)
        fleet.start()
        try:
            assert fleet.wait_converged(30), "fleet never converged"
            c0 = SNAPSHOT_COUNTERS.get_counters()
            stop = threading.Event()
            acct = {"submitted": 0, "resolved": 0}
            errors: list = []

            def load() -> None:
                while not stop.is_set():
                    fut = fleet.router.submit("paths", sources=("fleet-0",))
                    acct["submitted"] += 1
                    try:
                        fut.result(timeout=10)
                    except Exception as exc:  # noqa: BLE001 — accounted
                        errors.append(repr(exc))
                    acct["resolved"] += 1
                    time.sleep(0.002)

            t = threading.Thread(target=load, name="scale-load")
            t.start()
            time.sleep(0.3)
            modes = fleet.scale(3)
            # the joiner warm-started off daemon 0's snapshot: the
            # converged fleet hits the content-equality install rung
            assert modes == ["install"], modes
            assert len(fleet.daemons) == 3
            time.sleep(0.3)
            fleet.scale(2)
            assert len(fleet.daemons) == 2
            time.sleep(0.3)
            stop.set()
            t.join()
        finally:
            fleet.stop()
        # stop() joined every scheduler executor, so the ledger is final
        counters = fleet.router.get_counters()
        assert not errors, errors[:3]
        assert acct["resolved"] == acct["submitted"], "silent drops"
        assert dispatch_ledger_closes(counters, acct["submitted"]), counters
        c1 = SNAPSHOT_COUNTERS.get_counters()
        assert c1["snapshot.scaleouts"] == c0["snapshot.scaleouts"] + 1
        assert c1["snapshot.scaleins"] == c0["snapshot.scaleins"] + 1
        assert c1["snapshot.taken"] == c0["snapshot.taken"] + 1


class TestAutoscalePolicy:
    def test_shed_pressure_scales_out_then_cools_down(self):
        p = AutoscalePolicy(max_replicas=4, cooldown=2)
        assert p.observe(1, {"serving.router.sheds": 0}).action == "hold"
        d = p.observe(1, {"serving.router.sheds": 3})
        assert (d.action, d.target_k) == ("scale_out", 2)
        # cooldown: even under continued pressure the policy holds
        assert p.observe(2, {"serving.router.sheds": 6}).reason == "cooldown"
        assert p.observe(2, {"serving.router.sheds": 9}).reason == "cooldown"
        d = p.observe(2, {"serving.router.sheds": 12})
        assert (d.action, d.target_k) == ("scale_out", 3)

    def test_admission_depth_is_a_scale_out_signal(self):
        p = AutoscalePolicy(depth_high=10, cooldown=0)
        d = p.observe(1, {}, admission_depth=64)
        assert d.action == "scale_out"
        assert "admission_depth" in d.reason

    def test_max_replicas_clamps(self):
        p = AutoscalePolicy(max_replicas=2, cooldown=0)
        d = p.observe(2, {"serving.router.sheds": 5})
        assert (d.action, d.reason) == ("hold", "at max_replicas")

    def test_idle_streak_scales_in_but_never_below_min(self):
        p = AutoscalePolicy(min_replicas=1, idle_intervals=3, cooldown=0)
        assert p.observe(2, {}).action == "hold"
        assert p.observe(2, {}).action == "hold"
        d = p.observe(2, {})
        assert (d.action, d.target_k) == ("scale_in", 1)
        # at the floor: three more idle ticks, still no scale-in
        for _ in range(2):
            assert p.observe(1, {}).action == "hold"
        assert p.observe(1, {}).reason == "at min_replicas"

    def test_traffic_resets_the_idle_streak(self):
        p = AutoscalePolicy(idle_intervals=2, cooldown=0)
        assert p.observe(2, {"serving.router.dispatches": 0}).action == "hold"
        # a busy tick resets the streak
        assert (
            p.observe(2, {"serving.router.dispatches": 50}).reason == "steady"
        )
        assert p.observe(2, {"serving.router.dispatches": 50}).action == "hold"
        d = p.observe(2, {"serving.router.dispatches": 50})
        assert d.action == "scale_in"


class TestCounterRegistry:
    def test_family_is_pre_seeded_and_registry_shaped(self):
        c = SNAPSHOT_COUNTERS.get_counters()
        assert set(SNAPSHOT_COUNTER_KEYS) <= set(c)
        assert all(k.startswith("snapshot.") for k in c)
