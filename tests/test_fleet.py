"""Fleet route view (decision/fleet.py): the daemon consumer of the
reduced all-sources product (ops/allsources.py).

Golden parity contract (round-5 brief): for every node, the route DB
built from the fleet product equals the per-source build on BOTH
backends (host Dijkstra and device kernels) — the reference consumer
being buildRouteDb (openr/decision/Decision.cpp:615-793) and the
any-node ctrl query (Decision.cpp:1510-1530)."""

from __future__ import annotations

import pytest

from openr_tpu.decision.fleet import (
    INF32,
    FleetViewCache,
    fleet_destinations,
)
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)
from tests.test_spf_solver import (
    PFX,
    adj,
    build_link_state,
    prefix_state_with,
    square,
)


def test_inf_sentinel_matches_kernels():
    from openr_tpu.decision.fleet import INF16
    from openr_tpu.ops.banded import INF16 as KERNEL_INF16
    from openr_tpu.ops.sssp import INF32 as KERNEL_INF

    assert INF32 == int(KERNEL_INF)
    # the uint16 sentinel _row_i32 keys on must track the kernel's: a
    # retuned ops.banded.INF16 with a stale mirror here would classify
    # unreachable (sentinel) entries as finite distances
    assert INF16 == int(KERNEL_INF16)


def grid_link_state(side: int, metric=lambda a, b: 10) -> LinkState:
    """side x side grid as adjacency DBs (node names zero-padded so the
    sorted-name id order is the natural order)."""
    def name(r, c):
        return f"n{r * side + c:03d}"

    adj_map: dict[str, list] = {}
    labels: dict[str, int] = {}
    for r in range(side):
        for c in range(side):
            me = name(r, c)
            adjs = []
            for dr, dc in ((0, 1), (0, -1), (1, 0), (-1, 0)):
                rr, cc = r + dr, c + dc
                if 0 <= rr < side and 0 <= cc < side:
                    other = name(rr, cc)
                    adjs.append(adj(me, other, metric=metric(me, other)))
            adj_map[me] = adjs
            labels[me] = 1000 + r * side + c
    return build_link_state(adj_map, labels=labels)


def assert_fleet_parity(area_ls: dict, ps, nodes=None):
    """fleet_route_dbs == per-node build_route_db on host AND device."""
    host_solver = SpfSolver("__fleet__")
    fleet = host_solver.fleet_route_dbs(area_ls, ps, nodes=nodes)
    all_nodes = nodes or sorted(
        {n for ls in area_ls.values() for n in ls.node_names}
    )
    dev_backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
    for node in all_nodes:
        host = SpfSolver(node).build_route_db(area_ls, ps)
        device = SpfSolver(node, spf_backend=dev_backend).build_route_db(
            area_ls, ps
        )
        got = fleet[node]
        if host is None:
            assert device is None
            assert not got.unicast_routes and not got.mpls_routes
            continue
        assert got.unicast_routes == host.unicast_routes, node
        assert got.mpls_routes == host.mpls_routes, node
        assert device.unicast_routes == host.unicast_routes, node
        assert device.mpls_routes == host.mpls_routes, node
    return fleet


class TestFleetParity:
    def test_square_every_node(self):
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix="::2:0/112")),
        )
        assert_fleet_parity({"0": square()}, ps)

    def test_square_anycast_two_advertisers(self):
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("3", "0", PrefixEntry(prefix=PFX)),
        )
        assert_fleet_parity({"0": square()}, ps)

    def test_overloaded_transit_drain(self):
        # 1-2-4 and 1-3-4: overload 2; routes to 4's prefix must avoid 2
        # as transit while 2 itself stays reachable (d==0 exception)
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
            overloaded={"2"},
        )
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX)),
            ("2", "0", PrefixEntry(prefix="::2:0/112")),
        )
        fleet = assert_fleet_parity({"0": ls}, ps)
        nhs = {
            nh.neighbor_node_name
            for nh in fleet["1"].unicast_routes[PFX].nexthops
        }
        assert nhs == {"3"}

    def test_overloaded_advertiser_filtering(self):
        # both advertisers overloaded -> kept (maybeFilterDrainedNodes
        # keeps the full set when filtering would empty it)
        ls = build_link_state(
            {
                "1": [adj("1", "2")],
                "2": [adj("2", "1"), adj("2", "3")],
                "3": [adj("3", "2")],
            },
            overloaded={"3"},
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        assert_fleet_parity({"0": ls}, ps)

    def test_parallel_links_share_slot(self):
        # two links 1<->2 with different metrics: only the cheaper is an
        # ECMP next hop; fleet per-link evaluation must keep per-link
        # metric semantics (slots are per unique neighbor)
        a1 = Adjacency(
            other_node_name="2",
            if_name="1/2-a",
            other_if_name="2/1-a",
            metric=10,
            next_hop_v6="fe80::2a",
        )
        a2 = Adjacency(
            other_node_name="2",
            if_name="1/2-b",
            other_if_name="2/1-b",
            metric=20,
            next_hop_v6="fe80::2b",
        )
        b1 = Adjacency(
            other_node_name="1",
            if_name="2/1-a",
            other_if_name="1/2-a",
            metric=10,
            next_hop_v6="fe80::1a",
        )
        b2 = Adjacency(
            other_node_name="1",
            if_name="2/1-b",
            other_if_name="1/2-b",
            metric=20,
            next_hop_v6="fe80::1b",
        )
        ls = build_link_state({"1": [a1, a2], "2": [b1, b2]})
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        fleet = assert_fleet_parity({"0": ls}, ps)
        route = fleet["1"].unicast_routes[PFX]
        assert {nh.if_name for nh in route.nexthops} == {"1/2-a"}

    def test_equal_parallel_links_both_used(self):
        a1 = Adjacency(
            other_node_name="2",
            if_name="1/2-a",
            other_if_name="2/1-a",
            metric=10,
            next_hop_v6="fe80::2a",
        )
        a2 = Adjacency(
            other_node_name="2",
            if_name="1/2-b",
            other_if_name="2/1-b",
            metric=10,
            next_hop_v6="fe80::2b",
        )
        b1 = Adjacency(
            other_node_name="1",
            if_name="2/1-a",
            other_if_name="1/2-a",
            metric=10,
            next_hop_v6="fe80::1a",
        )
        b2 = Adjacency(
            other_node_name="1",
            if_name="2/1-b",
            other_if_name="1/2-b",
            metric=10,
            next_hop_v6="fe80::1b",
        )
        ls = build_link_state({"1": [a1, a2], "2": [b1, b2]})
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        fleet = assert_fleet_parity({"0": ls}, ps)
        route = fleet["1"].unicast_routes[PFX]
        assert {nh.if_name for nh in route.nexthops} == {"1/2-a", "1/2-b"}

    def test_ksp2_prefix_falls_back_to_per_source(self):
        # KSP2 prefixes go through get_kth_paths (per-source machinery);
        # the fleet build must still produce identical routes
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            ),
            ("2", "0", PrefixEntry(prefix="::2:0/112")),
        )
        assert_fleet_parity({"0": square()}, ps)

    def test_grid64_every_node(self):
        # 64 nodes — above DeviceSpfBackend's default min_device_nodes;
        # asymmetric metrics break ECMP ties in interesting ways
        import random

        rnd = random.Random(5)
        weights = {}

        def metric(a, b):
            return weights.setdefault((a, b), rnd.randint(1, 5))

        ls = grid_link_state(8, metric=metric)
        names = sorted(ls.node_names)
        ps = prefix_state_with(
            (names[0], "0", PrefixEntry(prefix=PFX)),
            (names[-1], "0", PrefixEntry(prefix=PFX)),
            (names[27], "0", PrefixEntry(prefix="::2:0/112")),
            (names[13], "0", PrefixEntry(prefix="::3:0/112")),
        )
        assert_fleet_parity({"0": ls}, ps)

    def test_multi_area(self):
        # area 0: 1-2; area 1: 2-3 (2 spans both); prefix in each area
        ls0 = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]}, area="0"
        )
        ls1 = LinkState("1")
        for node, adjs in (
            ("2", [adj("2", "3")]),
            ("3", [adj("3", "2")]),
        ):
            ls1.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=node,
                    adjacencies=adjs,
                    area="1",
                )
            )
        ps = prefix_state_with(
            ("3", "1", PrefixEntry(prefix=PFX)),
            ("1", "0", PrefixEntry(prefix="::2:0/112")),
        )
        assert_fleet_parity({"0": ls0, "1": ls1}, ps)

    def test_disconnected_components(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2")],
                "2": [adj("2", "1")],
                "3": [adj("3", "4")],
                "4": [adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
        )
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix="::2:0/112")),
        )
        fleet = assert_fleet_parity({"0": ls}, ps)
        assert PFX in fleet["1"].unicast_routes
        assert "::2:0/112" not in fleet["1"].unicast_routes
        assert "::2:0/112" in fleet["3"].unicast_routes


class TestFleetBitmapCrossCheck:
    def test_bitmap_matches_route_nexthops(self):
        # device bitmap decode == the host-side per-link evaluation for a
        # single-advertiser non-SR prefix
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("__fleet__")
        fleet = solver.fleet_route_dbs({"0": ls}, ps)
        view = solver.fleet.view({"0": ls}["0"], fleet_destinations(ls, ps))
        for me in ("1", "2", "3"):
            route = fleet[me].unicast_routes.get(PFX)
            route_nhs = (
                {nh.neighbor_node_name for nh in route.nexthops}
                if route
                else set()
            )
            assert view.next_hop_neighbors(me, "4") == route_nhs, me


class TestFleetCache:
    def test_warm_cache_reuses_view(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        cache = FleetViewCache()
        dests = fleet_destinations(ls, ps)
        v1 = cache.view(ls, dests)
        assert cache.is_warm(ls, dests)
        assert cache.view(ls, dests) is v1

    def test_version_bump_invalidates(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        cache = FleetViewCache()
        dests = fleet_destinations(ls, ps)
        v1 = cache.view(ls, dests)
        # metric change bumps the LinkState version
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=30), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        assert not cache.is_warm(ls, dests)
        v2 = cache.view(ls, dests)
        assert v2 is not v1 and v2.version == ls.version

    def test_dest_change_invalidates(self):
        # unlabeled topology: dests = advertisers only, so a new
        # advertiser really changes the destination set
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            }
        )
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        cache = FleetViewCache()
        v1 = cache.view(ls, fleet_destinations(ls, ps))
        assert v1.dest_names == ["4"]
        ps.update_prefix("2", "0", PrefixEntry(prefix="::9:0/112"))
        dests2 = fleet_destinations(ls, ps)
        assert dests2 == ["2", "4"]
        v2 = cache.view(ls, dests2)
        assert v2 is not v1

    def test_reroute_after_metric_change(self):
        # end-to-end: fleet answers track topology changes
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("__fleet__")
        fleet1 = solver.fleet_route_dbs({"0": ls}, ps)
        assert {
            nh.neighbor_node_name
            for nh in fleet1["1"].unicast_routes[PFX].nexthops
        } == {"2", "3"}
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=30), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        fleet2 = solver.fleet_route_dbs({"0": ls}, ps)
        assert {
            nh.neighbor_node_name
            for nh in fleet2["1"].unicast_routes[PFX].nexthops
        } == {"3"}
        assert_fleet_parity({"0": ls}, ps)


class TestAnyNodeQuery:
    def test_host_backend_no_fleet_compute(self):
        # host backend must not compute fleet views, but the answer is
        # still correct via the per-source path
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("1")
        db = solver.any_node_route_db({"0": ls}, ps, "2")
        ref = SpfSolver("2").build_route_db({"0": ls}, ps)
        assert db.unicast_routes == ref.unicast_routes
        assert not solver.fleet._views  # no view computed

    def test_device_backend_warm_fleet_serves_query(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        # warm the cache via a fleet dump, then query any node
        solver.fleet_route_dbs({"0": ls}, ps, nodes=["1"])
        dests = fleet_destinations(ls, ps)
        assert solver.fleet.is_warm(ls, dests)
        db = solver.any_node_route_db({"0": ls}, ps, "3")
        ref = SpfSolver("3").build_route_db({"0": ls}, ps)
        assert db.unicast_routes == ref.unicast_routes
        assert db.mpls_routes == ref.mpls_routes

    def test_unknown_node(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        assert SpfSolver("1").any_node_route_db({"0": ls}, ps, "zz") is None


class TestWarmStart:
    """Warm-started fleet rebuilds, BOTH directions: improvement-only
    changes seed the relax with the previous distances (upper-bound
    init, ops.banded.spf_forward_banded); worsening changes (link DOWN,
    metric increase, drain) seed it with the previous distances minus
    the certified affected set (fleet._affected_init).  Either way the
    result must equal a fresh cold build bit-for-bit — _rebuild_pair
    asserts dist AND bitmap equality on every path.

    Fixtures are 64-node rings: the warm paths engage only where the
    BANDED kernel runs (build_banded needs >=64 nodes with circulant
    structure; the ELL fallback ignores dist0 and stays cold)."""

    @staticmethod
    def ring_ls(n=64, metric=lambda a, b: 20):
        def name(i):
            return f"r{i % 64:03d}" if n <= 1000 else f"r{i % n:06d}"

        adj_map = {}
        labels = {}
        for i in range(n):
            me = name(i)
            adj_map[me] = [
                adj(me, name(i + d), metric=metric(i, (i + d) % n))
                for d in (1, -1, 2, -2)
            ]
            labels[me] = 1000 + i
        return build_link_state(adj_map, labels=labels)

    @staticmethod
    def ring_adjs(i, metric=lambda a, b: 20, drop=None):
        def name(j):
            return f"r{j % 64:03d}"

        return [
            adj(name(i), name(i + d), metric=metric(i, (i + d) % 64))
            for d in (1, -1, 2, -2)
            if d != drop
        ]

    def _dists(self, view):
        import numpy as np

        return np.asarray(view._dist_dev)

    def _assert_banded(self, view):
        # the fixture must actually run the banded kernel or this class
        # tests nothing (the ELL fallback never warms)
        from openr_tpu.ops.banded import build_banded

        assert (
            build_banded(
                view.csr.edge_src,
                view.csr.edge_dst,
                view.csr.n_edges,
                view.csr.n_nodes,
            )
            is not None
        )

    def _rebuild_pair(self, mutate):
        """(warm-capable view, fresh cold view) after `mutate(ls)` on
        two identically-constructed LinkStates."""
        import numpy as np

        views = []
        for use_cache in (True, False):
            ls = self.ring_ls()
            ps = prefix_state_with(
                ("r063", "0", PrefixEntry(prefix=PFX)),
                ("r000", "0", PrefixEntry(prefix="::2:0/112")),
            )
            dests = fleet_destinations(ls, ps)
            cache = FleetViewCache()
            if use_cache:
                v1 = cache.view(ls, dests)
                assert not v1.warm
                self._assert_banded(v1)
            mutate(ls)
            views.append(cache.view(ls, fleet_destinations(ls, ps)))
        warm_view, cold_view = views
        assert not cold_view.warm
        np.testing.assert_array_equal(
            self._dists(warm_view), self._dists(cold_view)
        )
        np.testing.assert_array_equal(
            np.asarray(warm_view._bitmap_dev),
            np.asarray(cold_view._bitmap_dev),
        )
        return warm_view, cold_view

    def _set_node(self, ls, i, **kw):
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=f"r{i:03d}",
                adjacencies=self.ring_adjs(i, **{
                    k: v for k, v in kw.items() if k in ("metric", "drop")
                }),
                is_overloaded=kw.get("is_overloaded", False),
                node_label=1000 + i,
                area="0",
            )
        )

    def test_metric_decrease_warm_starts(self):
        warm, _ = self._rebuild_pair(
            lambda ls: self._set_node(
                ls, 0, metric=lambda a, b: 5 if b == 1 else 20
            )
        )
        assert warm.warm

    def test_metric_increase_warm_starts_down(self):
        warm, _ = self._rebuild_pair(
            lambda ls: self._set_node(
                ls, 0, metric=lambda a, b: 90 if b == 1 else 20
            )
        )
        assert warm.warm
        assert warm.warm_mode == "worsen"

    def test_single_link_down_warm_bit_exact(self):
        warm, _ = self._rebuild_pair(
            lambda ls: self._set_node(ls, 0, drop=1)
        )
        assert warm.warm
        assert warm.warm_mode == "worsen"

    def test_multi_link_down_warm_bit_exact(self):
        def mutate(ls):
            self._set_node(ls, 0, drop=1)
            self._set_node(ls, 20, drop=-1)
            self._set_node(ls, 40, drop=2)

        warm, _ = self._rebuild_pair(mutate)
        assert warm.warm
        assert warm.warm_mode == "worsen"

    def test_mixed_change_warm_starts_down(self):
        # one link worsens while another improves in the SAME delta:
        # neither the improvement-only gate nor a naive "pure worsening"
        # gate fires, but the affected-set argument still holds (the
        # improved edge only loosens the upper bound)
        def mutate(ls):
            self._set_node(
                ls, 0, metric=lambda a, b: 90 if b == 1 else 20
            )
            self._set_node(
                ls, 32, metric=lambda a, b: 5 if b == 33 else 20
            )

        warm, _ = self._rebuild_pair(mutate)
        assert warm.warm
        assert warm.warm_mode == "worsen"

    def test_link_down_warm_then_up_warm(self):
        import numpy as np

        ls = self.ring_ls()
        ps = prefix_state_with(("r063", "0", PrefixEntry(prefix=PFX)))
        dests = fleet_destinations(ls, ps)
        cache = FleetViewCache()
        v1 = cache.view(ls, dests)
        # link r000-r001 down: a WORSENING change -> warm-down rebuild
        self._set_node(ls, 0, drop=1)
        v2 = cache.view(ls, dests)
        assert v2.warm
        assert v2.warm_mode == "worsen"
        # link back up: flap recovery -> improvement-direction warm
        self._set_node(ls, 0)
        v3 = cache.view(ls, dests)
        assert v3.warm
        assert v3.warm_mode == "improve"
        # warm result equals v1 (same topology as the original)
        np.testing.assert_array_equal(self._dists(v3), self._dists(v1))
        # and the daemon-level answer stays correct against the host
        # oracle at BOTH ends of the flap
        assert_fleet_parity(
            {"0": ls}, ps, nodes=[f"r{i:03d}" for i in (0, 1, 2, 31, 63)]
        )

    def test_link_down_warm_matches_host_oracle(self):
        # the WARM-DOWN product itself (same persistent solver cache,
        # so the second build really warms) must answer route builds
        # identically to the per-node host Dijkstra oracle
        ls = self.ring_ls()
        ps = prefix_state_with(("r063", "0", PrefixEntry(prefix=PFX)))
        nodes = [f"r{i:03d}" for i in (0, 1, 2, 31, 63)]
        solver = SpfSolver("r000")
        solver.fleet_route_dbs({"0": ls}, ps, nodes=nodes)
        self._set_node(ls, 0, drop=1)
        fleet = solver.fleet_route_dbs({"0": ls}, ps, nodes=nodes)
        view = solver.fleet._views.get(ls)
        assert view is not None and view.warm_mode == "worsen"
        for node in nodes:
            host = SpfSolver(node).build_route_db({"0": ls}, ps)
            assert fleet[node].unicast_routes == host.unicast_routes, node
            assert fleet[node].mpls_routes == host.mpls_routes, node

    def test_rebuild_counters_track_warm_hits(self):
        from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver

        ls = self.ring_ls()
        ps = prefix_state_with(("r063", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver(
            "r000",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        solver.fleet_route_dbs({"0": ls}, ps, nodes=["r000"])
        assert solver.counters.get("decision.fleet_rebuild_cold") == 1
        assert "decision.fleet_rebuild_warm" not in solver.counters
        self._set_node(ls, 0, metric=lambda a, b: 5 if b == 1 else 20)
        solver.fleet_route_dbs({"0": ls}, ps, nodes=["r000"])
        assert solver.counters.get("decision.fleet_rebuild_warm") == 1
        assert "decision.fleet_rebuild_warm_down" not in solver.counters
        # a cached re-read computes nothing and bumps nothing
        solver.fleet_route_dbs({"0": ls}, ps, nodes=["r000"])
        assert solver.counters.get("decision.fleet_rebuild_warm") == 1
        # a worsening change bumps warm AND the direction-split counter
        self._set_node(ls, 0, drop=1)
        solver.fleet_route_dbs({"0": ls}, ps, nodes=["r000"])
        assert solver.counters.get("decision.fleet_rebuild_warm") == 2
        assert solver.counters.get("decision.fleet_rebuild_warm_down") == 1

    def test_drain_set_warm_down_clear_warm_up(self):
        ls = self.ring_ls()
        ps = prefix_state_with(("r063", "0", PrefixEntry(prefix=PFX)))
        dests = fleet_destinations(ls, ps)
        cache = FleetViewCache()
        cache.view(ls, dests)
        self._set_node(ls, 5, is_overloaded=True)
        v2 = cache.view(ls, dests)
        # draining worsens transit distances: warm-down path
        assert v2.warm
        assert v2.warm_mode == "worsen"
        self._set_node(ls, 5)
        v3 = cache.view(ls, dests)
        assert v3.warm  # un-draining only improves distances
        assert v3.warm_mode == "improve"

    def test_drain_warm_bit_exact(self):
        warm, _ = self._rebuild_pair(
            lambda ls: self._set_node(ls, 5, is_overloaded=True)
        )
        assert warm.warm
        assert warm.warm_mode == "worsen"

    def test_ell_fallback_never_warms(self):
        # small (non-banded) topology + improvement-only change: the
        # gate passes but the ELL kernel ignores dist0, so the view must
        # NOT claim warm (it would poison _warm_hints with cold counts)
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        dests = fleet_destinations(ls, ps)
        cache = FleetViewCache()
        cache.view(ls, dests)
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=5), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        v2 = cache.view(ls, dests)
        assert not v2.warm
        # hint routing follows what actually ran: the cold (ELL) sweep
        # count must land in _hints, never in _warm_hints (an inherited
        # cold count there would oversize every later banded warm seed)
        key = (v2.csr.n_nodes, v2.csr.n_edges)
        assert key not in cache._warm_hints
        assert cache._hints.get(key) == v2.sweep_hint

    def test_ell_fallback_link_down_stays_cold_and_correct(self):
        # worsening change on a small (non-banded) topology: no runner
        # with a banded graph to propagate the affected set over, so the
        # rebuild cold-starts — and the product still matches the host
        # oracle after the link removal
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        dests = fleet_destinations(ls, ps)
        cache = FleetViewCache()
        cache.view(ls, dests)
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2")],  # 1-3 link dropped
                node_label=101,
                area="0",
            )
        )
        v2 = cache.view(ls, fleet_destinations(ls, ps))
        assert not v2.warm
        assert v2.warm_mode is None
        assert_fleet_parity({"0": ls}, ps)

    def test_dest_change_blocks_warm(self):
        ls = self.ring_ls()
        # label-free dest control is impossible here (every ring node is
        # labeled), so change the ADVERTISER set size via a node whose
        # label is already a dest: drop a prefix advertised by a node
        # OUTSIDE the label set — instead, flip dest equality by asking
        # with an explicitly different dest list
        ps = prefix_state_with(("r063", "0", PrefixEntry(prefix=PFX)))
        cache = FleetViewCache()
        dests = fleet_destinations(ls, ps)
        cache.view(ls, dests)
        self._set_node(ls, 0, metric=lambda a, b: 5 if b == 1 else 20)
        v2 = cache.view(ls, dests[:-1])  # same topology, fewer dests
        assert not v2.warm
