"""LinkMonitor tests (modeled on openr/link-monitor/tests/LinkMonitorTest.cpp):
interface flap backoff, neighbor -> peer + adjacency advertisement gated on
KvStore initial sync, drain state APIs, RTT metrics.
"""

from __future__ import annotations

import time

import pytest

import openr_tpu.link_monitor.link_monitor as lm_mod
from openr_tpu.kvstore import InProcessTransport, KvStore, KvStoreClientInternal
from openr_tpu.link_monitor import LinkMonitor
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.serializer import loads
from openr_tpu.types import (
    AddrEvent,
    AdjacencyDatabase,
    KvStoreSyncEvent,
    LinkEvent,
    NeighborEvent,
    NeighborEventType,
    adj_key,
)


def neighbor_up(node, if_name="if1", area="0", rtt_us=1000) -> NeighborEvent:
    return NeighborEvent(
        event_type=NeighborEventType.NEIGHBOR_UP,
        node_name=node,
        if_name=if_name,
        remote_if_name=f"{if_name}-r",
        area=area,
        neighbor_addr_v6=f"fe80::{node}",
        ctrl_port=2018,
        rtt_us=rtt_us,
    )


class Harness:
    def __init__(self, **lm_kwargs):
        self.fabric = InProcessTransport()
        self.kv_updates: ReplicateQueue = ReplicateQueue()
        self.kv_syncs: ReplicateQueue = ReplicateQueue()
        self.peer_events: ReplicateQueue = ReplicateQueue()
        self.if_updates: ReplicateQueue = ReplicateQueue()
        self.nbr_events: ReplicateQueue = ReplicateQueue()
        self.sync_events: ReplicateQueue = ReplicateQueue()
        self.nl_events: ReplicateQueue = ReplicateQueue()
        self.if_reader = self.if_updates.get_reader()
        self.peer_reader = self.peer_events.get_reader()

        self.kvstore = KvStore(
            "node1",
            self.kv_updates,
            self.kv_syncs,
            self.peer_events.get_reader(),
            transport=self.fabric.bind("node1"),
        )
        self.fabric.register("node1", self.kvstore)
        self.kvstore.run()

        self.lm = LinkMonitor(
            "node1",
            interface_updates_queue=self.if_updates,
            peer_updates_queue=self.peer_events,
            neighbor_updates=self.nbr_events.get_reader(),
            kvstore_sync_events=self.sync_events.get_reader(),
            netlink_events=self.nl_events.get_reader(),
            **lm_kwargs,
        )
        self.lm.run()
        self.client = KvStoreClientInternal(
            self.lm, "node1", self.kvstore, check_persist_interval_s=60
        )
        self.lm.kvstore_client = self.client

    def adj_db(self) -> AdjacencyDatabase | None:
        raw = self.kvstore.get_key_vals("0", [adj_key("node1")]).key_vals.get(
            adj_key("node1")
        )
        return None if raw is None else loads(raw.value, AdjacencyDatabase)

    def stop(self):
        for q in (
            self.kv_updates,
            self.kv_syncs,
            self.peer_events,
            self.if_updates,
            self.nbr_events,
            self.sync_events,
            self.nl_events,
        ):
            q.close()
        self.client.stop()
        self.lm.stop()
        self.kvstore.stop()
        self.lm.wait_until_stopped(5)
        self.kvstore.wait_until_stopped(5)


@pytest.fixture
def harness():
    h = Harness()
    yield h
    h.stop()


def wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class TestLinkMonitor:
    def test_link_event_feeds_spark(self, harness):
        harness.nl_events.push(LinkEvent("if1", 1, True))
        db = harness.if_reader.get(timeout=5)
        assert db.interfaces["if1"].is_up
        assert db.this_node_name == "node1"

    def test_flap_backoff(self, harness, monkeypatch):
        harness.nl_events.push(LinkEvent("if1", 1, True))
        db = harness.if_reader.get(timeout=5)
        assert db.interfaces["if1"].is_up
        # flap: down -> up; up must be held back by backoff
        harness.nl_events.push(LinkEvent("if1", 1, False))
        db = harness.if_reader.get(timeout=5)
        assert not db.interfaces["if1"].is_up
        harness.nl_events.push(LinkEvent("if1", 1, True))
        db = harness.if_reader.get(timeout=5)
        assert not db.interfaces["if1"].is_up  # still in backoff
        # after backoff expires (1s initial) it comes up
        db = harness.if_reader.get(timeout=5)
        assert db.interfaces["if1"].is_up

    def test_addr_event_tracks_networks(self, harness):
        harness.nl_events.push(LinkEvent("if1", 1, True))
        harness.if_reader.get(timeout=5)
        harness.nl_events.push(AddrEvent("if1", "fc00::1/128", True))
        db = harness.if_reader.get(timeout=5)
        assert db.interfaces["if1"].networks == ["fc00::1/128"]
        harness.nl_events.push(AddrEvent("if1", "fc00::1/128", False))
        db = harness.if_reader.get(timeout=5)
        assert db.interfaces["if1"].networks == []

    def test_neighbor_up_creates_peer_and_gated_adj(self, harness):
        harness.nbr_events.push(neighbor_up("node2"))
        peer_event = harness.peer_reader.get(timeout=5)
        assert "node2" in peer_event.peers_to_add
        assert peer_event.peers_to_add["node2"].peer_addr == "fe80::node2"
        # adjacency NOT advertised until initial kvstore sync with the peer
        time.sleep(0.2)
        assert harness.adj_db() is None
        harness.sync_events.push(KvStoreSyncEvent("node2", "0"))
        assert wait_for(lambda: harness.adj_db() is not None)
        db = harness.adj_db()
        assert [a.other_node_name for a in db.adjacencies] == ["node2"]
        adj = db.adjacencies[0]
        assert adj.if_name == "if1"
        assert adj.other_if_name == "if1-r"
        assert adj.metric == 1
        assert adj.next_hop_v6 == "fe80::node2"

    def test_neighbor_down_removes_peer_and_adj(self, harness):
        harness.nbr_events.push(neighbor_up("node2"))
        harness.peer_reader.get(timeout=5)
        harness.sync_events.push(KvStoreSyncEvent("node2", "0"))
        assert wait_for(
            lambda: (db := harness.adj_db()) is not None and db.adjacencies
        )
        harness.nbr_events.push(
            NeighborEvent(
                event_type=NeighborEventType.NEIGHBOR_DOWN,
                node_name="node2",
                if_name="if1",
                area="0",
            )
        )
        peer_event = harness.peer_reader.get(timeout=5)
        assert peer_event.peers_to_del == ["node2"]
        assert wait_for(
            lambda: (db := harness.adj_db()) is not None and not db.adjacencies
        )

    def test_drain_apis(self, harness):
        harness.nbr_events.push(neighbor_up("node2"))
        harness.peer_reader.get(timeout=5)
        harness.sync_events.push(KvStoreSyncEvent("node2", "0"))
        assert wait_for(lambda: harness.adj_db() is not None)

        harness.lm.set_node_overload(True)
        assert wait_for(lambda: harness.adj_db().is_overloaded)
        harness.lm.set_link_overload("if1", True)
        assert wait_for(
            lambda: harness.adj_db().adjacencies[0].is_overloaded
        )
        harness.lm.set_link_metric("if1", 42)
        assert wait_for(lambda: harness.adj_db().adjacencies[0].metric == 42)
        # adj override beats link override
        harness.lm.set_adj_metric("if1", "node2", 77)
        assert wait_for(lambda: harness.adj_db().adjacencies[0].metric == 77)
        harness.lm.set_adj_metric("if1", "node2", None)
        harness.lm.set_link_metric("if1", None)
        assert wait_for(lambda: harness.adj_db().adjacencies[0].metric == 1)
        state = harness.lm.get_state()
        assert state.is_overloaded and "if1" in state.overloaded_links

    def test_parallel_links_independent(self, harness):
        """Two links to the same node: each is its own adjacency; the peer
        survives until the LAST link goes down."""
        harness.nbr_events.push(neighbor_up("node2", if_name="if1"))
        harness.peer_reader.get(timeout=5)
        harness.sync_events.push(KvStoreSyncEvent("node2", "0"))
        assert wait_for(
            lambda: (db := harness.adj_db()) is not None and len(db.adjacencies) == 1
        )
        harness.nbr_events.push(neighbor_up("node2", if_name="if2"))
        assert wait_for(lambda: len(harness.adj_db().adjacencies) == 2)

        # drop if1: adjacency shrinks, peer stays
        harness.nbr_events.push(
            NeighborEvent(
                event_type=NeighborEventType.NEIGHBOR_DOWN,
                node_name="node2",
                if_name="if1",
                area="0",
            )
        )
        assert wait_for(lambda: len(harness.adj_db().adjacencies) == 1)
        assert harness.adj_db().adjacencies[0].if_name == "if2"
        # drop if2: now the peer goes too
        harness.nbr_events.push(
            NeighborEvent(
                event_type=NeighborEventType.NEIGHBOR_DOWN,
                node_name="node2",
                if_name="if2",
                area="0",
            )
        )
        deadline = time.monotonic() + 5
        deleted = False
        while time.monotonic() < deadline and not deleted:
            ev = harness.peer_reader.get(timeout=5)
            deleted = "node2" in ev.peers_to_del
        assert deleted

    def test_rtt_metric(self):
        h = Harness(enable_rtt_metric=True)
        try:
            h.nbr_events.push(neighbor_up("node2", rtt_us=2500))
            h.peer_reader.get(timeout=5)
            h.sync_events.push(KvStoreSyncEvent("node2", "0"))
            assert wait_for(
                lambda: (db := h.adj_db()) is not None
                and db.adjacencies
                and db.adjacencies[0].metric == 25
            )
            h.nbr_events.push(
                NeighborEvent(
                    event_type=NeighborEventType.NEIGHBOR_RTT_CHANGE,
                    node_name="node2",
                    if_name="if1",
                    area="0",
                    rtt_us=10000,
                )
            )
            assert wait_for(lambda: h.adj_db().adjacencies[0].metric == 100)
        finally:
            h.stop()

    def test_node_label_advertised(self):
        h = Harness(node_label=101)
        try:
            h.nbr_events.push(neighbor_up("node2"))
            h.peer_reader.get(timeout=5)
            h.sync_events.push(KvStoreSyncEvent("node2", "0"))
            assert wait_for(
                lambda: (db := h.adj_db()) is not None and db.node_label == 101
            )
        finally:
            h.stop()
