"""Golden route-level conformance cases ported from the reference's
DecisionTest corpus (openr/decision/tests/DecisionTest.cpp, 6,888 LoC),
round-4 batch: the interactions r3 flagged as uncovered — ordered-FIB
holds x route build, BGP MetricVector x KSP2, multi-area redistribution,
prepend labels, min-nexthop x drain, parallel links, duplicate labels.

Every case runs against BOTH backends (host Dijkstra and the device
kernel) and asserts identical RouteDatabases before checking the golden
expectations; each test names its DecisionTest.cpp ancestor.
"""

from __future__ import annotations

import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    MetricEntity,
    MetricVector,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
    PrefixType,
    UnicastRoute,
)
from tests.test_spf_solver import (
    PFX,
    adj,
    build_link_state,
    prefix_state_with,
    square,
)


def routes(my_node: str, area_ls: dict, ps: PrefixState, **solver_kw):
    """Build the route DB on BOTH backends and assert parity; returns the
    host result (the golden assertions read it)."""
    host = SpfSolver(my_node, **solver_kw).build_route_db(area_ls, ps)
    device = SpfSolver(
        my_node,
        spf_backend=DeviceSpfBackend(min_device_nodes=1, min_device_sources=1),
        **solver_kw,
    ).build_route_db(area_ls, ps)
    if host is None or device is None:
        # unknown node: both backends must agree on nullopt
        assert host is None and device is None, my_node
        return None
    assert host.unicast_routes == device.unicast_routes, my_node
    assert host.mpls_routes == device.mpls_routes, my_node
    return host


def nh_names(route) -> set:
    return {nh.neighbor_node_name for nh in route.nexthops}


def sq_ksp(advertiser: str = "1", **entry_kw) -> PrefixState:
    return prefix_state_with(
        (
            advertiser,
            "0",
            PrefixEntry(
                prefix=PFX,
                forwarding_type=PrefixForwardingType.SR_MPLS,
                forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                **entry_kw,
            ),
        )
    )


class TestShortestPathEdgeCases:
    """Ancestors: ShortestPathTest.* (DecisionTest.cpp:471-597)."""

    def test_unreachable_nodes(self):
        # DecisionTest.cpp:471 UnreachableNodes: two disconnected pairs
        ls = build_link_state(
            {
                "1": [adj("1", "2")],
                "2": [adj("2", "1")],
                "3": [adj("3", "4")],
                "4": [adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
        )
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix="::2:0/112")),
        )
        db = routes("1", {"0": ls}, ps)
        assert PFX in db.unicast_routes
        assert "::2:0/112" not in db.unicast_routes  # other component
        # label routes exist only for the reachable component
        assert 102 in db.mpls_routes
        assert 103 not in db.mpls_routes and 104 not in db.mpls_routes

    def test_missing_neighbor_adjacency_db(self):
        # DecisionTest.cpp:511: 1 claims adj to 2, but 2 never reported —
        # the bidirectional-link check keeps the link out of SPF
        ls = build_link_state({"1": [adj("1", "2")]})
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes

    def test_empty_neighbor_adjacency_db(self):
        # DecisionTest.cpp:543: 2 reports an EMPTY adjacency list
        ls = build_link_state({"1": [adj("1", "2")], "2": []})
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes

    def test_unknown_node(self):
        # DecisionTest.cpp:579: solver for a node absent from the graph
        # returns nullopt (no route DB at all), on both backends
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        assert routes("99", {"0": ls}, ps) is None

    def test_adjacency_metric_update_reroutes(self):
        # DecisionTest.cpp:598 AdjacencyUpdate: one direction's metric
        # change moves traffic (asymmetric metrics are per-direction)
        adj_map = {
            "1": [adj("1", "2"), adj("1", "3")],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        }
        ls = build_link_state(adj_map)
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        # raise metric of 1->2: path via 3 only
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=50), adj("1", "3")],
                area="0",
            )
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}


class TestAttachedNodes:
    """Ancestor: SimpleRingTopologyFixture.AttachedNodesTest
    (DecisionTest.cpp:2921-2967): the default route is an ordinary
    anycast prefix — advertisers ('attached' nodes) build NO route to
    it themselves, everyone else ECMPs toward the nearest advertisers."""

    DEFAULT = "::/0"

    def _ps(self):
        return prefix_state_with(
            ("1", "0", PrefixEntry(prefix=PFX)),
            ("1", "0", PrefixEntry(prefix=self.DEFAULT)),
            ("4", "0", PrefixEntry(prefix="::4:0/112")),
            ("4", "0", PrefixEntry(prefix=self.DEFAULT)),
        )

    def test_attached_advertiser_has_no_default_route(self):
        for me in ("1", "4"):
            db = routes(me, {"0": square()}, self._ps())
            assert self.DEFAULT not in db.unicast_routes, me

    def test_transit_nodes_ecmp_toward_nearest_attached(self):
        # 2 and 3 sit at distance 10 from BOTH advertisers -> ECMP {1, 4}
        for me in ("2", "3"):
            db = routes(me, {"0": square()}, self._ps())
            assert nh_names(db.unicast_routes[self.DEFAULT]) == {"1", "4"}, me

    def test_default_follows_nearest_after_metric_change(self):
        # pull node 2 toward node 4: the default route drops the farther
        # advertiser (1) and keeps only 4
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4", metric=1)],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2", metric=1), adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
        )
        db = routes("2", {"0": ls}, self._ps())
        assert nh_names(db.unicast_routes[self.DEFAULT]) == {"4"}


class TestParallelAdjacencies:
    """Ancestors: ParallelAdjRingTopologyFixture.ShortestPathTest /
    MultiPathTest (DecisionTest.cpp:3413, 3547), DecisionTestFixture.
    ParallelLinks (:5917)."""

    @staticmethod
    def parallel_ls(m1: int = 10, m2: int = 10) -> LinkState:
        a = Adjacency(
            other_node_name="2",
            if_name="1/2-a",
            other_if_name="2/1-a",
            metric=m1,
            next_hop_v6="fe80::2a",
        )
        b = Adjacency(
            other_node_name="2",
            if_name="1/2-b",
            other_if_name="2/1-b",
            metric=m2,
            next_hop_v6="fe80::2b",
        )
        ra = Adjacency(
            other_node_name="1",
            if_name="2/1-a",
            other_if_name="1/2-a",
            metric=m1,
            next_hop_v6="fe80::1a",
        )
        rb = Adjacency(
            other_node_name="1",
            if_name="2/1-b",
            other_if_name="1/2-b",
            metric=m2,
            next_hop_v6="fe80::1b",
        )
        ls = LinkState("0")
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1", adjacencies=[a, b], area="0",
                node_label=101,
            )
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2", adjacencies=[ra, rb], area="0",
                node_label=102,
            )
        )
        return ls

    def test_equal_parallel_links_both_used(self):
        ls = self.parallel_ls(10, 10)
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.if_name for nh in route.nexthops} == {"1/2-a", "1/2-b"}

    def test_unequal_parallel_links_best_only(self):
        ls = self.parallel_ls(10, 20)
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.if_name for nh in route.nexthops} == {"1/2-a"}

    def test_parallel_link_flap_reroutes(self):
        # ParallelLinks (:5917): losing the cheap link falls over to the
        # remaining one
        ls = self.parallel_ls(10, 20)
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        routes("1", {"0": ls}, ps)
        # re-advertise node 1 with only the expensive link
        b = Adjacency(
            other_node_name="2",
            if_name="1/2-b",
            other_if_name="2/1-b",
            metric=20,
            next_hop_v6="fe80::2b",
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1", adjacencies=[b], area="0", node_label=101
            )
        )
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.if_name for nh in route.nexthops} == {"1/2-b"}
        assert all(nh.metric == 20 for nh in route.nexthops)


class TestDuplicateNodeLabels:
    """Ancestor: SimpleRingTopologyFixture.DuplicateMplsRoutes
    (DecisionTest.cpp:2037)."""

    def test_duplicate_label_programs_single_route(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            labels={"1": 102, "2": 102, "3": 103, "4": 104},  # 1 == 2!
        )
        db = routes("3", {"0": ls}, PrefixState())
        # exactly ONE route for label 102 (not two conflicting ones)
        assert 102 in db.mpls_routes
        assert 103 in db.mpls_routes and 104 in db.mpls_routes

    def test_duplicate_resolved_after_relabel(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            labels={"1": 102, "2": 102, "3": 103, "4": 104},
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2"), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        db = routes("3", {"0": ls}, PrefixState())
        assert 101 in db.mpls_routes and 102 in db.mpls_routes


class TestOverloadInteractions:
    """Ancestors: SimpleRingTopologyFixture.OverloadNodeTest (:2974),
    OverloadLinkTest (:3093), x min-nexthop (IpToMplsLabelPrepend case 2,
    :2296)."""

    def test_overload_node_no_transit_golden(self):
        # ring 1-2, 1-3, 2-4, 3-4 with 2 and 3 overloaded: from 2, node 3
        # is reachable only via the long way 2->1->... no: 2-1-3 transits
        # 1 (ok). From 2 to 3: direct paths 2-4-3 and 2-1-3 — both transit
        # a non-overloaded node: ECMP of both (OverloadNodeTest golden)
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
            overloaded={"2", "3"},
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = routes("2", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"1", "4"}
        # label route to 3 mirrors the ECMP with SWAPs
        r3 = db.mpls_routes[103]
        assert nh_names(r3) == {"1", "4"}
        for nh in r3.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.SWAP, swap_label=103
            )

    def test_overload_link_disconnects(self):
        # OverloadLinkTest (:3093): overloading BOTH of node 3's links
        # leaves it unreachable from 1
        a31 = adj("3", "1")
        a31.is_overloaded = True
        a34 = adj("3", "4")
        a34.is_overloaded = True
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [a31, a34],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            labels={"1": 101, "2": 102, "3": 103, "4": 104},
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes
        assert 103 not in db.mpls_routes

    def test_overload_link_one_side_reroutes(self):
        # overloading 3's link to 1 (only) forces 1->3 via 2-4
        a31 = adj("3", "1")
        a31.is_overloaded = True
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [a31, adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2"}

    def test_min_nexthop_with_drained_transit(self):
        # min-nexthop x drain (r3 gap): draining 2 removes one ECMP arm;
        # a min_nexthop=2 prefix at 4 must then be withdrawn from 1
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            overloaded={"2"},
        )
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX, min_nexthop=2))
        )
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes
        # with min_nexthop=1 the surviving arm programs
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX, min_nexthop=1))
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}


class TestPrependLabels:
    """Ancestor: SimpleRingTopologyFixture.IpToMplsLabelPrepend
    (DecisionTest.cpp:2228)."""

    PREPEND = 60001

    def test_prepend_label_added_to_push_stack(self):
        # case-3 (:2316): remote advertiser with prepend label — PUSH
        # stack becomes [prepend, node-label]
        ls = square()
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    prepend_label=self.PREPEND,
                ),
            )
        )
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        for nh in route.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH,
                push_labels=(self.PREPEND, 104),
            )

    def test_prepend_label_to_neighbor_pushes_prepend_only(self):
        # neighbor advertiser: no node label to push, prepend alone rides
        ls = square()
        ps = prefix_state_with(
            (
                "2",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    prepend_label=self.PREPEND,
                ),
            )
        )
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2"}
        for nh in route.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(self.PREPEND,)
            )

    def test_invalid_prepend_label_empties_nexthops(self):
        # :2343 isMplsLabelValid guard — an out-of-range prepend label
        # skips every nexthop; the reference's addBestPaths still emits
        # the (empty) RibUnicastEntry (Decision.cpp:1090-1150 has no
        # empty-set early-out), so parity means: route present, no hops
        ls = square()
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    prepend_label=(1 << 20) + 7,  # > 20-bit label space
                ),
            )
        )
        db = routes("1", {"0": ls}, ps)
        assert db.unicast_routes[PFX].nexthops == frozenset()

    def test_self_prepend_label_with_static_nexthops(self):
        # case-4 (:2337-2397): the advertiser itself reports the prefix
        # with a prepend label + static MPLS nexthops for that label; its
        # own route carries the remote PUSH arms plus the static hops
        ls = square()
        entry = PrefixEntry(
            prefix=PFX,
            forwarding_type=PrefixForwardingType.SR_MPLS,
            prepend_label=self.PREPEND,
        )
        ps = prefix_state_with(("1", "0", entry), ("4", "0", entry))
        static_hops = [
            NextHop(address="1.1.1.1", mpls_action=MplsAction(MplsActionCode.PHP)),
            NextHop(address="2.2.2.2", mpls_action=MplsAction(MplsActionCode.PHP)),
        ]

        def with_static(solver):
            solver.update_static_mpls_routes(
                [MplsRoute(top_label=self.PREPEND, next_hops=static_hops)], []
            )
            return solver.build_route_db({"0": ls}, ps)

        host = with_static(SpfSolver("1"))
        device = with_static(
            SpfSolver("1", spf_backend=DeviceSpfBackend(min_device_nodes=1, min_device_sources=1))
        )
        assert host.unicast_routes == device.unicast_routes
        route = host.unicast_routes[PFX]
        addrs = {nh.address for nh in route.nexthops}
        # static next-hops surface (PUSH action stripped, :2365 NOTE)
        assert {"1.1.1.1", "2.2.2.2"} <= addrs
        static_in_route = [
            nh for nh in route.nexthops if nh.address in ("1.1.1.1", "2.2.2.2")
        ]
        assert all(nh.mpls_action is None for nh in static_in_route)
        # remote arms toward 4 push [prepend, label4]
        remote = [nh for nh in route.nexthops if nh.neighbor_node_name]
        assert remote and all(
            nh.mpls_action
            == MplsAction(
                MplsActionCode.PUSH, push_labels=(self.PREPEND, 104)
            )
            for nh in remote
        )


def mv(value: int, priority: int = 1, tie_breaker: bool = False) -> MetricVector:
    return MetricVector(
        metrics=[
            MetricEntity(
                type=1,
                priority=priority,
                is_best_path_tie_breaker=tie_breaker,
                metric=[value],
            )
        ]
    )


class TestBgpMetricVectorKsp2:
    """Ancestors: SimpleRingTopologyFixture.Ksp2EdEcmpForBGP (:2602),
    Ksp2EdEcmpForBGP123 (:2798), BGPRedistribution.IgpMetric (:973)."""

    @staticmethod
    def bgp_entry(value: int, **kw) -> PrefixEntry:
        return PrefixEntry(
            prefix=PFX,
            type=PrefixType.BGP,
            mv=mv(value),
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            **kw,
        )

    def test_bgp_winner_gets_ksp2_paths(self):
        # :2602 — the higher metric-vector advertiser wins BGP selection,
        # and KSP2 computes two edge-disjoint label paths to IT
        ls = square()
        ps = prefix_state_with(
            ("2", "0", self.bgp_entry(100)),
            ("4", "0", self.bgp_entry(200)),  # winner
        )
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert route.best_prefix_entry.mv == mv(200)
        # both edge-disjoint paths lead to 4: direct arms via 2 and 3
        assert nh_names(route) == {"2", "3"}

    def test_bgp_plain_tie_skips_route(self):
        # :893-897 — equal vectors with NO tie-breaker entity is a plain
        # TIE: the reference logs and skips the route entirely
        ls = square()
        ps = prefix_state_with(
            ("2", "0", self.bgp_entry(200)),
            ("3", "0", self.bgp_entry(200)),
        )
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes

    def test_bgp_tie_breaker_keeps_both_advertisers(self):
        # TIE_WINNER/TIE_LOOSER accumulate: a tie-breaker entity orders
        # the best entry but keeps BOTH advertisers in allNodeAreas
        # (:881-887), so the ECMP merges their paths
        ls = square()
        e2 = PrefixEntry(
            prefix=PFX,
            type=PrefixType.BGP,
            mv=mv(2, tie_breaker=True),
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )
        e3 = PrefixEntry(
            prefix=PFX,
            type=PrefixType.BGP,
            mv=mv(1, tie_breaker=True),
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )
        ps = prefix_state_with(("2", "0", e2), ("3", "0", e3))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # tie-winner is the best entry; both advertisers' paths merge
        assert route.best_prefix_entry.mv == mv(2, tie_breaker=True)
        assert nh_names(route) >= {"2", "3"}

    def test_bgp_loser_flip_reroutes(self):
        # flip the winner: routes must follow the new best advertiser
        ls = square()
        ps = prefix_state_with(
            ("2", "0", self.bgp_entry(300)),
            ("4", "0", self.bgp_entry(200)),
        )
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # winner is the neighbor 2: first path direct; second
        # edge-disjoint path around the ring
        assert "2" in nh_names(route)
        assert route.best_prefix_entry.mv == mv(300)

    def test_bgp_ksp2_min_nexthop_interaction(self):
        # KSP2 winner with min_nexthop above the path count: withdrawn
        ls = square()
        ps = prefix_state_with(
            ("4", "0", self.bgp_entry(200, min_nexthop=3)),
        )
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes


class TestBgpIgpMetricSequence:
    """Ancestor: BGPRedistribution.IgpMetric (DecisionTest.cpp:973-1137):
    two BGP advertisers whose vectors differ only in a tie-breaker entity
    both stay selected, so the route's next-hop set follows pure IGP
    distance through metric changes, a link drain, and the undrain."""

    def _ps(self):
        # same vector on priority-1, tie-breaker entity differs -> both
        # advertisers retained (TIE_WINNER orders, does not exclude)
        def entry(tb_value: int) -> PrefixEntry:
            return PrefixEntry(
                prefix=PFX,
                type=PrefixType.BGP,
                mv=MetricVector(
                    metrics=[
                        MetricEntity(
                            type=1, priority=2, metric=[7]
                        ),
                        MetricEntity(
                            type=2,
                            priority=1,
                            is_best_path_tie_breaker=True,
                            metric=[tb_value],
                        ),
                    ]
                ),
            )

        return prefix_state_with(
            ("2", "0", entry(1)),
            ("3", "0", entry(100)),
        )

    @staticmethod
    def _y(m13=10, drain_12=False):
        a12 = adj("1", "2")
        a12.is_overloaded = drain_12
        a21 = adj("2", "1")
        a21.is_overloaded = drain_12
        return build_link_state(
            {
                "1": [a12, adj("1", "3", metric=m13)],
                "2": [a21],
                "3": [adj("3", "1", metric=m13)],
            },
            labels={"1": 101, "2": 102, "3": 103},
        )

    def test_equal_igp_distance_ecmps_both(self):
        db = routes("1", {"0": self._y()}, self._ps())
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

    def test_costlier_advertiser_dropped(self):
        # cost toward 3 raised to 20 -> only 2 remains (IgpMetric step 2)
        db = routes("1", {"0": self._y(m13=20)}, self._ps())
        assert nh_names(db.unicast_routes[PFX]) == {"2"}

    def test_drained_nearest_falls_back_to_far(self):
        # link to 2 drained (both directions) -> 3 serves despite cost 20
        db = routes("1", {"0": self._y(m13=20, drain_12=True)}, self._ps())
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        # node 2's loopback-ish reachability is gone with the link
        assert 102 not in db.mpls_routes

    def test_undrain_restores_ecmp_at_equal_cost(self):
        # undrain with both legs at 20 -> ECMP again (IgpMetric step 5)
        ls = build_link_state(
            {
                "1": [adj("1", "2", metric=20), adj("1", "3", metric=20)],
                "2": [adj("2", "1", metric=20)],
                "3": [adj("3", "1", metric=20)],
            },
            labels={"1": 101, "2": 102, "3": 103},
        )
        db = routes("1", {"0": ls}, self._ps())
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}


class TestMultiAreaRedistribution:
    """Ancestor: DecisionTestFixture.MultiAreaBestPathCalculation
    (DecisionTest.cpp:5420) + SelfReditributePrefixPublication (:5563)."""

    @staticmethod
    def two_areas() -> dict:
        # area 0: 1 -- 2 ;  area 1: 1 -- 3   (node 1 spans both)
        ls0 = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]}, area="0"
        )
        ls1 = build_link_state(
            {"1": [adj("1", "3")], "3": [adj("3", "1")]}, area="1"
        )
        return {"0": ls0, "1": ls1}

    def test_cross_area_best_path(self):
        # the same prefix advertised in both areas: area-local advertiser
        # wins on distance at node 2's solver (10 vs 20 via 1)
        areas = self.two_areas()
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("3", "1", PrefixEntry(prefix=PFX)),
        )
        db = routes("1", areas, ps)
        route = db.unicast_routes[PFX]
        # node 1 sees both at distance 10: ECMP across areas
        assert nh_names(route) == {"2", "3"}
        areas_used = {nh.area for nh in route.nexthops}
        assert areas_used == {"0", "1"}

    def test_single_area_advertiser_reached_cross_area(self):
        areas = self.two_areas()
        ps = prefix_state_with(("3", "1", PrefixEntry(prefix=PFX)))
        db = routes("1", areas, ps)
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"3"}
        assert {nh.area for nh in route.nexthops} == {"1"}

    def test_redistributed_self_entry_not_looped(self):
        # SelfReditributePrefixPublication (:5563): a prefix this node
        # re-advertised into another area must not produce a self route
        areas = self.two_areas()
        ps = prefix_state_with(
            ("3", "1", PrefixEntry(prefix=PFX)),
            # node 1's own redistribution of the same prefix into area 0
            ("1", "0", PrefixEntry(prefix=PFX)),
        )
        db = routes("1", areas, ps)
        # node 1 is among the best advertisers -> no route programmed on 1
        # (reference: createRouteForPrefix skips self-advertised best)
        assert PFX not in db.unicast_routes
        # ...but node 2 in area 0 reaches it via 1
        db2 = routes("2", areas, ps)
        assert PFX in db2.unicast_routes
        assert nh_names(db2.unicast_routes[PFX]) == {"1"}


class TestBestRouteSelectionChain:
    """Ancestors: Decision.BestRouteSelection (DecisionTest.cpp:1139),
    EnableBestRouteSelectionFixture.PrefixWithMixedTypeRoutes (:6719),
    DecisionTestFixture.DuplicatePrefixes (:6267)."""

    def test_metrics_chain_flips(self):
        # path_preference dominates, then source_preference, then
        # distance — flip each level and watch the winner move
        ls = square()

        def entry(pp, sp):
            return PrefixEntry(
                prefix=PFX,
                metrics=PrefixMetrics(
                    path_preference=pp, source_preference=sp
                ),
            )

        ps = prefix_state_with(
            ("2", "0", entry(2000, 100)), ("3", "0", entry(1000, 900))
        )
        db = routes(
            "1", {"0": ls}, ps, enable_best_route_selection=True
        )
        assert nh_names(db.unicast_routes[PFX]) == {"2"}  # pp wins
        ps = prefix_state_with(
            ("2", "0", entry(2000, 100)), ("3", "0", entry(2000, 900))
        )
        db = routes(
            "1", {"0": ls}, ps, enable_best_route_selection=True
        )
        assert nh_names(db.unicast_routes[PFX]) == {"3"}  # sp breaks tie

    def test_mixed_bgp_nonbgp_requires_best_route_selection(self):
        # :6719 — a prefix advertised BGP by one node and RIB by another
        # is rejected without best-route selection and resolved with it
        ls = square()
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX, type=PrefixType.BGP, mv=mv(1))),
            ("3", "0", PrefixEntry(prefix=PFX, type=PrefixType.RIB)),
        )
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes  # mixed types rejected
        db = routes(
            "1", {"0": ls}, ps, enable_best_route_selection=True
        )
        assert PFX in db.unicast_routes  # selection resolves the mix

    def test_duplicate_prefix_withdrawal_keeps_other_advertiser(self):
        # DuplicatePrefixes (:6267): two advertisers, one withdraws —
        # the route survives via the other
        ls = square()
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix=PFX)),
        )
        db = routes("1", {"0": ls}, ps)
        assert "2" in nh_names(db.unicast_routes[PFX])
        ps.delete_prefix("2", "0", PFX)
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # advertiser 4 remains: ECMP via both neighbors at distance 20
        assert nh_names(route) == {"2", "3"}
        assert all(nh.metric == 20 for nh in route.nexthops)


class TestDuplicatePrefixTieBreaksPersistentPair:
    """Ancestors: DecisionTestFixture.DuplicatePrefixes (:6267) +
    Decision.BestRouteSelection (:1139), the tie-break ordering cases —
    ported onto ONE persistent dual-backend solver pair (the PR-5
    harness): every advertise/withdraw step rebuilds on the same host
    and device solvers and asserts route parity AND identical
    best-route cache verdicts, so the selection state machine (not a
    fresh solver's first impression) is what's proven."""

    @staticmethod
    def entry(pp=1000, sp=100, dist=0):
        return PrefixEntry(
            prefix=PFX,
            metrics=PrefixMetrics(
                path_preference=pp, source_preference=sp, distance=dist
            ),
        )

    def test_metric_tie_breaks_to_lowest_originator(self):
        ls = square()
        ps = PrefixState()
        host = SpfSolver("1", enable_best_route_selection=True)
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
            enable_best_route_selection=True,
        )
        steps = 0

        def check():
            nonlocal steps
            steps += 1
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, steps
            assert h.mpls_routes == d.mpls_routes, steps
            hb = host.best_routes_cache.get(PFX)
            db_ = device.best_routes_cache.get(PFX)
            if hb is None or db_ is None:
                assert hb is None and db_ is None, steps
                return h, None
            assert hb.best_node_area == db_.best_node_area, steps
            assert hb.all_node_areas == db_.all_node_areas, steps
            return h, hb

        # 1: full metric tie between 2 and 3 — both kept (ECMP), the
        # representative advertiser is the LOWEST originator
        ps.update_prefix("2", "0", self.entry())
        ps.update_prefix("3", "0", self.entry())
        db, best = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        assert best.best_node_area == ("2", "0")
        assert best.all_node_areas == {("2", "0"), ("3", "0")}

        # 2: a third tied advertiser joins; selection keeps all three,
        # the originator tie-break is unmoved, and forwarding still
        # points at the nearest advertisers only
        ps.update_prefix("4", "0", self.entry())
        db, best = check()
        assert best.best_node_area == ("2", "0")
        assert best.all_node_areas == {("2", "0"), ("3", "0"), ("4", "0")}
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # 3: the lowest originator withdraws — tie re-breaks to the next
        # lowest, on the same solver pair
        ps.delete_prefix("2", "0", PFX)
        db, best = check()
        assert best.best_node_area == ("3", "0")
        assert best.all_node_areas == {("3", "0"), ("4", "0")}

        # 4: distance ASC beats originator order: "3" readvertises with
        # a worse (higher) distance, so "4" wins alone despite being
        # lexicographically higher
        ps.update_prefix("3", "0", self.entry(dist=2))
        db, best = check()
        assert best.best_node_area == ("4", "0")
        assert best.all_node_areas == {("4", "0")}
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}  # to 4

        # 5: path_preference dominates the whole chain — "3" comes back
        # with higher pp and takes the route from "4" outright
        ps.update_prefix("3", "0", self.entry(pp=2000, dist=2))
        db, best = check()
        assert best.best_node_area == ("3", "0")
        assert best.all_node_areas == {("3", "0")}
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

        # 6: restore the exact tie — selection converges back to the
        # lowest-originator verdict, bit-identical on both backends
        ps.update_prefix("3", "0", self.entry())
        ps.update_prefix("4", "0", self.entry())
        db, best = check()
        assert best.best_node_area == ("3", "0")
        assert best.all_node_areas == {("3", "0"), ("4", "0")}
        # forwarding follows the nearest advertiser (3 at 10, 4 at 20)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        assert steps == 6

    def test_source_preference_tie_still_breaks_by_originator(self):
        """sp ties at a non-default value must NOT shadow the
        originator rule: equal (pp, sp, distance) keeps the set and
        the lowest advertiser as representative."""
        ls = square()
        ps = prefix_state_with(
            ("3", "0", self.entry(sp=500)),
            ("4", "0", self.entry(sp=500)),
        )
        db = routes("1", {"0": ls}, ps, enable_best_route_selection=True)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        host = SpfSolver("1", enable_best_route_selection=True)
        host.build_route_db({"0": ls}, ps)
        best = host.best_routes_cache[PFX]
        assert best.best_node_area == ("3", "0")
        assert best.all_node_areas == {("3", "0"), ("4", "0")}


class TestPartialSyncSequencesPersistentPair:
    """Ancestors: DecisionTestFixture's incremental-publication cases
    (DecisionTest.cpp adj-db update/withdraw sequences around :1400 and
    the prefix-churn counterparts): the daemon never re-syncs the world
    — it applies adjacency-only or prefix-only deltas to live state.
    Ported onto ONE persistent dual-backend solver pair so each partial
    step rebuilds on solvers carrying warm SPF/best-route caches from
    the previous step, and parity (unicast + MPLS) must hold at every
    intermediate state, not just the final one."""

    @staticmethod
    def _pair():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )

        def check(ls, ps, step):
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, step
            assert h.mpls_routes == d.mpls_routes, step
            return h

        return check

    def test_adjacency_only_sequence_on_pinned_prefixes(self):
        # prefixes are synced ONCE; every later step is an adjacency-
        # only delta (metric change, link loss, node loss, node rejoin)
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        check = self._pair()

        db = check(ls, ps, "baseline")
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # 1: metric-only adj update — 1-2 worsens, path shifts via 3
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=50), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        db = check(ls, ps, "worsen-1-2")
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

        # 2: link loss — 3 drops its side of 3-4; the bidirectional
        # check kills the link, forcing the long way around via 2
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="3",
                adjacencies=[adj("3", "1")],
                node_label=103,
                area="0",
            )
        )
        db = check(ls, ps, "drop-3-4")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2"}
        assert all(nh.metric == 60 for nh in route.nexthops)

        # 3: node loss — 2's adj db withdrawn entirely; the advertiser
        # is unreachable and the route (and 4's label) must vanish
        ls.delete_adjacency_database("2")
        db = check(ls, ps, "lose-node-2")
        assert PFX not in db.unicast_routes
        assert 104 not in db.mpls_routes

        # 4: rejoin + heal — 2 and 3 republish full adjacency sets and
        # 1 restores its calibrated metric; ECMP comes back bit-exact
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                node_label=102,
                area="0",
            )
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="3",
                adjacencies=[adj("3", "1"), adj("3", "4")],
                node_label=103,
                area="0",
            )
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2"), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        db = check(ls, ps, "heal")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        assert all(nh.metric == 20 for nh in route.nexthops)
        assert 104 in db.mpls_routes

    def test_prefix_only_sequence_on_pinned_topology(self):
        # the topology is synced ONCE; every later step is a prefix-
        # only delta (advertise, second advertiser, withdraw, flip-back)
        ls = square()
        ps = PrefixState()
        check = self._pair()

        db = check(ls, ps, "empty")
        assert PFX not in db.unicast_routes
        assert 102 in db.mpls_routes  # labels come from topology alone

        # 1: first advertiser appears on 4
        ps.update_prefix("4", "0", PrefixEntry(prefix=PFX))
        db = check(ls, ps, "advertise-4")
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # 2: a nearer advertiser joins on 2 — forwarding collapses to
        # the closest advertiser without any topology event
        ps.update_prefix("2", "0", PrefixEntry(prefix=PFX))
        db = check(ls, ps, "advertise-2")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2"}
        assert all(nh.metric == 10 for nh in route.nexthops)

        # 3: the near advertiser withdraws — the far one takes back over
        ps.delete_prefix("2", "0", PFX)
        db = check(ls, ps, "withdraw-2")
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # 4: the last advertiser withdraws — the route vanishes while
        # the label plane (topology-derived) is untouched
        ps.delete_prefix("4", "0", PFX)
        db = check(ls, ps, "withdraw-4")
        assert PFX not in db.unicast_routes
        assert 102 in db.mpls_routes and 104 in db.mpls_routes

        # 5: flip-back on a different node — state from the withdrawn
        # advertisers must not leak into the fresh advertisement
        ps.update_prefix("3", "0", PrefixEntry(prefix=PFX))
        db = check(ls, ps, "advertise-3")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"3"}
        assert all(nh.metric == 10 for nh in route.nexthops)


class TestOrderedFibHolds:
    """Ancestor: the ordered-FIB hold machinery (HoldableValue,
    LinkState.cpp decrementHolds + DecisionTest hold coverage): route
    builds during the hold window must see the HELD topology, and the
    hold decrement must atomically reveal the new one."""

    def test_metric_hold_defers_reroute_until_decrement(self):
        adj_map = {
            "1": [adj("1", "2"), adj("1", "3")],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        }
        ls = build_link_state(adj_map)
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # metric bump arrives WITH a hold (ordered-FIB): the route build
        # must still use the old metric until holds decrement
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=50), adj("1", "3")],
                area="0",
            ),
            hold_up_ttl=2,
            hold_down_ttl=2,
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}  # held
        assert ls.has_holds()

        # decrement to expiry: the new metric takes effect
        while ls.has_holds():
            ls.decrement_holds()
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

    def test_overload_hold_defers_drain(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            }
        )
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        # drain node 2 under a hold: traffic keeps flowing through it
        # until the hold decrements (make-before-break)
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                is_overloaded=True,
                area="0",
            ),
            hold_up_ttl=1,
            hold_down_ttl=1,
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        while ls.has_holds():
            ls.decrement_holds()
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

    def test_ksp2_during_hold_window(self):
        # holds x KSP2 (r3 gap): the masked KSP2 re-run must ALSO see the
        # held topology, not the pending one
        ls = square()
        ps = sq_ksp("4")
        db = routes("1", {"0": ls}, ps)
        base_hops = nh_names(db.unicast_routes[PFX])
        assert base_hops == {"2", "3"}
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=100), adj("1", "3")],
                node_label=101,
                area="0",
            ),
            hold_up_ttl=2,
            hold_down_ttl=2,
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}  # held
        while ls.has_holds():
            ls.decrement_holds()
        db = routes("1", {"0": ls}, ps)
        # after the hold, the 1->2 arm costs 100: KSP first path rides 3,
        # second edge-disjoint path still uses 2 (disjointness wins over
        # cost — reference Ksp2EdEcmp longer-second-path semantics)
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        by_nh = {nh.neighbor_node_name: nh.metric for nh in route.nexthops}
        assert by_nh["3"] < by_nh["2"]


# ---------------------------------------------------------------------------
# Round-5 tranche: RibPolicy interactions, static-overlay edges, label-range
# edges, multi-event sequences (flap storms, churn during holds).
# ---------------------------------------------------------------------------

from openr_tpu.decision.rib_policy import (  # noqa: E402
    PolicyError,
    RibPolicy,
    RibPolicyConfig,
    RibPolicyStatementConfig,
    RibRouteActionWeight,
)


def policy(*statements, ttl_secs: int = 60) -> RibPolicy:
    return RibPolicy(
        RibPolicyConfig(statements=list(statements), ttl_secs=ttl_secs)
    )


def weights_by_neighbor(route) -> dict:
    return {nh.neighbor_node_name: nh.weight for nh in route.nexthops}


class TestRibPolicyInteractions:
    """Ancestors: DecisionTestFixture.RibPolicy / RibPolicyError
    (DecisionTest.cpp:5644-5776) + RibPolicyTest.cpp — applied here to
    route DBs computed by BOTH backends (the policy transform must see
    identical inputs either way)."""

    def test_area_weight_applies_per_area(self):
        # cross-area ECMP: area-0 arm via 2, area-1 arm via 3
        ls0 = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]}, area="0"
        )
        ls1 = LinkState("1")
        for node, adjs in (("1", [adj("1", "3")]), ("3", [adj("3", "1")])):
            ls1.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=node, adjacencies=adjs, area="1"
                )
            )
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("3", "1", PrefixEntry(prefix=PFX)),
        )
        db = routes("1", {"0": ls0, "1": ls1}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.area for nh in route.nexthops} == {"0", "1"}
        pol = policy(
            RibPolicyStatementConfig(
                name="area-w",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"0": 7, "1": 3}
                ),
            )
        )
        change = pol.apply_policy(db.unicast_routes)
        assert change.updated_routes == [PFX]
        by_area = {nh.area: nh.weight for nh in route.nexthops}
        assert by_area == {"0": 7, "1": 3}

    def test_neighbor_weight_overrides_area(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        pol = policy(
            RibPolicyStatementConfig(
                name="nb-w",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1,
                    area_to_weight={"0": 5},
                    neighbor_to_weight={"2": 9},
                ),
            )
        )
        assert pol.apply_policy(db.unicast_routes).updated_routes == [PFX]
        assert weights_by_neighbor(route) == {"2": 9, "3": 5}

    def test_zero_weight_drops_nexthop(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        pol = policy(
            RibPolicyStatementConfig(
                name="drop-2",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1, neighbor_to_weight={"2": 0}
                ),
            )
        )
        pol.apply_policy(db.unicast_routes)
        assert nh_names(route) == {"3"}

    def test_all_zero_weights_retain_nexthops(self):
        # RibPolicy.cpp:146-158: never transform a route into a blackhole
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        route = db.unicast_routes[PFX]
        before = set(route.nexthops)
        pol = policy(
            RibPolicyStatementConfig(
                name="blackhole",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(default_weight=0),
            )
        )
        change = pol.apply_policy(db.unicast_routes)
        assert change.updated_routes == []
        assert set(route.nexthops) == before

    def test_tag_matcher_transforms_only_tagged(self):
        ls = square()
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX, tags=("edge",))),
            ("4", "0", PrefixEntry(prefix="::2:0/112")),
        )
        db = routes("1", {"0": ls}, ps)
        pol = policy(
            RibPolicyStatementConfig(
                name="tagged",
                tags=["edge"],
                set_weight=RibRouteActionWeight(default_weight=4),
            )
        )
        change = pol.apply_policy(db.unicast_routes)
        assert change.updated_routes == [PFX]
        assert all(
            nh.weight == 4 for nh in db.unicast_routes[PFX].nexthops
        )
        assert all(
            nh.weight == 0
            for nh in db.unicast_routes["::2:0/112"].nexthops
        )

    def test_first_matching_statement_wins(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        pol = policy(
            RibPolicyStatementConfig(
                name="first",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(default_weight=2),
            ),
            RibPolicyStatementConfig(
                name="second",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(default_weight=8),
            ),
        )
        pol.apply_policy(db.unicast_routes)
        assert all(
            nh.weight == 2 for nh in db.unicast_routes[PFX].nexthops
        )

    def test_expired_policy_is_noop(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        before = set(db.unicast_routes[PFX].nexthops)
        pol = policy(
            RibPolicyStatementConfig(
                name="expired",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(default_weight=5),
            ),
            ttl_secs=0,
        )
        assert not pol.is_active()
        assert pol.apply_policy(db.unicast_routes).updated_routes == []
        assert set(db.unicast_routes[PFX].nexthops) == before

    def test_policy_requires_statements_and_matcher(self):
        with pytest.raises(PolicyError):
            RibPolicy(RibPolicyConfig(statements=[], ttl_secs=10))
        with pytest.raises(PolicyError):
            policy(
                RibPolicyStatementConfig(
                    name="no-matcher",
                    set_weight=RibRouteActionWeight(default_weight=1),
                )
            )
        with pytest.raises(PolicyError):
            policy(RibPolicyStatementConfig(name="no-action", prefixes=[PFX]))


class TestRibPolicyAreaInteractions:
    """Ancestors: DecisionTestFixture.RibPolicy (DecisionTest.cpp:5644)
    x MultiAreaBestPathCalculation (:5420) — the policy's area-keyed
    weight action applied over genuinely multi-area route DBs (the
    two_areas() topology: node 1 spans area 0 via 2 and area 1 via 3),
    computed by BOTH backends through routes()."""

    @staticmethod
    def cross_area_db(ps=None):
        areas = TestMultiAreaRedistribution.two_areas()
        if ps is None:
            ps = prefix_state_with(
                ("2", "0", PrefixEntry(prefix=PFX)),
                ("3", "1", PrefixEntry(prefix=PFX)),
            )
        return routes("1", areas, ps)

    def test_area_weight_zero_drops_one_areas_arm(self):
        # steer all traffic onto the area-0 arm: area-1 weight 0 drops
        # the cross-area next-hop entirely, not just down-weights it
        db = self.cross_area_db()
        route = db.unicast_routes[PFX]
        assert {nh.area for nh in route.nexthops} == {"0", "1"}
        pol = policy(
            RibPolicyStatementConfig(
                name="drain-area-1",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"0": 1, "1": 0}
                ),
            )
        )
        assert pol.apply_policy(db.unicast_routes).updated_routes == [PFX]
        assert {nh.area for nh in route.nexthops} == {"0"}
        assert nh_names(route) == {"2"}

    def test_all_areas_zeroed_retains_cross_area_ecmp(self):
        # the blackhole guard (RibPolicy.cpp:146-158) must hold when the
        # zeros arrive via the area map rather than default_weight
        db = self.cross_area_db()
        route = db.unicast_routes[PFX]
        before = set(route.nexthops)
        pol = policy(
            RibPolicyStatementConfig(
                name="drain-everything",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"0": 0, "1": 0}
                ),
            )
        )
        assert pol.apply_policy(db.unicast_routes).updated_routes == []
        assert set(route.nexthops) == before

    def test_neighbor_weight_overrides_area_weight_cross_area(self):
        # neighbor 3 sits in area 1; its per-neighbor weight must beat
        # the area-1 weight while area 0 keeps its area-level value
        db = self.cross_area_db()
        route = db.unicast_routes[PFX]
        pol = policy(
            RibPolicyStatementConfig(
                name="nb-beats-area",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1,
                    area_to_weight={"0": 5, "1": 2},
                    neighbor_to_weight={"3": 9},
                ),
            )
        )
        assert pol.apply_policy(db.unicast_routes).updated_routes == [PFX]
        assert weights_by_neighbor(route) == {"2": 5, "3": 9}

    def test_unknown_area_falls_back_to_default_weight(self):
        # the weight map names an area that is not in the route: both
        # arms take default_weight (RibPolicy.cpp's map lookup fallback)
        db = self.cross_area_db()
        route = db.unicast_routes[PFX]
        pol = policy(
            RibPolicyStatementConfig(
                name="no-such-area",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=4, area_to_weight={"9": 1}
                ),
            )
        )
        assert pol.apply_policy(db.unicast_routes).updated_routes == [PFX]
        assert weights_by_neighbor(route) == {"2": 4, "3": 4}

    def test_prefix_matcher_scopes_to_one_areas_prefix(self):
        # distinct prefixes advertised from different areas: the policy
        # transforms only the matched one, leaving the other area's
        # route untouched — weights stay the solver's defaults
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("3", "1", PrefixEntry(prefix="::2:0/112")),
        )
        db = self.cross_area_db(ps)
        other_before = set(db.unicast_routes["::2:0/112"].nexthops)
        pol = policy(
            RibPolicyStatementConfig(
                name="area0-prefix-only",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(default_weight=6),
            )
        )
        change = pol.apply_policy(db.unicast_routes)
        assert change.updated_routes == [PFX]
        assert all(
            nh.weight == 6 for nh in db.unicast_routes[PFX].nexthops
        )
        assert set(db.unicast_routes["::2:0/112"].nexthops) == other_before

    def test_redistribution_consumer_sees_area_weight(self):
        # SelfReditributePrefixPublication (:5563) interaction: node 2
        # reaches the area-1 prefix via node 1's area-0 re-advertisement,
        # so from 2's perspective the route is purely area-0 and the
        # area-0 weight applies to the single next-hop
        areas = TestMultiAreaRedistribution.two_areas()
        ps = prefix_state_with(
            ("3", "1", PrefixEntry(prefix=PFX)),
            ("1", "0", PrefixEntry(prefix=PFX)),
        )
        db2 = routes("2", areas, ps)
        route = db2.unicast_routes[PFX]
        assert nh_names(route) == {"1"}
        pol = policy(
            RibPolicyStatementConfig(
                name="consumer-side",
                prefixes=[PFX],
                set_weight=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"0": 8}
                ),
            )
        )
        assert pol.apply_policy(db2.unicast_routes).updated_routes == [PFX]
        assert weights_by_neighbor(route) == {"1": 8}


class TestStaticOverlayEdges:
    """Ancestors: static-route handling in buildRouteDb
    (Decision.cpp:427-449 createRouteForPrefixOrGetStaticRoute,
    :776-791 static overlays appended last)."""

    @staticmethod
    def sq_solvers():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        return host, device

    @staticmethod
    def static_nh(addr="fe80::9", metric=0):
        return NextHop(address=addr, metric=metric)

    def both(self, solver_pair, area_ls, ps):
        host = solver_pair[0].build_route_db(area_ls, ps)
        device = solver_pair[1].build_route_db(area_ls, ps)
        assert host.unicast_routes == device.unicast_routes
        assert host.mpls_routes == device.mpls_routes
        return host

    def test_computed_wins_over_static(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_unicast_routes(
                [UnicastRoute(dest=PFX, next_hops=[self.static_nh()])], []
            )
        db = self.both(pair, {"0": ls}, ps)
        # the computed route's nexthops, not the static one's
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

    def test_static_surfaces_after_withdrawal(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_unicast_routes(
                [UnicastRoute(dest=PFX, next_hops=[self.static_nh()])], []
            )
        ps.delete_prefix("4", "0", PFX)
        db = self.both(pair, {"0": ls}, ps)
        assert {nh.address for nh in db.unicast_routes[PFX].nexthops} == {
            "fe80::9"
        }

    def test_static_only_prefix_coexists(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_unicast_routes(
                [
                    UnicastRoute(
                        dest="::5:0/112", next_hops=[self.static_nh()]
                    )
                ],
                [],
            )
        db = self.both(pair, {"0": ls}, ps)
        assert PFX in db.unicast_routes
        assert "::5:0/112" in db.unicast_routes

    def test_static_mpls_loses_to_node_label(self):
        ls = square()  # node labels 101..104
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_mpls_routes(
                [
                    MplsRoute(
                        top_label=102, next_hops=[self.static_nh()]
                    )
                ],
                [],
            )
        db = self.both(pair, {"0": ls}, ps)
        # 102 is node 2's label: the computed label route wins
        assert all(
            nh.address != "fe80::9" for nh in db.mpls_routes[102].nexthops
        )

    def test_static_mpls_unused_label_appears(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_mpls_routes(
                [
                    MplsRoute(
                        top_label=7777, next_hops=[self.static_nh()]
                    )
                ],
                [],
            )
        db = self.both(pair, {"0": ls}, ps)
        assert {nh.address for nh in db.mpls_routes[7777].nexthops} == {
            "fe80::9"
        }

    def test_static_update_then_delete(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        pair = self.sq_solvers()
        for s in pair:
            s.update_static_unicast_routes(
                [
                    UnicastRoute(
                        dest="::5:0/112", next_hops=[self.static_nh()]
                    )
                ],
                [],
            )
            s.update_static_unicast_routes(
                [
                    UnicastRoute(
                        dest="::5:0/112",
                        next_hops=[self.static_nh(addr="fe80::a")],
                    )
                ],
                [],
            )
        db = self.both(pair, {"0": ls}, ps)
        assert {
            nh.address for nh in db.unicast_routes["::5:0/112"].nexthops
        } == {"fe80::a"}
        for s in pair:
            s.update_static_unicast_routes([], ["::5:0/112"])
        db = self.both(pair, {"0": ls}, ps)
        assert "::5:0/112" not in db.unicast_routes


class TestLabelRangeEdges:
    """Ancestors: MplsRoutes.BasicTest label-validity handling
    (DecisionTest.cpp:737-780; isMplsLabelValid, openr/common/Util.h) —
    the 20-bit MPLS label space boundaries."""

    def test_labels_at_range_bounds_valid(self):
        lo, hi = 16, (1 << 20) - 1
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": lo, "2": hi},
        )
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert lo in db.mpls_routes and hi in db.mpls_routes

    def test_label_above_max_skipped(self):
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": 101, "2": 1 << 20},
        )
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert (1 << 20) not in db.mpls_routes
        assert PFX in db.unicast_routes  # unicast unaffected

    def test_label_below_min_skipped(self):
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": 101, "2": 15},
        )
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert 15 not in db.mpls_routes
        assert 101 in db.mpls_routes  # own POP_AND_LOOKUP route intact

    def test_invalid_adj_label_skipped(self):
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": 101, "2": 102},
        )
        for link in ls.links_from_node("1"):
            link.set_adj_label_from_node("1", (1 << 20) + 5)
        ls._invalidate()
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert ((1 << 20) + 5) not in db.mpls_routes

    def test_relabel_invalid_to_valid(self):
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": 101, "2": 1 << 20},
        )
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert (1 << 20) not in db.mpls_routes
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1")],
                node_label=500,
                area="0",
            )
        )
        db = routes("1", {"0": ls}, ps)
        assert 500 in db.mpls_routes


class TestLabelRangeExhaustion:
    """Ancestors: MplsRoutes.BasicTest label validity (DecisionTest.cpp
    :737-780) x DuplicateMplsRoutes (:2037) — the EXHAUSTION corner of
    the 20-bit space: node labels packing the last valid slots, an
    allocator that wrapped past the edge, and a collision on the final
    slot.  Distinct from TestLabelRangeEdges (single boundary labels):
    these cases interact several top-of-range labels in one topology,
    against the engine-backed solver pair via routes()."""

    def _ring_ls(self, labels):
        return build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "4")],
                "2": [adj("2", "1"), adj("2", "3")],
                "3": [adj("3", "2"), adj("3", "4")],
                "4": [adj("4", "3"), adj("4", "1")],
            },
            labels=labels,
        )

    def test_top_of_range_packs_without_collision(self):
        # the last four valid slots all program: no off-by-one at the
        # 2^20-1 ceiling when neighbors also sit at the ceiling
        hi = (1 << 20) - 1
        ls = self._ring_ls(
            {"1": hi - 3, "2": hi - 2, "3": hi - 1, "4": hi}
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        for lbl in (hi - 2, hi - 1, hi):
            assert lbl in db.mpls_routes, lbl
        assert PFX in db.unicast_routes

    def test_exhausted_allocator_collides_on_final_slot(self):
        # exhaustion symptom: two nodes claim the one remaining slot;
        # exactly one route programs for it and the rest of the space
        # still resolves (the duplicate-label rule at the range edge)
        hi = (1 << 20) - 1
        ls = self._ring_ls({"1": hi - 1, "2": hi, "3": hi, "4": 105})
        db = routes("4", {"0": ls}, PrefixState())
        assert hi in db.mpls_routes
        assert len(db.mpls_routes[hi].nexthops) >= 1
        assert (hi - 1) in db.mpls_routes
        assert 105 in db.mpls_routes  # own POP_AND_LOOKUP intact

    def test_wrap_past_max_skipped_then_recovered_into_free_slot(self):
        # an allocator that wrapped past the edge emits 2^20: invalid,
        # skipped (unicast untouched); relabeling into the still-free
        # top slot recovers the MPLS route — the operator remediation
        hi = (1 << 20) - 1
        ls = self._ring_ls(
            {"1": hi - 2, "2": hi - 1, "3": hi + 1, "4": 105}
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = routes("1", {"0": ls}, ps)
        assert (hi + 1) not in db.mpls_routes
        assert PFX in db.unicast_routes
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="3",
                adjacencies=[adj("3", "2"), adj("3", "4")],
                node_label=hi,
                area="0",
            )
        )
        db = routes("1", {"0": ls}, ps)
        assert hi in db.mpls_routes


class TestMultiEventSequences:
    """Ancestors: the longer DecisionTestFixture sequences
    (BasicOperations :4787, PubDebouncing :6024, DuplicatePrefixes
    :6267) — adjacency churn, flap storms, withdraw/re-advertise, and
    interactions with hold windows, asserted at the route level."""

    @staticmethod
    def sq_map(m12=10):
        return {
            "1": [adj("1", "2", metric=m12), adj("1", "3")],
            "2": [adj("2", "1", metric=m12), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        }

    def test_flap_storm_final_state(self):
        ls = build_link_state(self.sq_map())
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        # 6 alternating flaps of the 1-2 arm (reported by node 1)
        for i in range(6):
            adjs = (
                [adj("1", "3")]
                if i % 2 == 0
                else [adj("1", "2"), adj("1", "3")]
            )
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="1", adjacencies=adjs, area="0"
                )
            )
            db = routes("1", {"0": ls}, ps)
            expected = {"3"} if i % 2 == 0 else {"2", "3"}
            assert nh_names(db.unicast_routes[PFX]) == expected, i
        # final state equals a freshly-built equivalent topology
        fresh = build_link_state(self.sq_map())
        db_churned = routes("1", {"0": ls}, ps)
        db_fresh = routes("1", {"0": fresh}, ps)
        assert db_churned.unicast_routes == db_fresh.unicast_routes

    def test_churn_during_hold_falls_back_to_fast_update(self):
        ls = build_link_state(self.sq_map())
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        # two metric updates inside one hold window: 10 -> 50 -> 9.
        # Reference semantics (HoldableValue::updateValue,
        # LinkState.cpp:93-98): a second change while a hold is active
        # CANCELS the hold ("fall back to fast update" — holding longer
        # risks longer transient loops), so the final value applies
        # immediately, not at decrement time.
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=50), adj("1", "3")],
                area="0",
            ),
            hold_up_ttl=3,
            hold_down_ttl=3,
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}  # held at 10
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=9), adj("1", "3")],
                area="0",
            ),
            hold_up_ttl=3,
            hold_down_ttl=3,
        )
        db = routes("1", {"0": ls}, ps)
        # metric 9 visible immediately: 1->2->4 costs 19 < 1->3->4 20
        assert nh_names(db.unicast_routes[PFX]) == {"2"}
        assert not ls.has_holds()

    def test_node_delete_and_readd(self):
        ls = build_link_state(self.sq_map())
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        assert nh_names(routes("1", {"0": ls}, ps).unicast_routes[PFX]) == {
            "2",
            "3",
        }
        change = ls.delete_adjacency_database("2")
        assert change.topology_changed
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                area="0",
            )
        )
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

    def test_withdraw_readvertise_different_node(self):
        ls = build_link_state(self.sq_map())
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        assert PFX in routes("1", {"0": ls}, ps).unicast_routes
        ps.delete_prefix("4", "0", PFX)
        db = routes("1", {"0": ls}, ps)
        assert PFX not in db.unicast_routes
        ps.update_prefix("2", "0", PrefixEntry(prefix=PFX))
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2"}

    def test_overload_toggle_sequence(self):
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        ls = build_link_state(self.sq_map())
        for overloaded, expected in (
            (True, {"3"}),
            (False, {"2", "3"}),
            (True, {"3"}),
        ):
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="2",
                    adjacencies=[adj("2", "1"), adj("2", "4")],
                    is_overloaded=overloaded,
                    area="0",
                )
            )
            db = routes("1", {"0": ls}, ps)
            assert nh_names(db.unicast_routes[PFX]) == expected

    def test_hold_then_node_delete_no_stale_routes(self):
        ls = build_link_state(self.sq_map())
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        # 2 drains under a hold, then disappears entirely before the
        # hold decrements: deletion must not leave held state behind
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                is_overloaded=True,
                area="0",
            ),
            hold_up_ttl=4,
            hold_down_ttl=4,
        )
        ls.delete_adjacency_database("2")
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        while ls.has_holds():
            ls.decrement_holds()
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"3"}


class TestLongPublicationSequenceEngineBacked:
    """Satellite (PR 5): a 25-event publication sequence — adjacency and
    prefix updates interleaved with TTL expiry — against ONE persistent
    solver pair: host Dijkstra vs the device backend routed through the
    residency engine.  Persistence is the point: the engine must absorb
    the whole stream through its incremental-residency path (fresh
    solvers per event would re-upload the graph and prove nothing).
    Ancestors: DecisionTestFixture BasicOperations (:4787) and
    PubDebouncing (:6024) event streams."""

    P2 = "::2:0/112"
    P3 = "::3:0/112"

    @staticmethod
    def ring6(m12=10, m56=10):
        return {
            "1": [adj("1", "2", metric=m12), adj("1", "3")],
            "2": [adj("2", "1", metric=m12), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "5")],
            "4": [adj("4", "2"), adj("4", "6")],
            "5": [adj("5", "3"), adj("5", "6", metric=m56)],
            "6": [adj("6", "4"), adj("6", "5", metric=m56)],
        }

    def test_25_event_stream_parity_and_incremental_residency(self):
        ls = build_link_state(self.ring6())
        ps = PrefixState()
        host = SpfSolver("1")
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        device = SpfSolver("1", spf_backend=backend)
        engine = backend.engine
        assert engine is not None
        events = 0

        def check():
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, events
            assert h.mpls_routes == d.mpls_routes, events
            return h

        def pub(node, adjs, **kw):
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name=node, adjacencies=adjs, area="0", **kw
                )
            )

        def step(mutate):
            nonlocal events
            mutate()
            events += 1
            return check()

        r = self.ring6()
        # 1-2: prefix advertisements land
        db = step(lambda: ps.update_prefix("6", "0", PrefixEntry(prefix=PFX)))
        assert PFX in db.unicast_routes
        step(lambda: ps.update_prefix("4", "0", PrefixEntry(prefix=self.P2)))
        # 3-4: metric raise + restore on the 1-2 arm
        step(lambda: pub("1", self.ring6(m12=50)["1"]))
        step(lambda: pub("1", r["1"]))
        # 5-7: transit drain of node 2 around a new advertisement
        step(lambda: pub("2", r["2"], is_overloaded=True))
        step(lambda: ps.update_prefix("3", "0", PrefixEntry(prefix=self.P3)))
        step(lambda: pub("2", r["2"]))
        # 8-10: link 1-3 down (edge-set change), prefix TTL expiry of
        # node 3's announcements, link back up
        step(lambda: pub("1", [adj("1", "2")]))
        db = step(lambda: ps.delete_all_from_node("3", "0"))
        assert self.P3 not in db.unicast_routes
        step(lambda: pub("1", r["1"]))
        # 11-13: far-side metric churn; node 4's adjacency database
        # TTL-expires wholesale, then the node re-announces
        step(lambda: pub("5", self.ring6(m56=77)["5"]))
        step(lambda: ls.delete_adjacency_database("4"))
        step(lambda: pub("4", r["4"]))
        # 14-17: duplicate re-advertisement, metric restore, overload
        # pulse on node 5
        step(lambda: ps.update_prefix("6", "0", PrefixEntry(prefix=PFX)))
        step(lambda: pub("5", r["5"]))
        step(lambda: pub("5", r["5"], is_overloaded=True))
        step(lambda: pub("5", r["5"]))
        # 18-19: prefix TTL expiry of P2, re-advertised by a new owner
        step(lambda: ps.delete_prefix("4", "0", self.P2))
        db = step(
            lambda: ps.update_prefix("2", "0", PrefixEntry(prefix=self.P2))
        )
        assert self.P2 in db.unicast_routes
        # 20-22: metric shift, link 2-4 flap
        step(lambda: pub("1", self.ring6(m12=15)["1"]))
        step(lambda: pub("2", [adj("2", "1", metric=15)]))
        step(lambda: pub("2", self.ring6(m12=15)["2"]))
        # 23-25: own-node overload pulse, then settle
        step(lambda: pub("1", self.ring6(m12=15)["1"], is_overloaded=True))
        step(lambda: pub("1", self.ring6(m12=15)["1"]))
        db = step(lambda: ps.update_prefix("5", "0", PrefixEntry(prefix=self.P3)))
        assert self.P3 in db.unicast_routes

        assert events == 25
        # the stream really went through the engine, and mostly through
        # its incremental path (edge-set changes legitimately restage)
        c = engine.get_counters()
        assert c["device.engine.queries"] > 0
        assert c["device.engine.incremental_updates"] >= 10
        # initial upload + the two node-set changes (adj-db expiry +
        # re-announce of node 4); the four bounded edge-set changes
        # (link 1-3 down/up, link 2-4 down/up) ride the rewire rung in
        # place, everything else goes through the incremental path
        assert c["device.engine.full_restages"] == 3
        assert c["device.engine.rewires"] == 4
        assert c["device.engine.rewire_fallbacks"] == 0
        # settled state matches a freshly-built equivalent topology on
        # fresh solvers (the routes() harness)
        fresh = build_link_state(self.ring6(m12=15))
        db_fresh = routes("1", {"0": fresh}, ps)
        assert db_fresh.unicast_routes == check().unicast_routes


class TestDeltaPathEventParity:
    """DecisionTest-tranche slice for the incremental delta rung: a
    persistent solver with fleet_delta=True and one with the legacy full
    path consume the same interleaved adjacency + metric + overload
    event stream, and every intermediate fleet RIB must be identical —
    the delta product is a pure perf substitution, never a route change."""

    NODES = [
        "r000", "r001", "r004", "r016", "r031", "r032", "r047", "r063"
    ]

    def test_interleaved_events_identical_ribs(self):
        from tests.test_delta import _ps, ring_ls, set_node

        ls = ring_ls()
        ps = _ps()
        area_ls = {"0": ls}

        def backend():
            return DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)

        solver_delta = SpfSolver(
            "r000", spf_backend=backend(), fleet_delta=True
        )
        solver_full = SpfSolver(
            "r000", spf_backend=backend(), fleet_delta=False
        )

        def step(mutate=None):
            if mutate is not None:
                mutate()
            dbs_d = solver_delta.fleet_route_dbs(area_ls, ps, nodes=self.NODES)
            dbs_f = solver_full.fleet_route_dbs(area_ls, ps, nodes=self.NODES)
            assert dbs_d.keys() == dbs_f.keys()
            for node in dbs_d:
                assert (
                    dbs_d[node].unicast_routes == dbs_f[node].unicast_routes
                ), node
                assert (
                    dbs_d[node].mpls_routes == dbs_f[node].mpls_routes
                ), node

        step()  # cold baseline
        # metric worsen + restore on the r000-r001 link
        step(lambda: set_node(ls, 0, metric=lambda a, b: 90 if b == 1 else 20))
        step(lambda: set_node(ls, 0))
        # adjacency down + up (edge-set change: slot re-encode rung)
        step(lambda: set_node(ls, 0, drop=1))
        step(lambda: set_node(ls, 0))
        # overload pulse on a transit node (dense frontier: the delta
        # solver falls back to the legacy program — parity must hold
        # through the fallback too)
        step(lambda: set_node(ls, 5, is_overloaded=True))
        step(lambda: set_node(ls, 5))
        # coalesced batch: two metric events land between rebuilds
        def batch():
            set_node(ls, 4, metric=lambda a, b: 5 if b == 5 else 20)
            set_node(ls, 2, metric=lambda a, b: 70 if b == 3 else 20)

        step(batch)

        # the delta rung really carried updates (not wall-to-wall fallback)
        assert solver_delta.counters["decision.delta.updates"] >= 4
        assert solver_delta.counters["decision.delta.events_coalesced"] >= 5
        # and the legacy solver never touched it
        assert solver_full.counters["decision.delta.updates"] == 0


class TestOcsOverlayEdges:
    """DecisionTest-tranche slice (ISSUE 11): static overlay edges
    expressed as OCS-style edge injections.  A persistent dual-backend
    solver pair consumes a base hexagon plus programmed overlay
    circuits injected, swapped, and retired mid-stream; route parity
    must hold at every step, a programmed circuit must actually attract
    traffic, and every bounded injection rides the CSR slot freelist +
    engine rewire rung — the graph uploads exactly once.
    Ancestors: DecisionTest.cpp ParallelLinks / topology-overlay cases
    (adjacency sets changing under persistent solvers)."""

    @staticmethod
    def hexagon(overlays=()):
        """1-2-4-6-5-3-1 ring; `overlays` are extra (a, b, metric)
        circuits injected symmetrically on both endpoints."""
        adjs = {
            "1": [adj("1", "2"), adj("1", "3")],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "5")],
            "4": [adj("4", "2"), adj("4", "6")],
            "5": [adj("5", "3"), adj("5", "6")],
            "6": [adj("6", "4"), adj("6", "5")],
        }
        for a, b, m in overlays:
            adjs[a].append(adj(a, b, metric=m))
            adjs[b].append(adj(b, a, metric=m))
        return adjs

    def test_overlay_injection_swap_and_retirement(self):
        ls = build_link_state(self.hexagon())
        ps = prefix_state_with(("6", "0", PrefixEntry(prefix=PFX)))
        host = SpfSolver("1")
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        device = SpfSolver("1", spf_backend=backend)
        engine = backend.engine

        def push(overlays):
            for node, adjs in self.hexagon(overlays).items():
                ls.update_adjacency_database(
                    AdjacencyDatabase(
                        this_node_name=node, adjacencies=adjs, area="0"
                    )
                )

        def check():
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes
            assert h.mpls_routes == d.mpls_routes
            return h

        # baseline: two equal 3-hop arms toward the advertiser
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # inject the 1-6 circuit: programmed capacity attracts the flow
        push([("1", "6", 5)])
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"6"}

        # second overlay elsewhere: parity through a 2-circuit overlay
        push([("1", "6", 5), ("2", "5", 5)])
        check()

        # OCS swap: retire 1-6, program 3-6 — the flow follows the
        # reprogrammed circuit through node 3
        push([("2", "5", 5), ("3", "6", 5)])
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

        # retire every overlay: bit-exact return to the base ring
        push([])
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # all four edge-set events were bounded rewires on the same
        # resident graph: one upload, zero fallbacks
        c = engine.get_counters()
        assert c["device.engine.full_restages"] == 1
        assert c["device.engine.rewires"] == 4
        assert c["device.engine.rewire_fallbacks"] == 0
        # 2+2 injected slots, 2 swapped in place (retire+recycle share
        # a slot), 4 retired on the final push
        assert c["device.engine.rewire_slots"] >= 10


def nh_weights(route) -> dict:
    return {nh.neighbor_node_name: nh.weight for nh in route.nexthops}


def wadj(me: str, other: str, metric: int = 10, weight: int = 1) -> Adjacency:
    a = adj(me, other, metric=metric)
    a.weight = weight
    return a


class TestUcmpWeightsPersistentPair:
    """Ancestors: the DecisionTest Ucmp tranche (DecisionTestFixture.Ucmp
    + SpfSolver weight-propagation cases) — ECMP next-hops stay
    weightless, SP_UCMP_PREFIX_WEIGHT_PROPAGATION turns advertised
    `PrefixEntry.weight` into gcd-normalized next-hop weights, and
    SP_UCMP_ADJ_WEIGHT_PROPAGATION takes the first-hop adjacency
    weight.  Ported onto ONE persistent dual-backend solver pair: every
    advertise/re-weight/withdraw step rebuilds on the same host and
    device solvers and asserts full route parity (NextHop equality
    includes the weight field, so the device kernel must reproduce the
    weights bit for bit, not just the next-hop set)."""

    @staticmethod
    def uentry(weight=None, algo=None):
        return PrefixEntry(
            prefix=PFX,
            forwarding_algorithm=(
                PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION
                if algo is None
                else algo
            ),
            weight=weight,
        )

    @staticmethod
    def pair():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        return host, device

    def test_ecmp_next_hops_carry_no_weight(self):
        """SP_ECMP baseline: the weight field stays 0 even when the
        advertiser sets a prefix weight (the algorithm, not the entry
        field, turns UCMP on)."""
        db = routes(
            "1",
            {"0": square()},
            prefix_state_with(("4", "0", PrefixEntry(prefix=PFX, weight=300))),
        )
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 0, "3": 0}

    def test_prefix_weight_propagation_lifecycle(self):
        ls = square()
        ps = PrefixState()
        host, device = self.pair()
        steps = 0

        def check():
            nonlocal steps
            steps += 1
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, steps
            assert h.mpls_routes == d.mpls_routes, steps
            return h

        # 1: anycast from 2 (w=400) and 3 (w=100), both one hop from
        # 1 — weights normalize by gcd to 4:1
        ps.update_prefix("2", "0", self.uentry(weight=400))
        ps.update_prefix("3", "0", self.uentry(weight=100))
        db = check()
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 4, "3": 1}

        # 2: re-advertise 3 at w=200 on the SAME solver pair — the
        # normalization follows (gcd 200 -> 2:1)
        ps.update_prefix("3", "0", self.uentry(weight=200))
        db = check()
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 2, "3": 1}

        # 3: the heavier advertiser withdraws — the survivor normalizes
        # to weight 1
        ps.delete_prefix("2", "0", PFX)
        db = check()
        assert nh_weights(db.unicast_routes[PFX]) == {"3": 1}

        # 4: both advertise with NO weight set: UCMP degrades to plain
        # ECMP (weight 0) instead of black-holing the route
        ps.update_prefix("2", "0", self.uentry())
        ps.update_prefix("3", "0", self.uentry())
        db = check()
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 0, "3": 0}

        # 5: one advertiser downgrades to SP_ECMP — min-compatible
        # algorithm selection turns the whole route back to ECMP even
        # though the other still asks for UCMP with a weight
        ps.update_prefix(
            "2",
            "0",
            self.uentry(algo=PrefixForwardingAlgorithm.SP_ECMP),
        )
        ps.update_prefix("3", "0", self.uentry(weight=500))
        db = check()
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 0, "3": 0}
        assert steps == 5

    def test_weights_restricted_to_min_metric_advertisers(self):
        """A weighted advertiser that loses the metric race contributes
        nothing: 2 is one hop away, 4 is two hops — only 2's weight
        survives and normalizes to 1."""
        db = routes(
            "1",
            {"0": square()},
            prefix_state_with(
                ("2", "0", self.uentry(weight=100)),
                ("4", "0", self.uentry(weight=500)),
            ),
        )
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 1}

    def test_shared_first_hop_accumulates_advertiser_weights(self):
        """Two equal-distance advertisers behind one first-hop: the
        next-hop accumulates both weights.  1-2, then 2-3 and 2-4 with
        3 (w=100) and 4 (w=300) advertising — neighbor 2 carries
        100+300, the direct advertiser 5 (w=400) on a parallel arm
        matches it, so the pair normalizes to 1:1."""
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "5", metric=20)],
                "2": [adj("2", "1"), adj("2", "3"), adj("2", "4")],
                "3": [adj("3", "2")],
                "4": [adj("4", "2")],
                "5": [adj("5", "1", metric=20)],
            }
        )
        db = routes(
            "1",
            {"0": ls},
            prefix_state_with(
                ("3", "0", self.uentry(weight=100)),
                ("4", "0", self.uentry(weight=300)),
                ("5", "0", self.uentry(weight=400)),
            ),
        )
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 1, "5": 1}

    def test_adj_weight_propagation_uses_first_hop_weights(self):
        """SP_UCMP_ADJ_WEIGHT_PROPAGATION reads the local adjacency
        weight, not the advertised prefix weight."""
        ls = build_link_state(
            {
                "1": [wadj("1", "2", weight=6), wadj("1", "3", weight=2)],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            }
        )
        db = routes(
            "1",
            {"0": ls},
            prefix_state_with(
                (
                    "4",
                    "0",
                    self.uentry(
                        weight=999,  # ignored by adj propagation
                        algo=(
                            PrefixForwardingAlgorithm
                            .SP_UCMP_ADJ_WEIGHT_PROPAGATION
                        ),
                    ),
                )
            ),
        )
        assert nh_weights(db.unicast_routes[PFX]) == {"2": 3, "3": 1}

    def test_drained_weighted_advertiser_degrades_to_ecmp(self):
        """The drain filter runs before weighting: when the only
        weighted advertiser is overloaded, the surviving set has no
        positive weight and ships as plain ECMP instead of a black
        hole."""
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            overloaded={"2"},
        )
        db = routes(
            "1",
            {"0": ls},
            prefix_state_with(
                ("2", "0", self.uentry(weight=700)),
                ("3", "0", self.uentry()),
            ),
        )
        assert nh_weights(db.unicast_routes[PFX]) == {"3": 0}


class TestDrainLifecyclePersistentPair:
    """Ancestors: SimpleRingTopologyFixture.OverloadNodeTest (:2974) +
    the semi-drain cases around nodeMetricIncrementVal
    (DecisionTest's drained-metric goldens), stepped as ONE lifecycle:
    hard drain (is_overloaded, a transit cutoff), soft drain
    (node_metric_increment_val folded into every metric the node
    originates — proportional steering, not a cutoff), and recovery,
    all replayed through update_adjacency_database on one LinkState
    against ONE persistent dual-backend solver pair with full route
    parity at every step."""

    @staticmethod
    def pair():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )
        return host, device

    # square() neighbor map, used to re-advertise one node's db with new
    # drain state while keeping its adjacencies bit-identical
    SQUARE = {
        "1": ("2", "3"),
        "2": ("1", "4"),
        "3": ("1", "4"),
        "4": ("2", "3"),
    }

    @classmethod
    def readvertise(cls, ls, node, inc=0, overloaded=False):
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=node,
                adjacencies=[adj(node, o) for o in cls.SQUARE[node]],
                is_overloaded=overloaded,
                node_label=100 + int(node),
                area="0",
                node_metric_increment_val=inc,
            )
        )

    def test_drain_lifecycle(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        host, device = self.pair()
        steps = 0

        def check():
            nonlocal steps
            steps += 1
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, steps
            assert h.mpls_routes == d.mpls_routes, steps
            return h

        # 1: baseline square — ECMP to 4 via both arms at cost 20
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}

        # 2: soft-drain 2 (+100): the 2->4 hop costs 110, so the via-2
        # path loses (120 > 20) — traffic steers to 3, but 2 stays a
        # legal transit (no cutoff)
        self.readvertise(ls, "2", inc=100)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

        # 3: soft-drain 3 too (+100): the drain is RELATIVE — with both
        # arms equally inflated (120 each) ECMP returns at the higher
        # cost, where a hard drain of both would have black-holed
        self.readvertise(ls, "3", inc=100)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        assert {nh.metric for nh in db.unicast_routes[PFX].nexthops} == {120}

        # 4: undrain 3, hard-drain 2 — transit cutoff beats any metric:
        # only the via-3 arm survives
        self.readvertise(ls, "3")
        self.readvertise(ls, "2", overloaded=True)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

        # 5: hard-drain 3 as well — no transit-legal path to 4 remains;
        # the route (and 4's node-label route) disappear instead of
        # pointing through a drained node
        self.readvertise(ls, "3", overloaded=True)
        db = check()
        assert PFX not in db.unicast_routes
        assert 104 not in db.mpls_routes

        # 6: full recovery on the same solver pair
        self.readvertise(ls, "2")
        self.readvertise(ls, "3")
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        assert {nh.metric for nh in db.unicast_routes[PFX].nexthops} == {20}

        # 7: soft-draining YOURSELF shifts every egress equally — the
        # selection is unchanged, only the advertised cost rises
        self.readvertise(ls, "1", inc=50)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        assert steps == 7

    def test_soft_drain_is_proportional(self):
        """Unlike the overload bit, the increment competes on cost: an
        increment smaller than the alternative-path slack leaves the
        drained node carrying traffic."""
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3", metric=50)],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1", metric=50), adj("3", "4", metric=50)],
                "4": [adj("4", "2"), adj("4", "3", metric=50)],
            }
        )
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        host, device = self.pair()

        def check():
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes
            assert h.mpls_routes == d.mpls_routes
            return h

        def drain2(inc):
            ls.update_adjacency_database(
                AdjacencyDatabase(
                    this_node_name="2",
                    adjacencies=[adj("2", "1"), adj("2", "4")],
                    area="0",
                    node_metric_increment_val=inc,
                )
            )

        # via 2: 20; via 3: 100
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2"}

        # +10 is within the 80-cost slack: 2 keeps the traffic at 30
        drain2(10)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"2"}
        assert {nh.metric for nh in db.unicast_routes[PFX].nexthops} == {30}

        # +100 exceeds the slack (120 > 100): traffic finally moves
        drain2(100)
        db = check()
        assert nh_names(db.unicast_routes[PFX]) == {"3"}

    def test_soft_drained_node_stays_a_destination(self):
        """Soft drain never isolates: a prefix advertised BY the drained
        node keeps its route (at inflated cost), where a hard drain of
        an intermediate hop can orphan it."""
        ls = square()
        ps = prefix_state_with(("2", "0", PrefixEntry(prefix=PFX)))
        self.readvertise(ls, "2", inc=100)
        db = routes("1", {"0": ls}, ps)
        assert nh_names(db.unicast_routes[PFX]) == {"2"}


class TestMplsLabelSemanticsPersistentPair:
    """Ancestors: SimpleRingTopologyFixture.IpToMplsLabelPrepend
    (DecisionTest.cpp:2228) + the node-label pop cases around
    Decision.cpp:655-745, stepped as prefix-only deltas on ONE
    persistent dual-backend solver pair: prepend-label add / change /
    remove / invalid must each rebuild correctly on warm caches, and
    the label plane's pop semantics (POP_AND_LOOKUP at the label
    owner, PHP at its neighbors, SWAP farther away) must hold at every
    intermediate state."""

    PREPEND = 60001

    @staticmethod
    def _pair():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )

        def check(ls, ps, step):
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, step
            assert h.mpls_routes == d.mpls_routes, step
            return h

        return check

    @staticmethod
    def entry(**kw) -> PrefixEntry:
        return PrefixEntry(
            prefix=PFX,
            forwarding_type=PrefixForwardingType.SR_MPLS,
            **kw,
        )

    def test_prepend_label_lifecycle_on_warm_pair(self):
        # the topology is synced ONCE; each step only edits 4's prefix
        # entry and the PUSH stack must track it exactly
        ls = square()
        ps = prefix_state_with(("4", "0", self.entry()))
        check = self._pair()

        db = check(ls, ps, "baseline")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        for nh in route.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(104,)
            )

        # 1: prepend label appears — it rides FIRST in the push stack
        ps.update_prefix("4", "0", self.entry(prepend_label=self.PREPEND))
        db = check(ls, ps, "add-prepend")
        for nh in db.unicast_routes[PFX].nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(self.PREPEND, 104)
            )

        # 2: prepend label changes value — no topology event, the warm
        # rebuild must not serve the stale stack
        ps.update_prefix(
            "4", "0", self.entry(prepend_label=self.PREPEND + 1)
        )
        db = check(ls, ps, "change-prepend")
        for nh in db.unicast_routes[PFX].nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(self.PREPEND + 1, 104)
            )

        # 3: prepend label goes invalid (> 20-bit) — isMplsLabelValid
        # (DecisionTest.cpp:2343) empties the nexthop set but the entry
        # itself still ships
        ps.update_prefix(
            "4", "0", self.entry(prepend_label=(1 << 20) + 7)
        )
        db = check(ls, ps, "invalid-prepend")
        assert db.unicast_routes[PFX].nexthops == frozenset()

        # 4: prepend label removed — the plain node-label stack returns
        ps.update_prefix("4", "0", self.entry())
        db = check(ls, ps, "remove-prepend")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        for nh in route.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(104,)
            )

    def test_pop_semantics_track_topology_on_warm_pair(self):
        # label plane derives from topology alone: own label pops,
        # neighbor labels PHP, distant labels SWAP — and a topology
        # delta that moves a node from distant to adjacent must flip
        # its action on the warm pair
        ls = square()
        ps = prefix_state_with(("4", "0", self.entry()))
        check = self._pair()

        db = check(ls, ps, "baseline")
        # own label: POP_AND_LOOKUP toward the lookup address
        own = db.mpls_routes[101]
        assert len(own.nexthops) == 1
        (nh,) = own.nexthops
        assert nh.address == "::"
        assert nh.mpls_action == MplsAction(MplsActionCode.POP_AND_LOOKUP)
        # neighbor label: penultimate hop pop, no swap label
        for nh in db.mpls_routes[102].nexthops:
            assert nh.mpls_action == MplsAction(MplsActionCode.PHP)
            assert nh.mpls_action.swap_label is None
        # distant label: SWAP carrying the same label toward both ECMP
        # arms (4 is two hops away on either side of the square)
        far = db.mpls_routes[104]
        assert {nh.neighbor_node_name for nh in far.nexthops} == {"2", "3"}
        for nh in far.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.SWAP, swap_label=104
            )

        # delta: 1 gains a direct adjacency to 4 — label 104 must flip
        # from SWAP (distant) to PHP (adjacent) on the warm pair
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2"), adj("1", "3"), adj("1", "4")],
                node_label=101,
                area="0",
            )
        )
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="4",
                adjacencies=[adj("4", "2"), adj("4", "3"), adj("4", "1")],
                node_label=104,
                area="0",
            )
        )
        db = check(ls, ps, "direct-1-4")
        far = db.mpls_routes[104]
        assert {nh.neighbor_node_name for nh in far.nexthops} == {"4"}
        for nh in far.nexthops:
            assert nh.mpls_action == MplsAction(MplsActionCode.PHP)
            assert nh.mpls_action.swap_label is None


class TestGracefulRestartPersistentPair:
    """Ancestors: DecisionTestFixture's graceful-restart sequences
    (DecisionTest.cpp adj-db withdraw/re-learn around node restarts and
    the prefix re-origination counterparts).  A node restart is three
    distinct link-state phases — withdrawal, a holddown window where the
    *peers'* stale adjacency entries still point at the restarting node
    (the bidirectional check is what holds them out of SPF), and a
    partial-then-complete re-learn — and the route plane must be right,
    on both backends, at every phase, not just after convergence."""

    @staticmethod
    def _pair():
        host = SpfSolver("1")
        device = SpfSolver(
            "1",
            spf_backend=DeviceSpfBackend(
                min_device_nodes=1, min_device_sources=1
            ),
        )

        def check(ls, ps, step):
            h = host.build_route_db({"0": ls}, ps)
            d = device.build_route_db({"0": ls}, ps)
            assert h.unicast_routes == d.unicast_routes, step
            assert h.mpls_routes == d.mpls_routes, step
            return h

        return check

    def test_adjacency_withdraw_and_relearn_with_stale_holddown(self):
        # node 2 restarts while 1 and 4 keep advertising their (now
        # stale) adjacencies toward it the whole time — the holddown.
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        check = self._pair()

        db = check(ls, ps, "baseline")
        assert nh_names(db.unicast_routes[PFX]) == {"2", "3"}
        assert 102 in db.mpls_routes

        # phase 1: restart — 2's own adj db is withdrawn.  1 and 4
        # still hold adj("1","2") / adj("4","2"); those stale entries
        # must not reach SPF, and 2's label must vanish with its db.
        ls.delete_adjacency_database("2")
        db = check(ls, ps, "restart-withdraw")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"3"}
        assert all(nh.metric == 20 for nh in route.nexthops)
        assert 102 not in db.mpls_routes
        assert 104 in db.mpls_routes  # 4 stays reachable via 3

        # phase 2: partial re-learn — 2 comes back speaking only to 1.
        # The stale 4-side holddown entry now has a live partner on one
        # link only: 2 is reachable again (label returns) but traffic
        # to 4 must still go via 3, not through the half-healed 2-4.
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1")],
                node_label=102,
                area="0",
            )
        )
        db = check(ls, ps, "partial-relearn")
        assert nh_names(db.unicast_routes[PFX]) == {"3"}
        near = db.mpls_routes[102]
        assert nh_names(near) == {"2"}

        # phase 3: complete re-learn — 2 republishes its full set and
        # the pre-restart ECMP comes back bit-exact.
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                node_label=102,
                area="0",
            )
        )
        db = check(ls, ps, "complete-relearn")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}
        assert all(nh.metric == 20 for nh in route.nexthops)
        assert 102 in db.mpls_routes and 104 in db.mpls_routes

    def test_prefix_reorigination_after_restart(self):
        # the advertiser itself restarts: its prefix is withdrawn with
        # it, the far advertiser takes over, and after the adjacency
        # plane heals the prefix must be re-originated explicitly —
        # adjacency recovery alone must NOT resurrect it.
        ls = square()
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix=PFX)),
        )
        check = self._pair()

        db = check(ls, ps, "baseline")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2"}  # nearest advertiser wins
        assert all(nh.metric == 10 for nh in route.nexthops)

        # phase 1: 2 restarts — both its adj db and its origination go
        ls.delete_adjacency_database("2")
        ps.delete_prefix("2", "0", PFX)
        db = check(ls, ps, "restart")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"3"}
        assert all(nh.metric == 20 for nh in route.nexthops)

        # phase 2: adjacency plane heals first.  The route must stay on
        # the far advertiser until 2 actually re-originates — no state
        # from the pre-restart origination may leak through the restart.
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name="2",
                adjacencies=[adj("2", "1"), adj("2", "4")],
                node_label=102,
                area="0",
            )
        )
        db = check(ls, ps, "adjacency-healed")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2", "3"}  # ECMP to advertiser 4
        assert all(nh.metric == 20 for nh in route.nexthops)

        # phase 3: re-origination — forwarding collapses back to the
        # recovered nearest advertiser, bit-exact with the baseline.
        ps.update_prefix("2", "0", PrefixEntry(prefix=PFX))
        db = check(ls, ps, "reoriginate")
        route = db.unicast_routes[PFX]
        assert nh_names(route) == {"2"}
        assert all(nh.metric == 10 for nh in route.nexthops)
