"""Flap-storm chaos scenario: the delta rung under a 1k-event storm.

The storm is the end-to-end proof of the incremental delta dataflow:
a seeded, replayable 1k-event flap sequence is coalesced into one
engine dispatch chain per chunk, every chunk must land through the
delta programs (no frontier-overflow fallbacks), the engine must never
restage the full product after the initial upload, and the post-storm
product must be bit-exact against a cold host-oracle rebuild.
"""

import pytest

from openr_tpu.chaos import ChaosEventLog, FlapStormScenario

jax = pytest.importorskip("jax")


@pytest.fixture(scope="module")
def storm():
    log = ChaosEventLog()
    result = FlapStormScenario(seed=7, log_=log).run()
    return result, log


class TestFlapStormScenario:
    def test_every_chunk_lands_through_the_delta_path(self, storm):
        result, _ = storm
        assert result.chunk_modes == ["delta"] * result.chunks
        assert result.delta_updates == result.chunks
        assert result.delta_fallbacks == 0

    def test_post_storm_product_is_bit_exact_vs_host_oracle(self, storm):
        result, _ = storm
        assert result.bit_exact

    def test_initial_upload_is_the_only_full_restage(self, storm):
        result, _ = storm
        assert result.full_restages == 1
        # every chunk costs at most a frontier + relax + rows chain
        assert result.delta_dispatches >= 2 * result.chunks
        assert result.delta_dispatches <= 3 * result.chunks

    def test_storm_coalesces_events_into_chunk_dispatches(self, storm):
        result, _ = storm
        assert result.events == 1000
        assert result.counters["decision.delta.events_coalesced"] > 0
        # 250 events per chunk collapse into one delta rebuild each
        assert result.delta_updates + result.delta_noops == result.chunks

    def test_same_seed_replays_bit_for_bit(self, storm, cpu_burner):
        # the replay runs under the shared CPU burner (tests/conftest.py):
        # a contended box must still produce the exact event log the
        # uncontended original run did — any scheduling dependence in the
        # storm's coalescing or dispatch accounting diverges the streams
        _, log = storm
        relog = ChaosEventLog()
        FlapStormScenario(seed=7, log_=relog).run()
        assert log.matches(relog)

    def test_different_seed_diverges(self, storm):
        _, log = storm
        other = ChaosEventLog()
        FlapStormScenario(seed=8, log_=other).run()
        assert not log.matches(other)
