"""Incremental delta SPF rung (ops/delta.py, decision/delta.py, engine
delta_dispatch): a coalesced batch of LinkState events folds into the
previous device product at frontier-proportional cost, bit-exact against
a fresh cold build in every change direction, with the legacy paths as
the fallback on any gate failure."""

from __future__ import annotations

import numpy as np
import pytest

from openr_tpu.decision.fleet import FleetViewCache, fleet_destinations
from openr_tpu.decision.link_state import LinkState
from openr_tpu.device.engine import (
    DeviceResidencyEngine,
    EpochMismatchError,
)
from openr_tpu.types import AdjacencyDatabase, PrefixEntry
from tests.test_spf_solver import (
    PFX,
    adj,
    build_link_state,
    prefix_state_with,
    square,
)


def ring_ls(n=64, metric=lambda a, b: 20) -> LinkState:
    """64-node ring with +-1/+-2 links, every node labeled — the banded
    warm-path fixture of tests/test_fleet.py (P == 64 >= delta_min_p, so
    the delta rung engages)."""
    def name(i):
        return f"r{i % n:03d}"

    adj_map = {}
    labels = {}
    for i in range(n):
        me = name(i)
        adj_map[me] = [
            adj(me, name(i + d), metric=metric(i, (i + d) % n))
            for d in (1, -1, 2, -2)
        ]
        labels[me] = 1000 + i
    return build_link_state(adj_map, labels=labels)


def set_node(ls, i, metric=lambda a, b: 20, drop=None, is_overloaded=False):
    def name(j):
        return f"r{j % 64:03d}"

    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=name(i),
            adjacencies=[
                adj(name(i), name(i + d), metric=metric(i, (i + d) % 64))
                for d in (1, -1, 2, -2)
                if d != drop
            ],
            is_overloaded=is_overloaded,
            node_label=1000 + i,
            area="0",
        )
    )


def _ps():
    return prefix_state_with(
        ("r063", "0", PrefixEntry(prefix=PFX)),
        ("r000", "0", PrefixEntry(prefix="::2:0/112")),
    )


class TestDeltaPath:
    """Every change direction through FleetViewCache(delta=True): the
    delta rung must label the rebuild warm_mode == "delta" and match a
    fresh cold build bit-for-bit on distances AND bitmap."""

    def _run(self, mutations, **cache_kw):
        counters: dict[str, int] = {}

        def bump(name, delta=1):
            counters[name] = counters.get(name, 0) + delta

        views = []
        for use_delta in (True, False):
            ls = ring_ls()
            ps = _ps()
            dests = fleet_destinations(ls, ps)
            engine = DeviceResidencyEngine()
            cache = FleetViewCache(
                delta=use_delta, bump=bump if use_delta else None, **cache_kw
            )
            if use_delta:
                v1 = cache.view(ls, dests, engine=engine)
                assert not v1.warm
            for m in mutations:
                m(ls)
            views.append(
                (
                    cache.view(ls, fleet_destinations(ls, ps), engine=engine),
                    engine,
                )
            )
        (delta_view, engine), (cold_view, _) = views
        assert not cold_view.warm
        np.testing.assert_array_equal(
            np.asarray(delta_view._dist_dev), np.asarray(cold_view._dist_dev)
        )
        np.testing.assert_array_equal(
            np.asarray(delta_view._bitmap_dev),
            np.asarray(cold_view._bitmap_dev),
        )
        return delta_view, engine, counters

    def test_metric_increase_delta_bit_exact(self):
        view, engine, counters = self._run(
            [lambda ls: set_node(ls, 0, metric=lambda a, b: 90 if b == 1 else 20)]
        )
        assert view.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 1
        assert counters["decision.delta.affected_cols"] > 0
        assert engine.counters["device.engine.delta_dispatches"] >= 2

    def test_metric_decrease_delta_bit_exact(self):
        view, _, counters = self._run(
            [lambda ls: set_node(ls, 0, metric=lambda a, b: 5 if b == 1 else 20)]
        )
        assert view.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 1

    def test_link_down_delta_bit_exact(self):
        # adjacency withdrawal changes the edge SET: exercises the
        # worsened frontier AND the out-slot row re-encode kernel
        view, _, counters = self._run([lambda ls: set_node(ls, 0, drop=1)])
        assert view.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 1

    def test_link_up_delta_bit_exact(self):
        def down(ls):
            set_node(ls, 0, drop=1)

        def up(ls):
            set_node(ls, 0)

        # two cache rounds: down (delta), then back up (delta) — the
        # second is the improvement direction over a changed edge set
        counters: dict[str, int] = {}

        def bump(name, delta=1):
            counters[name] = counters.get(name, 0) + delta

        ls = ring_ls()
        ps = _ps()
        dests = fleet_destinations(ls, ps)
        engine = DeviceResidencyEngine()
        cache = FleetViewCache(delta=True, bump=bump)
        cache.view(ls, dests, engine=engine)
        down(ls)
        v2 = cache.view(ls, dests, engine=engine)
        assert v2.warm_mode == "delta"
        up(ls)
        v3 = cache.view(ls, dests, engine=engine)
        assert v3.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 2
        # flap recovery restores the original product bit-for-bit
        cold = FleetViewCache().view(ring_ls(), dests)
        np.testing.assert_array_equal(
            np.asarray(v3._dist_dev), np.asarray(cold._dist_dev)
        )
        np.testing.assert_array_equal(
            np.asarray(v3._bitmap_dev), np.asarray(cold._bitmap_dev)
        )

    def test_overload_dense_frontier_falls_back_bit_exact(self):
        # draining a symmetric-ring transit node invalidates paths in
        # more than half the columns: the bucket ladder refuses (the full
        # fused product is cheaper) and the legacy worsen path serves —
        # still bit-exact (asserted by _run)
        view, engine, counters = self._run(
            [lambda ls: set_node(ls, 5, is_overloaded=True)]
        )
        assert view.warm_mode == "worsen"
        assert counters["decision.delta.fallbacks"] == 1
        assert (
            engine.counters["device.engine.delta_overflow_fallbacks"] == 1
        )

    def test_overload_of_non_transit_node_is_sparse_delta(self):
        # node 5's links are expensive in both directions, so no tight
        # chain transits it: draining it must flag (at most) its own
        # column — the slot-level worsened mask conservatively marks the
        # tight last-hop into the drained node — and relax just that
        counters: dict[str, int] = {}

        def bump(name, delta=1):
            counters[name] = counters.get(name, 0) + delta

        expensive = lambda a, b: 200 if 5 in (a, b) else 20  # noqa: E731
        ls = ring_ls(metric=expensive)
        dests = fleet_destinations(ls, _ps())
        engine = DeviceResidencyEngine()
        cache = FleetViewCache(delta=True, bump=bump)
        cache.view(ls, dests, engine=engine)
        set_node(ls, 5, metric=expensive, is_overloaded=True)
        v2 = cache.view(ls, dests, engine=engine)
        assert v2.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 1
        assert counters["decision.delta.affected_cols"] <= 4
        ls_cold = ring_ls(metric=expensive)
        set_node(ls_cold, 5, metric=expensive, is_overloaded=True)
        cold = FleetViewCache().view(ls_cold, dests)
        np.testing.assert_array_equal(
            np.asarray(v2._dist_dev), np.asarray(cold._dist_dev)
        )
        np.testing.assert_array_equal(
            np.asarray(v2._bitmap_dev), np.asarray(cold._bitmap_dev)
        )

    def test_worsening_dominated_link_is_certified_noop(self):
        # the r000->r002 chord starts strictly dominated (100 vs 40 via
        # r001), so worsening it further is tight NOWHERE: the frontier
        # certifies empty and the previous product is adopted verbatim
        counters: dict[str, int] = {}

        def bump(name, delta=1):
            counters[name] = counters.get(name, 0) + delta

        dom = lambda w: (  # noqa: E731
            lambda a, b: w if (a, b) == (0, 2) else 20
        )
        ls = ring_ls(metric=dom(100))
        dests = fleet_destinations(ls, _ps())
        engine = DeviceResidencyEngine()
        cache = FleetViewCache(delta=True, bump=bump)
        cache.view(ls, dests, engine=engine)
        set_node(ls, 0, metric=dom(150))
        v2 = cache.view(ls, dests, engine=engine)
        assert v2.warm_mode == "delta"
        assert counters["decision.delta.noop_updates"] == 1
        assert "decision.delta.updates" not in counters
        # the adopted product (inherited verbatim from the previous
        # view) still matches a cold build of the mutated snapshot
        ls_cold = ring_ls(metric=dom(100))
        set_node(ls_cold, 0, metric=dom(150))
        cold = FleetViewCache().view(ls_cold, dests)
        np.testing.assert_array_equal(
            np.asarray(v2._dist_dev), np.asarray(cold._dist_dev)
        )
        np.testing.assert_array_equal(
            np.asarray(v2._bitmap_dev), np.asarray(cold._bitmap_dev)
        )
        # only the frontier program ran: no relax, no row re-encode
        assert engine.counters["device.engine.delta_dispatches"] == 1

    def test_mixed_event_batch_coalesces_to_one_update(self):
        # k pending metric events (two worsens + an improve on nearby
        # nodes) fold into ONE delta update whose events_coalesced
        # counts them all and whose frontier is the union of the three
        view, _, counters = self._run(
            [
                lambda ls: set_node(
                    ls, 0, metric=lambda a, b: 90 if b == 1 else 20
                ),
                lambda ls: set_node(
                    ls, 4, metric=lambda a, b: 5 if b == 5 else 20
                ),
                lambda ls: set_node(
                    ls, 2, metric=lambda a, b: 70 if b == 3 else 20
                ),
            ]
        )
        assert view.warm_mode == "delta"
        assert counters["decision.delta.updates"] == 1
        assert counters["decision.delta.events_coalesced"] >= 3

    def test_parity_gate_clean(self):
        _, _, counters = self._run(
            [lambda ls: set_node(ls, 0, drop=1)], delta_parity=True
        )
        assert counters["decision.delta.parity_checks"] == 1
        assert counters.get("decision.delta.parity_failures", 0) == 0

    def test_min_p_gate_falls_back_to_legacy(self):
        view, engine, counters = self._run(
            [lambda ls: set_node(ls, 0, drop=1)], delta_min_p=1000
        )
        assert view.warm_mode == "worsen"  # legacy path, still bit-exact
        assert "decision.delta.updates" not in counters
        assert engine.counters["device.engine.delta_dispatches"] == 0

    def test_small_topology_stays_on_legacy_paths(self):
        # no banded structure -> eligible() False, zero delta dispatches
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        dests = fleet_destinations(ls, ps)
        engine = DeviceResidencyEngine()
        cache = FleetViewCache(delta=True)
        cache.view(ls, dests, engine=engine)
        set_node_sq = lambda: ls.update_adjacency_database(  # noqa: E731
            AdjacencyDatabase(
                this_node_name="1",
                adjacencies=[adj("1", "2", metric=30), adj("1", "3")],
                node_label=101,
                area="0",
            )
        )
        set_node_sq()
        v2 = cache.view(ls, dests, engine=engine)
        assert v2.warm_mode != "delta"
        assert engine.counters["device.engine.delta_dispatches"] == 0

    def test_no_engine_stays_on_legacy_paths(self):
        ls = ring_ls()
        dests = fleet_destinations(ls, _ps())
        cache = FleetViewCache(delta=True)
        cache.view(ls, dests)
        set_node(ls, 0, drop=1)
        v2 = cache.view(ls, dests)
        assert v2.warm_mode == "worsen"


class TestEngineDeltaRung:
    def test_bucket_ladder(self):
        engine = DeviceResidencyEngine()
        assert engine.delta_bucket(5, 1024) == 8
        assert engine.delta_bucket(9, 1024) == 16
        assert engine.delta_bucket(129, 1024) == 256
        assert (
            engine.counters["device.engine.delta_overflow_fallbacks"] == 0
        )

    def test_bucket_overflow(self):
        engine = DeviceResidencyEngine()
        # more than half the product: the full program is cheaper
        assert engine.delta_bucket(600, 1024) is None
        # bucket would cover the whole product
        assert engine.delta_bucket(40, 64) is None
        # above the ladder entirely
        assert engine.delta_bucket(600, 4096) is None
        assert (
            engine.counters["device.engine.delta_overflow_fallbacks"] == 3
        )

    def test_epoch_refusal(self):
        from types import SimpleNamespace

        engine = DeviceResidencyEngine()
        csr = SimpleNamespace(version=7)
        with pytest.raises(EpochMismatchError):
            engine.delta_dispatch(
                "relax", lambda: None, csr=csr, expect_epoch=6
            )
        assert engine.counters["device.engine.epoch_invalidations"] == 1
        assert engine.counters["device.engine.delta_dispatches"] == 0

    def test_dispatch_and_bucket_accounting(self):
        engine = DeviceResidencyEngine()
        key = ("relax", (64, 256, 64), 16, 1, True, 0, True)
        engine.delta_dispatch("relax", lambda: 1, bucket_key=key)
        engine.delta_dispatch("relax", lambda: 1, bucket_key=key)
        assert engine.counters["device.engine.delta_dispatches"] == 2
        assert engine.counters["device.engine.delta_bucket_misses"] == 1
        assert engine.counters["device.engine.delta_bucket_hits"] == 1

    def test_register_accounts_the_initial_upload(self):
        engine = DeviceResidencyEngine()
        engine.delta_register(4096)
        assert engine.counters["device.engine.full_restages"] == 1
        assert engine.counters["device.engine.bytes_staged"] == 4096

    def test_fault_hook_sees_delta_ops(self):
        seen = []
        engine = DeviceResidencyEngine()
        engine.fault_hook = seen.append
        engine.delta_dispatch("frontier", lambda: None)
        assert seen == ["delta_frontier"]
