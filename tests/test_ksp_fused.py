"""Fused dual-plane KSP2 pipeline (ops/ksp.py): base SPF + on-device
path trace + masked edge-disjoint re-run in one compiled program.

Reference semantics: getKthPaths' repeated SPF with link exclusion
(openr/decision/LinkState.cpp:763-793); parity is asserted against the
host Dijkstra oracle under the device's own exclusions."""

from __future__ import annotations

import numpy as np
import pytest

from benchmarks import cpp_baseline
from benchmarks.synthetic import wan
from openr_tpu.ops.ksp import FusedKsp2Runner, build_in_start
from openr_tpu.ops.protection import build_reverse_edge_ids
from openr_tpu.ops.sssp import INF32


@pytest.fixture(scope="module")
def setup():
    topo = wan(768, seed=11)
    e = topo.n_edges
    rng = np.random.default_rng(17)
    te = topo.edge_metric.copy()
    te[:e] = rng.integers(1, 101, size=e).astype(np.int32)
    dests = rng.choice(
        np.arange(1, topo.n_nodes), size=8, replace=False
    ).astype(np.int32)
    rev = np.asarray(
        build_reverse_edge_ids(topo.edge_src[:e], topo.edge_dst[:e])
    )
    fk = FusedKsp2Runner(
        topo.runner, topo.edge_dst, e, topo.n_nodes, rev, [topo.edge_metric, te]
    )
    res = fk.run(0, dests)
    return topo, te, dests, rev, fk, res


def oracle_dist(topo, metric, up=None):
    e = topo.n_edges
    _, cd = cpp_baseline.spf_all_sources(
        topo.n_nodes,
        topo.edge_src[:e],
        topo.edge_dst[:e],
        metric[:e],
        (up if up is not None else topo.edge_up)[:e],
        topo.node_overloaded[: topo.n_nodes],
        np.zeros(1, np.int32),
        want_dist=True,
    )
    return cd[0]


class TestFusedKsp2:
    def test_verdicts(self, setup):
        _topo, _te, _dests, _rev, _fk, res = setup
        for r in res:
            assert bool(r.ok_base) and bool(r.ok_masked) and bool(r.trace_ok)

    def test_k1_matches_oracle(self, setup):
        topo, te, dests, _rev, _fk, res = setup
        for plane, metric in enumerate((topo.edge_metric, te)):
            cd = oracle_dist(topo, metric)
            np.testing.assert_array_equal(np.asarray(res[plane].k1), cd[dests])

    def test_traced_paths_are_shortest(self, setup):
        topo, te, dests, _rev, _fk, res = setup
        e = topo.n_edges
        for plane, metric in enumerate((topo.edge_metric, te)):
            cd = oracle_dist(topo, metric)
            excl = np.asarray(res[plane].excl)
            for i, d in enumerate(dests):
                ee = excl[i]
                ee = ee[ee < e]
                # traced edges sum to the shortest distance and end at src
                assert metric[ee].sum() == cd[d]

    def test_k2_matches_masked_oracle(self, setup):
        topo, te, dests, rev, _fk, res = setup
        e = topo.n_edges
        for plane, metric in enumerate((topo.edge_metric, te)):
            excl = np.asarray(res[plane].excl)
            k2 = np.asarray(res[plane].k2)
            for i, d in enumerate(dests):
                up = topo.edge_up.copy()
                ee = excl[i]
                ee = ee[ee < e]
                up[ee] = False
                rv = rev[ee]
                up[rv[rv >= 0]] = False
                cd2 = oracle_dist(topo, metric, up=up)
                assert int(k2[i]) == int(cd2[d]), (plane, i)

    def test_k2_at_least_k1(self, setup):
        _topo, _te, _dests, _rev, _fk, res = setup
        for r in res:
            k1 = np.asarray(r.k1)
            k2 = np.asarray(r.k2)
            finite = k2 < int(INF32)
            assert np.all(k2[finite] >= k1[finite])

    def test_non_adaptive_reuses_hints(self, setup):
        topo, te, dests, _rev, fk, res = setup
        h, hm = topo.runner.hint, topo.runner.hint_masked
        res2 = fk.run(0, np.roll(dests, 1), adaptive=False)
        assert topo.runner.hint == h and topo.runner.hint_masked == hm
        for r in res2:
            assert bool(r.ok_base) and bool(r.ok_masked) and bool(r.trace_ok)


class TestInStart:
    def test_in_start_contract(self):
        topo = wan(512, seed=2)
        e = topo.n_edges
        s = build_in_start(topo.edge_dst, e, topo.n_nodes)
        assert s[0] == 0 and s[-1] == e
        # in-edges of v are exactly the run [s[v], s[v+1])
        for v in (0, 17, 200, topo.n_nodes - 1):
            run = np.arange(s[v], s[v + 1])
            assert np.all(topo.edge_dst[run] == v)
