"""Seeded lock-discipline violations: lock-order and guarded-by.

tests/test_race.py asserts exact (rule, line) pairs against this file —
keep line numbers stable when editing.
"""

import threading


class Inverted:
    """A->B in one method and B->A in another: a 2-cycle in the lock graph."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:  # lock-order: cycle with ba() below
                pass

    def ba(self):
        with self._b:
            with self._a:  # lock-order: reverse of ab() above
                pass


class Hierarchical:
    """Consistent A->B everywhere, including the multi-item form: clean."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def nested(self):
        with self._a:
            with self._b:
                pass

    def multi_item(self):
        with self._a, self._b:
            pass


class HalfGuarded:
    """`count` written under `_lock` in bump() but bare in reset()."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # __init__ writes happen-before every other thread

    def bump(self):
        with self._lock:
            self.count += 1

    def reset(self):
        self.count = 0  # guarded-by: bare write

    def reset_quiesced(self):
        # single-threaded maintenance path, every worker already joined
        self.count = -1  # openr: disable=guarded-by


class CondAlias:
    """Condition(self._mu) shares _mu's lock: same node, so taking one
    inside the other is not a graph edge (and no self-cycle)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)

    def signal(self):
        with self._cv:
            self.ready = True

    def also_under_mu(self):
        with self._mu:
            self.ready = False
