"""Seeded ``suppression-unused`` cases (dead-marker detection).

Four declarations:

- a USED marker (real violation on the line): stays silent, finding
  lands in the suppressed list;
- a DEAD marker (clean line): flagged suppression-unused;
- a multi-rule marker where only one rule fires: the idle rule is
  flagged, the firing one is not;
- a marker for a program-* rule: must NOT be flagged by an AST-only run
  (the program family did not execute, so the rule had no chance to
  fire).

Line numbers are asserted exactly by tests/test_analysis.py.
"""


class Module:
    def _bump(self, key, n=1):
        pass

    def run(self):
        # legacy spelling kept for dashboard continuity
        self._bump("BadSpelling")  # openr: disable=counter-name
        self._bump("kvstore.ok")  # openr: disable=counter-name
        self._bump("AlsoBad")  # openr: disable=counter-name,counter-registry
        # openr: disable=program-dtype
        self._bump("fib.converged")
