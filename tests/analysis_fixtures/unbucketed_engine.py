"""Sanctioned dispatch front-end for the jit-unbucketed-dispatch fixture.

Listed under engine_dispatch_paths in the test config: its direct jitted
calls model the device-residency engine and must not be flagged.
"""

from .unbucketed_ops import kernel_add


def engine_dispatch(a, b):
    return kernel_add(a, b)
