"""Kernel layer for the jit-unbucketed-dispatch fixture (in jit_paths).

Defines jitted roots the daemon fixture calls directly; kept free of
other jit-hygiene violations so the rule assertions stay exact.
"""

import functools

import jax


@jax.jit
def kernel_add(a, b):
    return a + b


@functools.partial(jax.jit, static_argnames=("n",))
def kernel_scale(a, n):
    return a * n


def plain_helper(a):
    return a
