"""Seeded thread-discipline violations for the analyzer self-tests.

Parsed only, never imported.  Line numbers are asserted exactly in
tests/test_analysis.py.
"""


class ReplicateQueue:
    def push(self, item):
        return True


class KvStore:
    def __init__(self):
        self.counters = {}
        self.peers = {}


class Daemon:
    def __init__(self):
        self.kvstore = KvStore()
        self.registered_queue = ReplicateQueue()  # registered below: clean
        self.orphan_queue = ReplicateQueue()  # line 23: thread-queue-registration
        self._queues = {
            "registered": self.registered_queue,
        }

    def bad_wiring(self):
        self.kvstore.peers = {}  # line 29: thread-cross-module-write

    def suppressed_wiring(self):
        # pre-start composition wiring  # openr: disable=thread-cross-module-write
        self.kvstore.peers = {}

    def clean_read(self):
        # reads across the seam are allowed
        return dict(self.kvstore.counters)


class LinkMonitor:
    def __init__(self, kvstore):
        self._kvstore_ref = kvstore

    def deep_write(self):
        self._kvstore_ref.peers = {}  # clean: not a recognized module handle


def local_handle_write(link_monitor):
    link_monitor.state = "up"  # line 49: thread-cross-module-write (local name)
