"""Seeded counter-hygiene violations for the analyzer self-tests.

Parsed only, never imported.  The export surface is injected by the test
via counter_extra_prefixes = ["kvstore", "fib", "queue"], standing in for
a parsed OpenrCtrlHandler._all_counters.  Line numbers are asserted
exactly in tests/test_analysis.py.
"""


class Module:
    def __init__(self):
        self.counters = {}

    def _bump(self, counter, n=1):
        self.counters[counter] = self.counters.get(counter, 0) + n

    def good(self):
        self._bump("kvstore.sent_publications")  # clean
        self.counters["fib.loop_runs"] = 1  # clean

    def bad_name(self):
        self._bump("SentPublications")  # line 22: counter-name

    def bad_registry(self):
        self._bump("ghost.module_counter")  # line 25: counter-registry

    def duplicate_a(self):
        self._bump("kvstore.num_updates")  # line 28: counter-duplicate

    def duplicate_b(self):
        self.counters["kvstore.updates"] = 1  # line 31: counter-duplicate

    def suppressed(self):
        self._bump("legacy_flat_counter")  # openr: disable=counter-name
