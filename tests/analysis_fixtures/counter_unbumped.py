"""Seeded ``counter-unbumped`` violations (inverse counter hygiene).

Both seed forms the rule recognizes, each with a bumped (clean) and a
never-bumped (flagged) member, plus a rationale-suppressed seed:

- dict-literal registry: ``self.counters = {"lit": 0, ...}``
- comprehension over a module-level literal tuple (the engine's
  ``ENGINE_COUNTER_KEYS`` pattern)

Line numbers are asserted exactly by tests/test_analysis.py — keep the
layout stable.
"""

MODULE_KEYS = (
    "fib.sync_ok",
    "fib.sync_retries",
)


class Registry:
    def __init__(self):
        self.counters = {
            "kvstore.sent": 0,
            "kvstore.dropped": 0,
            # reserved for the next protocol rev; seeded so dashboards
            # pre-create the series
            "kvstore.reserved": 0,  # openr: disable=counter-unbumped
        }
        self.comp_counters = {k: 0 for k in MODULE_KEYS}

    def _bump(self, key, n=1):
        self.counters[key] = self.counters.get(key, 0) + n

    def run(self):
        self._bump("kvstore.sent")
        self._bump("fib.sync_ok")
