"""Seeded blocking-call-in-eventbase violations for the analyzer
self-tests.

Parsed only, never imported.  Line numbers are asserted exactly in
tests/test_analysis.py.
"""

import time
from time import sleep


class Module:
    def __init__(self, queue, fut, loop):
        self._queue = queue
        self._fut = fut
        self._loop = loop

    # -- positives -----------------------------------------------------------

    async def fiber(self):
        time.sleep(0.1)  # line 21: blocking-call-in-eventbase (fiber task)
        await self._queue.aget()

    def start(self):
        self.run_in_event_base_thread(self._callback)

    def _callback(self):
        return self._fut.result()  # line 28: via run_in_event_base_thread

    def arm(self):
        self.schedule_timeout(1.0, self._on_timer)

    def _on_timer(self):
        self._helper()

    def _helper(self):
        sleep(2)  # line 37: two hops deep from a schedule_timeout callback

    def marshal(self):
        self._loop.call_soon_threadsafe(lambda: self._queue.get())  # line 40

    # -- suppressed ----------------------------------------------------------

    async def known_block(self):
        time.sleep(0)  # startup barrier  # openr: disable=blocking-call-in-eventbase

    # -- clean ---------------------------------------------------------------

    async def awaited_get(self):
        # await suspends the coroutine; the loop keeps running
        return await self._queue.get()

    def _bounded(self):
        self._fut.result(timeout=1.0)
        return self._queue.get(timeout=5)

    def bounded_callback(self):
        self.run_in_event_base_thread(self._bounded)

    def off_loop(self):
        # never marshalled anywhere: blocking on a caller thread is fine
        time.sleep(0.1)
        return self._fut.result()

    def run(self):
        # blocking startup RPC from the CALLER thread (re-entrant inline
        # on the loop thread); must stay clean
        return self.run_in_event_base_thread(self._bounded).result(5.0)

    def shadowed(self):
        self.run_in_event_base_thread(self._alias_user)

    def _alias_user(self):
        from time import monotonic as run

        return run()  # resolves to the import alias, NOT Module.run

    def dict_get(self, d):
        self.run_in_event_base_thread(lambda: d.get("key"))
