"""Runnable seeded-race scenarios for the OPENR_TSAN dynamic detector.

tests/test_race.py loads this by path, registers `State` as a tracked
class, runs each scenario with the detector armed, and asserts on the
drained findings — including the exact source lines of the racing
accesses, located via the ``# RACE-*`` markers so the assertions survive
edits to this file.

Scenarios deliberately avoid incidental synchronization (no Events, no
joins before the racy access): under the armed detector those would
create happens-before edges and hide the seeded race.
"""

import threading
import time

from openr_tpu.runtime.queue import RWQueue


class State:
    """Tracked fixture class: plain attribute storage."""

    def __init__(self):
        self.value = 0


def bare_write_race():
    """Two threads write the same attribute with no synchronization."""
    state = State()

    def writer_a():
        state.value = 1  # RACE-A

    def writer_b():
        state.value = 2  # RACE-B

    a = threading.Thread(target=writer_a, name="race-a")
    b = threading.Thread(target=writer_b, name="race-b")
    a.start()
    b.start()
    a.join()
    b.join()


def bare_read_race():
    """An unsynchronized read against a concurrent write."""
    state = State()
    out = []

    def reader():
        out.append(state.value)  # RACE-READ

    def writer():
        state.value = 7  # RACE-WRITE

    r = threading.Thread(target=reader, name="race-reader")
    w = threading.Thread(target=writer, name="race-writer")
    r.start()
    w.start()
    r.join()
    w.join()


def dedup_double_race():
    """The same two code sites race over two distinct objects: the
    detector dedups by site pair, so this must yield ONE finding."""
    s1, s2 = State(), State()

    def writer(tag):
        for obj in (s1, s2):
            obj.value = tag  # RACE-DEDUP

    a = threading.Thread(target=writer, args=(1,), name="dedup-a")
    b = threading.Thread(target=writer, args=(2,), name="dedup-b")
    a.start()
    b.start()
    a.join()
    b.join()


def queue_handoff_clean():
    """Producer writes, pushes; consumer gets, writes: the put->get edge
    orders the writes.  Must stay silent."""
    state = State()
    q = RWQueue()

    def producer():
        state.value = 1
        q.push("ready")

    def consumer():
        q.get(timeout=10)
        state.value = 2

    p = threading.Thread(target=producer, name="q-producer")
    c = threading.Thread(target=consumer, name="q-consumer")
    p.start()
    c.start()
    p.join()
    c.join()


def two_hop_relay_clean():
    """Transitive HB: origin -> q1 -> relay -> q2 -> sink.  The sink's
    write is ordered after the origin's only through two queue hops."""
    state = State()
    q1 = RWQueue()
    q2 = RWQueue()

    def origin():
        state.value = 1
        q1.push("hop")

    def relay():
        q1.get(timeout=10)
        q2.push("hop")

    def sink():
        q2.get(timeout=10)
        state.value = 2

    threads = [
        threading.Thread(target=fn, name=f"hop-{fn.__name__}")
        for fn in (origin, relay, sink)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def lock_protected_clean():
    """Read-modify-write under one lock from two threads: every pair is
    ordered by release->acquire edges.  Must stay silent."""
    state = State()
    mu = threading.Lock()

    def flip():
        for _ in range(50):
            with mu:
                state.value += 1

    threads = [
        threading.Thread(target=flip, name=f"flip-{i}") for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return state


def token_ordered_clean(det):
    """Explicit publish/acquire tokens order cross-thread writes; the
    token rides a plain list (append is GIL-atomic, no hidden edge)."""
    state = State()
    box = []

    def producer():
        state.value = 1
        box.append(det.publish_token())

    t = threading.Thread(target=producer, name="token-producer")
    t.start()
    while not box:
        time.sleep(0.001)
    det.acquire_token(box[0])
    state.value = 2  # ordered: acquire_token joined the producer's clock
    t.join()


def token_missing_race():
    """Same shape as token_ordered_clean but nobody acquires the token:
    the main-thread write must race the producer's."""
    state = State()
    box = []

    def producer():
        state.value = 1  # RACE-TOKEN-A
        box.append(None)

    t = threading.Thread(target=producer, name="token-producer")
    t.start()
    while not box:
        time.sleep(0.001)
    state.value = 2  # RACE-TOKEN-B
    t.join()
