"""Daemon layer for the jit-unbucketed-dispatch fixture.

Outside jit_paths and engine_dispatch_paths, so every direct jitted call
here is a seeded violation; the plain-helper call and the rationale-
suppressed call stay silent.
"""

import jax

from . import unbucketed_ops as uops
from .unbucketed_ops import kernel_add, plain_helper


def _adhoc_kernel(a):
    return a * 2


_adhoc_jit = jax.jit(_adhoc_kernel)


def handle_query(a, b):
    out = kernel_add(a, b)
    return uops.kernel_scale(out, 2)


def handle_adhoc(a):
    return _adhoc_jit(a)


def handle_host(a):
    return plain_helper(a)


def handle_pinned(a, b):
    # caller pins one shape for the process lifetime; measured faster than
    # engine dispatch and exempt from bucketing by design
    # openr: disable=jit-unbucketed-dispatch
    return kernel_add(a, b)
