"""Seeded thread-shutdown-order violations.

tests/test_race.py asserts exact (rule, line) pairs against this file —
keep line numbers stable when editing.  Names are resolved purely by
shape (AST); the classes referenced here do not need to import.
"""


class BadDaemon:
    """One consumer stops before its queue closes; another's queue is
    never closed at all."""

    def __init__(self):
        self.updates = ReplicateQueue()  # noqa: F821
        self.events = ReplicateQueue()  # noqa: F821
        self._queues = {"updates": self.updates, "events": self.events}
        self.decision = Decision(self.updates.get_reader())  # noqa: F821
        self.fib = Fib(self.events.get_reader())  # noqa: F821

    def stop(self):
        self.decision.stop()  # stops before updates closes (line below)
        self.updates.close()
        self.fib.stop()  # events is never closed in stop()


class GoodDaemon:
    """Close-all loop, then the gather-then-stop idiom: clean."""

    def __init__(self):
        self.updates = ReplicateQueue()  # noqa: F821
        self._queues = {"updates": self.updates}
        self.decision = Decision(self.updates.get_reader())  # noqa: F821

    def stop(self):
        for q in self._queues.values():
            q.close()
        modules = [self.decision]
        for m in modules:
            m.stop()
