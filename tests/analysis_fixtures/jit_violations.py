"""Seeded jit-hygiene violations for the analyzer self-tests.

This file is parsed by openr_tpu.analysis, never imported or executed.
Line numbers are asserted exactly in tests/test_analysis.py — keep edits
append-only or renumber the expectations.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def host_sync_in_trace(x):
    y = jnp.cumsum(x)
    total = float(y[-1])  # line 18: jit-host-sync (float on traced)
    arr = np.asarray(y)  # line 19: jit-host-sync (np.asarray on traced)
    print(y)  # line 20: jit-host-sync (trace-time print)
    y.block_until_ready()  # line 21: jit-host-sync (sync method)
    return total + arr.sum()


@jax.jit
def tracer_branch(x):
    s = jnp.sum(x)
    if s > 0:  # line 28: jit-tracer-branch
        return s
    while s < 0:  # line 30: jit-tracer-branch
        s = s + 1
    return -s


@functools.partial(jax.jit, static_argnames=("flag",))
def static_ok_branch(x, flag):
    # clean: branching on a static arg is concrete at trace time
    if flag:
        return x + 1
    return x - 1


@functools.partial(jax.jit, static_argnames=("missing",))
def bad_static_name(x):  # line 43: jit-static-hygiene (flagged at decorator)
    return x


@functools.partial(jax.jit, static_argnames=("shape",))
def takes_shape(x, shape=[4, 4]):  # line 49: jit-static-hygiene (mutable default)
    return x.reshape(tuple(shape))


def helper_reached_from_jit(v):
    # traced via the call in jitted_caller below
    if v.sum() > 0:  # line 55: jit-tracer-branch (interprocedural)
        return v
    return -v


@jax.jit
def jitted_caller(x):
    return helper_reached_from_jit(x * 2)


@jax.jit
def suppressed_sync(x):
    y = jnp.sum(x)
    return float(y)  # deliberate fixture suppression  # openr: disable=jit-host-sync


def dispatch_layer(x):
    dist = jitted_caller(x)
    if dist[0] > 0:  # line 73: jit-dispatch-sync (branch on device value)
        return int(dist[1])  # line 74: jit-dispatch-sync (int on device value)
    return 0


def dispatch_explicit_fetch(x):
    # clean: single explicit fetch, host branching on host values
    dist = jax.device_get(jitted_caller(x))
    if dist[0] > 0:
        return int(dist[1])
    return 0


def takes_shape_callsite(x):
    return takes_shape(x, shape=[2, 8])  # line 87: jit-static-hygiene (literal)


@jax.jit
def clean_kernel(x, y):
    # clean: is-None checks, shape/dtype reads and lax control flow are fine
    if y is not None:
        x = x + y
    n = x.shape[0]
    if x.dtype == jnp.int32:
        x = x * 2
    return jax.lax.fori_loop(0, n, lambda i, a: a + 1, x)
