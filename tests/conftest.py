"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the real chip).  Env vars must be set before jax
imports anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def cpu_devices():
    import jax

    return jax.devices("cpu")
