"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the real chip).  Env vars must be set before jax
imports anywhere.
"""

import os

# force, don't setdefault: the driver environment exports
# JAX_PLATFORMS=axon (the real-TPU tunnel), which would silently route the
# whole suite through shared TPU hardware — flaky and orders slower
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env vars alone are NOT enough in this environment: the TPU-tunnel
# plugin pre-imports jax at interpreter startup and force-updates
# jax_platforms to "axon,cpu", so JAX_PLATFORMS set here is read too
# late.  Re-assert cpu at the config layer AND pin the default device —
# either alone can leave uncommitted computations landing on the shared
# (sometimes wedged) tunnel.  XLA_FLAGS still applies because the CPU
# client initializes lazily on first use.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax.default_backend() != "cpu":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])

import pytest  # noqa: E402


@pytest.fixture
def cpu_devices():
    import jax

    return jax.devices("cpu")
