"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the real chip).  Env vars must be set before jax
imports anywhere.
"""

import os

# force, don't setdefault: the driver environment exports
# JAX_PLATFORMS=axon (the real-TPU tunnel), which would silently route the
# whole suite through shared TPU hardware — flaky and orders slower
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The TPU-tunnel sitecustomize registers its backend at interpreter start
# and force-updates jax_platforms to "axon,cpu", overriding the env var —
# so backends() would still dial the (shared, sometimes unavailable)
# tunnel.  Re-assert cpu at the config layer too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def cpu_devices():
    import jax

    return jax.devices("cpu")
