"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the real chip).  Env vars must be set before jax
imports anywhere.
"""

import os

# force, don't setdefault: the driver environment exports
# JAX_PLATFORMS=axon (the real-TPU tunnel), which would silently route the
# whole suite through shared TPU hardware — flaky and orders slower
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env vars alone are NOT enough in this environment: the TPU-tunnel
# plugin pre-imports jax at interpreter startup and force-updates
# jax_platforms to "axon,cpu", so JAX_PLATFORMS set here is read too
# late.  Re-assert cpu at the config layer AND pin the default device —
# either alone can leave uncommitted computations landing on the shared
# (sometimes wedged) tunnel.  XLA_FLAGS still applies because the CPU
# client initializes lazily on first use.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax.default_backend() != "cpu":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])

import threading  # noqa: E402

import pytest  # noqa: E402

from openr_tpu.analysis import race as _race  # noqa: E402


def pytest_configure(config):
    # OPENR_TSAN=1 arms the happens-before race detector HERE — before
    # test modules import and construct modules/locks/queues, so every
    # Lock/Condition created for the suite is a proxy and every tracked
    # class carries its access hooks (no-op otherwise; docs/OPERATIONS.md)
    _race.maybe_enable()


@pytest.fixture(autouse=True)
def tsan_guard():
    """Zero-unsuppressed-findings gate for armed (OPENR_TSAN=1) runs.

    Drains stale findings before the test, and fails the test that
    actually produced a race — with both stacks — after it.  Unarmed runs
    pay one `is None` check."""
    det = _race.TSAN
    if det is None:
        yield
        return
    det.drain()
    yield
    findings = det.drain()
    if findings:
        pytest.fail(_race.format_findings(findings), pytrace=False)


@pytest.fixture
def cpu_devices():
    import jax

    return jax.devices("cpu")


class CpuBurner:
    """Background threads spinning pure-Python arithmetic to steal GIL
    slices from the test body.

    On this 1-CPU container the chaos suites only flake when the whole
    suite runs — other tests' threads perturb scheduling enough that a
    convergence wait which merely *polled once* passes standalone and
    races under load.  Burners reproduce that contention deterministically
    in a single test, so hold-based waits (pinned write counters, observed
    quiescence) are exercised rather than lucky instantaneous polls.
    """

    def __init__(self, threads: int = 2) -> None:
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._burn, daemon=True, name=f"burn-{i}")
            for i in range(threads)
        ]

    def _burn(self) -> None:
        x = 1
        while not self._stop.is_set():
            x = (x * 1103515245 + 12345) % (1 << 31)

    def start(self) -> "CpuBurner":
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


@pytest.fixture(scope="module")
def cpu_burner():
    """Shared CPU-contention fixture for the chaos suites (test_ocs,
    test_chaos, test_flapstorm, test_replicafleet).  Module-scoped so
    module- and class-scoped scenario fixtures can run under it."""
    burner = CpuBurner(threads=2).start()
    yield burner
    burner.stop()
