"""Standalone FibService platform agent (reference: openr/platform/
NetlinkFibHandler + LinuxPlatformMain.cpp): wire-level unit tests plus a
real two-process system test — daemon and agent over real sockets, with
`breeze fib validate` auditing them and an agent restart driving the
aliveSince-based full resync (reference: Fib::keepAliveCheck, Fib.h:181)."""

from __future__ import annotations

import contextlib
import io
import socket
import subprocess
import sys
import time

import pytest

from openr_tpu.cli import breeze
from openr_tpu.platform import FibAgentServer, SimulatedRouteTable, TcpFibAgent
from openr_tpu.types import MplsRoute, NextHop, UnicastRoute

CLIENT = 786


def free_port() -> int:
    with socket.socket(socket.AF_INET6, socket.SOCK_STREAM) as s:
        s.bind(("::1", 0))
        return s.getsockname()[1]


def route(dest: str, *nbrs: str) -> UnicastRoute:
    return UnicastRoute(
        dest=dest,
        next_hops=[
            NextHop(address="::1", if_name=f"if-{n}", neighbor_node_name=n)
            for n in nbrs
        ],
    )


class TestAgentWire:
    @pytest.fixture
    def pair(self):
        server = FibAgentServer()
        server.start()
        client = TcpFibAgent(port=server.port)
        yield server, client
        client.close()
        server.stop()

    def test_unicast_roundtrip(self, pair):
        server, client = pair
        client.add_unicast_routes(CLIENT, [route("fc00::/64", "a")])
        client.add_unicast_routes(
            CLIENT, [route("fc00:1::/64", "a", "b")]
        )
        table = client.get_route_table_by_client(CLIENT)
        assert [r.dest for r in table] == ["fc00:1::/64", "fc00::/64"]
        assert len(table[0].next_hops) == 2

        client.delete_unicast_routes(CLIENT, ["fc00::/64"])
        table = client.get_route_table_by_client(CLIENT)
        assert [r.dest for r in table] == ["fc00:1::/64"]

    def test_sync_replaces_table(self, pair):
        server, client = pair
        client.add_unicast_routes(CLIENT, [route("fc00::/64", "a")])
        client.sync_fib(CLIENT, [route("fc00:2::/64", "b")])
        table = client.get_route_table_by_client(CLIENT)
        assert [r.dest for r in table] == ["fc00:2::/64"]

    def test_mpls_roundtrip(self, pair):
        server, client = pair
        client.add_mpls_routes(
            CLIENT,
            [MplsRoute(top_label=100, next_hops=[NextHop(address="::1")])],
        )
        assert [
            r.top_label for r in client.get_mpls_route_table_by_client(CLIENT)
        ] == [100]
        client.delete_mpls_routes(CLIENT, [100])
        assert client.get_mpls_route_table_by_client(CLIENT) == []

    def test_clients_isolated(self, pair):
        server, client = pair
        client.add_unicast_routes(1, [route("fc00::/64", "a")])
        assert client.get_route_table_by_client(2) == []

    def test_alive_since_and_counters(self, pair):
        server, client = pair
        assert client.alive_since() <= int(time.time() * 1000)
        client.add_unicast_routes(CLIENT, [route("fc00::/64", "a")])
        assert client.get_counters()["fibagent.add_unicast"] == 1

    def test_unknown_method_is_error(self, pair):
        server, client = pair
        with pytest.raises(RuntimeError, match="unknown method"):
            client._call("nope", {})

    def test_connection_failure_raises(self):
        client = TcpFibAgent(port=free_port(), timeout_s=0.5)
        with pytest.raises(OSError):
            client.alive_since()


class TestTwoProcessSystem:
    """Daemon + agent as two real processes over real sockets."""

    @pytest.fixture
    def agent_proc(self):
        port = free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "openr_tpu.platform.fib_agent",
             "--port", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        # wait until it accepts connections
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                TcpFibAgent(port=port, timeout_s=0.5).alive_since()
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(0.05)
        else:
            pytest.fail("agent did not come up")
        yield port, proc
        proc.terminate()
        proc.wait(5)

    def test_daemon_programs_real_agent_process(self, agent_proc):
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import LinkEvent, PrefixEntry, PrefixType
        from tests.test_system import make_config, wait_for

        agent_port, proc = agent_proc
        spark_fabric = MockIoProvider()
        ctrl_port = free_port()
        daemons = []
        for i, port in enumerate((ctrl_port, free_port())):
            name = f"pa-{i}"
            cfg = make_config(name, ctrl_port=port)
            if i == 0:
                cfg.fib_agent_port = agent_port  # node 0 uses the real agent
            d = OpenrDaemon(
                cfg,
                io_provider=spark_fabric.endpoint(name),
                spark_v6_addr="::1",
            )
            d.start()
            daemons.append(d)
        spark_fabric.connect("pa-0", "veth0", "pa-1", "veth1")
        daemons[0].netlink_events_queue.push(LinkEvent("veth0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("veth1", 1, True))

        probe = TcpFibAgent(port=agent_port)
        try:
            daemons[1].prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK, [PrefixEntry(prefix="fc03::/64")]
            )
            assert wait_for(
                lambda: any(
                    r.dest == "fc03::/64"
                    for r in probe.get_route_table_by_client(CLIENT)
                ),
                timeout=30,
            ), "route never reached the agent process"

            # breeze fib validate: daemon vs agent must agree
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = breeze.main(
                    ["-p", str(ctrl_port), "fib", "validate",
                     "--agent-port", str(agent_port)]
                )
            assert rc == 0, out.getvalue()
            assert "PASS" in out.getvalue()

            # agent restart: new process, fresh (empty) table + new
            # aliveSince -> daemon's keepalive triggers a full resync
            proc.terminate()
            proc.wait(5)
            probe.close()
            proc2 = subprocess.Popen(
                [sys.executable, "-m", "openr_tpu.platform.fib_agent",
                 "--port", str(agent_port)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            def resynced() -> bool:
                try:
                    table = TcpFibAgent(
                        port=agent_port, timeout_s=0.5
                    ).get_route_table_by_client(CLIENT)
                except OSError:
                    return False
                return any(r.dest == "fc03::/64" for r in table)

            try:
                assert wait_for(
                    resynced, timeout=30
                ), "daemon did not resync after agent restart"
            finally:
                proc2.terminate()
                proc2.wait(5)
        finally:
            probe.close()
            for d in daemons:
                d.stop()
