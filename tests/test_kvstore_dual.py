"""KvStore + DUAL flood-topology integration.

Models the reference's flood-topo scenarios: KvStoreDb extends DualNode
(openr/kvstore/KvStore.h:191) so flooding rides per-root spanning trees
instead of the full peer mesh (getFloodPeers, KvStore.cpp:2813-2834).
These tests run a real multi-store mesh over the in-process transport and
assert (a) SPT formation, (b) fanout reduction vs full-mesh flooding,
(c) fallback to full-mesh when no SPT is valid, and (d) root failover.
"""

from __future__ import annotations

import time

import pytest

from openr_tpu.kvstore.kvstore import (
    InProcessTransport,
    KvStore,
    KvStorePeerState,
)
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.types import PeerSpec, Publication, Value


def v(version=1, originator="node", value=b"x", ttl_ms=-1):
    return Value(
        version=version, originator_id=originator, value=value, ttl_ms=ttl_ms
    )


def spec(addr: str) -> PeerSpec:
    return PeerSpec(peer_addr=addr)


def wait_for(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def fabric():
    fab = InProcessTransport()
    stores = []

    def _make(name, **kw):
        updates: ReplicateQueue[Publication] = ReplicateQueue()
        syncs: ReplicateQueue = ReplicateQueue()
        store = KvStore(
            name,
            updates,
            syncs,
            None,
            transport=fab.bind(name),
            enable_flood_optimization=True,
            **kw,
        )
        fab.register(name, store)
        store.run()
        stores.append((store, updates, syncs))
        return store

    yield fab, _make
    for store, updates, syncs in stores:
        updates.close()
        syncs.close()
        store.stop()
    for store, *_ in stores:
        store.wait_until_stopped(5)


def full_mesh(stores):
    for s in stores:
        s.add_peers(
            "0", {o.node_id: spec(o.node_id) for o in stores if o is not s}
        )


def all_initialized(stores):
    return all(
        s.get_peer_state("0", o.node_id) == KvStorePeerState.INITIALIZED
        for s in stores
        for o in stores
        if o is not s
    )


def spt_converged(stores, root):
    """Every store agrees on the flood root and is PASSIVE on it."""
    for s in stores:
        infos = s.get_flood_topo("0")
        if infos.flood_root_id != root:
            return False
        spt = infos.infos.get(root)
        if spt is None or not spt.passive:
            return False
        if s.node_id != root and spt.parent is None:
            return False
    return True


def flood_pub_total(stores):
    return sum(
        s.get_counters().get("kvstore.thrift.num_flood_pub", 0) for s in stores
    )


class TestDualFloodTopo:
    def test_triangle_spt_formation(self, fabric):
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        c = make("c", is_flood_root=False)
        stores = [a, b, c]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a")), [
            s.get_flood_topo("0") for s in stores
        ]

        # triangle rooted at a: b and c hang off a directly (cost 1 < 2)
        ia, ib, ic = (s.get_flood_topo("0") for s in stores)
        assert sorted(ia.infos["a"].children) == ["b", "c"]
        assert ib.infos["a"].parent == "a"
        assert ic.infos["a"].parent == "a"
        # a floods to both children; b/c flood only towards a
        assert sorted(ia.flood_peers) == ["b", "c"]
        assert ib.flood_peers == ["a"]
        assert ic.flood_peers == ["a"]

    def test_spt_flooding_fanout_reduced(self, fabric):
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        c = make("c", is_flood_root=False)
        stores = [a, b, c]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a"))
        # flood_peers narrow asynchronously after the SPT converges; a
        # publication sent before the mesh fallback retires still fans
        # out full-mesh (cost 4) and races the exact count below
        assert wait_for(
            lambda: sorted(a.get_flood_topo("0").flood_peers) == ["b", "c"]
            and b.get_flood_topo("0").flood_peers == ["a"]
            and c.get_flood_topo("0").flood_peers == ["a"]
        )

        before = flood_pub_total(stores)
        c.set_key_vals("0", {"k": v(originator="c", value=b"fv")})
        assert wait_for(
            lambda: b.get_key_vals("0", ["k"]).key_vals.get("k") is not None
        )
        assert a.get_key_vals("0", ["k"]).key_vals["k"].value == b"fv"
        # SPT path is c -> a -> b: exactly 2 peer sends.  Full-mesh flooding
        # of the same triangle costs 4 (c->{a,b}, a->b, b->a).
        time.sleep(0.2)  # let any stray relays land
        assert flood_pub_total(stores) - before == 2

    def test_full_mesh_fallback_before_spt(self, fabric):
        fab, make = fabric
        # no node is a root -> no SPT ever forms -> full-mesh flooding
        a = make("a", is_flood_root=False)
        b = make("b", is_flood_root=False)
        stores = [a, b]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert a.get_flood_topo("0").flood_root_id is None

        a.set_key_vals("0", {"k": v(originator="a")})
        assert wait_for(
            lambda: b.get_key_vals("0", ["k"]).key_vals.get("k") is not None
        )

    def test_root_failover(self, fabric):
        fab, make = fabric
        # two roots: smallest id wins while alive (DualNode::getSptRootId,
        # Dual.cpp:788-803); survivors fall back to the next root on failure
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=True)
        c = make("c", is_flood_root=False)
        stores = [a, b, c]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a"))

        # a dies: peers notice (LinkMonitor would drive del_peers in prod)
        fab.set_partitioned("a", "b", True)
        fab.set_partitioned("a", "c", True)
        b.del_peers("0", ["a"])
        c.del_peers("0", ["a"])
        assert wait_for(lambda: spt_converged([b, c], "b")), [
            s.get_flood_topo("0") for s in (b, c)
        ]

        b.set_key_vals("0", {"after": v(originator="b")})
        assert wait_for(
            lambda: c.get_key_vals("0", ["after"]).key_vals.get("after")
            is not None
        )

    def test_disabled_store_drops_dual_traffic(self, fabric):
        """A flood-opt-disabled node must reject DUAL messages (reference:
        KvStore.cpp:906-923) instead of half-processing them and wedging
        enabled queriers."""
        from openr_tpu.kvstore.dual import DualMessage, DualMessages, DualMessageType

        fab, make = fabric
        updates: ReplicateQueue[Publication] = ReplicateQueue()
        syncs: ReplicateQueue = ReplicateQueue()
        off = KvStore(
            "off",
            updates,
            syncs,
            None,
            transport=fab.bind("off"),
            enable_flood_optimization=False,
        )
        fab.register("off", off)
        off.run()
        try:
            msgs = DualMessages(
                src_id="x",
                messages=[DualMessage(dst_id="x", distance=0)],
            )
            off.process_dual_messages("0", msgs)
            counters = off.get_counters()
            assert counters.get("kvstore.dual.num_pkt_dropped") == 1
            assert counters.get("kvstore.dual.num_pkt_recv", 0) == 0
            assert off.get_flood_topo("0").infos == {}
        finally:
            updates.close()
            syncs.close()
            off.stop()
            off.wait_until_stopped(5)

    def test_reassert_heals_lost_child_registration(self, fabric):
        """A lost FLOOD_TOPO_SET detaches a node from the flood SPT; the
        periodic re-assert must reconcile it."""
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        stores = [a, b]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a"))
        assert wait_for(
            lambda: a.get_flood_topo("0").infos["a"].children == ["b"]
        )

        # simulate the lost/reordered registration: drop b from a's children
        a._call(lambda: a._db("0").dual.get_dual("a").remove_child("b"))
        assert a.get_flood_topo("0").infos["a"].children == []

        # b's re-assert restores it (driven directly instead of waiting out
        # the 15s timer)
        b._call(lambda: b._db("0").reassert_spt_children())
        assert wait_for(
            lambda: a.get_flood_topo("0").infos["a"].children == ["b"]
        )

    def test_full_sync_delta_not_echoed_to_sender(self, fabric):
        """Keys learned from a full-sync response must not be captured in the
        sender's pending_flood_keys and retransmitted back (sync responses
        carry no node_ids trail, so exclusion needs the explicit sender)."""
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        a.set_key_vals(
            "0", {f"k{i}": v(originator="a", value=b"x") for i in range(5)}
        )
        # b syncs from a: learns 5 keys; a must not receive them back
        b.add_peers("0", {"a": spec("a")})
        a.add_peers("0", {"b": spec("b")})
        assert wait_for(lambda: all_initialized([a, b]))
        assert wait_for(
            lambda: len(b.dump_all("0").key_vals) == 5
        )
        time.sleep(0.3)  # allow any (wrong) echo to land
        counters = a.get_counters()
        # exactly one key-set: a's own local origination.  An echo of the
        # sync delta from b would bump it to 2.
        assert counters.get("kvstore.cmd_key_set", 0) == 1, counters

    def test_mixed_config_peer_still_flooded(self, fabric):
        """A flood-opt-disabled node in an enabled mesh must keep receiving
        floods: it never speaks DUAL, so it is never in any SPT, and without
        the dual_seen fallback it would be silently starved once the
        enabled nodes' SPT became valid."""
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        # c has the optimization off
        updates: ReplicateQueue[Publication] = ReplicateQueue()
        syncs: ReplicateQueue = ReplicateQueue()
        c = KvStore(
            "c",
            updates,
            syncs,
            None,
            transport=fab.bind("c"),
            enable_flood_optimization=False,
        )
        fab.register("c", c)
        c.run()
        try:
            stores = [a, b, c]
            full_mesh(stores)
            assert wait_for(lambda: all_initialized(stores))
            assert wait_for(lambda: spt_converged([a, b], "a"))

            a.set_key_vals("0", {"mixed": v(originator="a")})
            assert wait_for(
                lambda: c.get_key_vals("0", ["mixed"]).key_vals.get("mixed")
                is not None
            ), "disabled peer starved of flood"
            assert b.get_key_vals("0", ["mixed"]).key_vals.get("mixed") is not None
        finally:
            updates.close()
            syncs.close()
            c.stop()
            c.wait_until_stopped(5)

    def test_line_topology_spt_matches_line(self, fabric):
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        c = make("c", is_flood_root=False)
        # line a - b - c: c's SPT parent must be b (cost 2 via b)
        a.add_peers("0", {"b": spec("b")})
        b.add_peers("0", {"a": spec("a"), "c": spec("c")})
        c.add_peers("0", {"b": spec("b")})
        assert wait_for(
            lambda: all(
                s.get_peer_state("0", p) == KvStorePeerState.INITIALIZED
                for s, p in [(a, "b"), (b, "a"), (b, "c"), (c, "b")]
            )
        )
        assert wait_for(lambda: spt_converged([a, b, c], "a"))
        ic = c.get_flood_topo("0")
        assert ic.infos["a"].parent == "b"
        assert ic.infos["a"].cost == 2
        ib = b.get_flood_topo("0")
        assert sorted(ib.flood_peers) == ["a", "c"]


class TestUnreliablePeerBounds:
    def test_dual_backlog_bounded_to_unreachable_peer(self, fabric):
        """An unreachable peer must not accumulate unbounded parked send
        tasks/messages: the DUAL backlog is capped (oldest dropped,
        counted) and topo-sets coalesce to one pending entry per root."""
        from openr_tpu.kvstore.kvstore import DUAL_SEND_BACKLOG_MAX
        from openr_tpu.types import FloodTopoSetParams

        fab, make = fabric
        a = make("a", is_flood_root=True)
        # "ghost" is registered as a peer but has no store behind it, so
        # every transport call raises TransportError and retries park
        a.add_peers("0", {"ghost": spec("ghost")})

        def enqueue_storm():
            db = a._db("0")
            peer = db.peers["ghost"]
            for i in range(DUAL_SEND_BACKLOG_MAX * 3):
                db._dual_to_peer(peer, object())
            for i in range(50):
                db._send_topo_set(
                    peer,
                    FloodTopoSetParams(
                        root_id="a", src_id="a", set_child=bool(i % 2)
                    ),
                )
            return len(peer.outbox), len(peer.pending_topo_set)

        outbox_len, topo_len = a._call(enqueue_storm)
        assert outbox_len <= DUAL_SEND_BACKLOG_MAX
        # 50 alternating sets for one root coalesce to a single entry
        # (possibly + the all-roots clear from add_peers)
        assert topo_len <= 2
        dropped = a.get_counters().get(
            "kvstore.dual.num_pkt_backlog_dropped", 0
        )
        assert dropped >= DUAL_SEND_BACKLOG_MAX

    def test_overflow_to_live_peer_triggers_dual_reconcile(self, fabric):
        """An outbox overflow against a peer that STAYS UP must schedule
        a DUAL state bounce once the backlog drains (advisor r3:
        reconnect-time reconciliation alone never fires for a
        slow-but-alive peer).  The overflow marks the peer; the drainer
        clears the flag and bounces peer_down/peer_up, whose regenerated
        messages deliver over the now-healthy channel."""
        from openr_tpu.kvstore.kvstore import DUAL_SEND_BACKLOG_MAX
        from openr_tpu.types import DualMessages

        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        stores = [a, b]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a"))

        def overflow_storm():
            db = a._db("0")
            peer = db.peers["b"]
            # flood the outbox past the cap with empty (but well-formed)
            # message batches; peer b is alive, so the drainer delivers
            # and then reconciles
            for _ in range(DUAL_SEND_BACKLOG_MAX + 8):
                db._dual_to_peer(peer, DualMessages(src_id="a"))
            return peer.dual_reconcile_needed

        marked = a._call(overflow_storm)
        assert marked, "overflow against a live peer must mark reconcile"

        def reconciled():
            counters = a.get_counters()
            db_peer_flag = a._call(
                lambda: a._db("0").peers["b"].dual_reconcile_needed
            )
            return (
                counters.get("kvstore.dual.num_overflow_reconcile", 0) >= 1
                and not db_peer_flag
            )

        assert wait_for(reconciled), "drainer never ran the DUAL bounce"
        # the mesh must re-converge to a valid SPT after the bounce
        assert wait_for(lambda: spt_converged(stores, "a"))

    def test_anti_entropy_sync_is_silent_in_steady_state(self, fabric):
        """Periodic anti-entropy reconciliation must not re-fire
        KvStoreSyncEvent (downstream initialization signaling) or the
        initial-sync counters (ADVICE r2: kvstore.py:631)."""
        fab, make = fabric
        a = make("a", is_flood_root=True)
        b = make("b", is_flood_root=False)
        stores = [a, b]
        full_mesh(stores)
        assert wait_for(lambda: all_initialized(stores))
        assert wait_for(lambda: spt_converged(stores, "a"))
        sync_reader = b.kvstore_sync_events_queue.get_reader()
        before_full = b.get_counters().get(
            "kvstore.thrift.num_full_sync_success", 0
        )
        # force the periodic anti-entropy tick now
        b._call(lambda: b._db("0").anti_entropy_sync())
        assert wait_for(
            lambda: b.get_counters().get(
                "kvstore.num_anti_entropy_sync_success", 0
            )
            >= 1
        ), b.get_counters()
        # peer is INITIALIZED again...
        assert wait_for(
            lambda: b.get_peer_state("0", "a") == KvStorePeerState.INITIALIZED
        )
        # ...but no new initial-sync signaling fired
        assert sync_reader.size() == 0
        assert (
            b.get_counters().get("kvstore.thrift.num_full_sync_success", 0)
            == before_full
        )
