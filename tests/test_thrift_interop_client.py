"""Stock-Thrift generated-client interop against the fb303 shim.

tests/test_thrift_binary.py drives the shim with the repo's OWN codec —
a useful round trip, but one that would still pass if encoder and
decoder shared a bug.  This file is the other half of the interop
proof: the client side is a vendored slice of the Apache Thrift Python
runtime (TSocket / TFramedTransport / TBinaryProtocol, strict mode)
plus `thrift --gen py`-style generated classes for the OpenrCtrl slice
(reference signatures openr/if/OpenrCtrl.thrift:398-612, field ids
openr/if/Types.thrift:555 Value, :647 KeySetParams, :897 Publication),
and imports NOTHING from openr_tpu — if our shim drifts from the
thrift binary protocol, this client stops parsing it.

The runtime classes are vendored here verbatim in shape (method names,
envelope bytes, framing) so the suite runs even where the `thrift` pip
package is absent; only the server-side fixture touches openr_tpu.
Every test is additionally parametrized over the REAL Apache `thrift`
runtime (TSocket / TFramedTransport / TBinaryProtocol from the pip
package) when it is importable — that leg skips cleanly otherwise — so
an environment that does carry the stock runtime proves the shim
against the canonical implementation, not just our vendored copy.
"""

from __future__ import annotations

import socket
import struct

import pytest

# ---------------------------------------------------------------------------
# Vendored Apache-Thrift-style runtime (client side only, strict binary)
# ---------------------------------------------------------------------------


class TType:
    STOP = 0
    VOID = 1
    BOOL = 2
    BYTE = 3
    DOUBLE = 4
    I16 = 6
    I32 = 8
    I64 = 10
    STRING = 11
    STRUCT = 12
    MAP = 13
    SET = 14
    LIST = 15


class TTransportException(Exception):
    pass


class TApplicationException(Exception):
    UNKNOWN_METHOD = 1

    def __init__(self, type=0, message=None):
        super().__init__(message)
        self.type = type
        self.message = message

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRING:
                self.message = iprot.readString().decode()
            elif fid == 2 and ftype == TType.I32:
                self.type = iprot.readI32()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class TSocket:
    def __init__(self, host, port):
        self.host, self.port = host, port
        self.handle = None

    def open(self):
        self.handle = socket.create_connection(
            (self.host, self.port), timeout=10
        )

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def read(self, sz):
        buff = self.handle.recv(sz)
        if not buff:
            raise TTransportException("TSocket read 0 bytes")
        return buff

    def write(self, buff):
        self.handle.sendall(buff)

    def flush(self):
        pass


class TFramedTransport:
    def __init__(self, trans):
        self.__trans = trans
        self.__wbuf = b""
        self.__rbuf = b""

    def open(self):
        self.__trans.open()

    def close(self):
        self.__trans.close()

    def read(self, sz):
        if not self.__rbuf:
            self.readFrame()
        ret, self.__rbuf = self.__rbuf[:sz], self.__rbuf[sz:]
        return ret

    def readFrame(self):
        head = b""
        while len(head) < 4:
            head += self.__trans.read(4 - len(head))
        (length,) = struct.unpack("!i", head)
        data = b""
        while len(data) < length:
            data += self.__trans.read(length - len(data))
        self.__rbuf = data

    def write(self, buf):
        self.__wbuf += buf

    def flush(self):
        out = struct.pack("!i", len(self.__wbuf)) + self.__wbuf
        self.__wbuf = b""
        self.__trans.write(out)
        self.__trans.flush()


class TBinaryProtocol:
    """Strict-mode thrift binary protocol, write + read halves."""

    VERSION_MASK = -65536  # 0xffff0000
    VERSION_1 = -2147418112  # 0x80010000

    def __init__(self, trans):
        self.trans = trans

    # -- write half --------------------------------------------------------

    def writeMessageBegin(self, name, type, seqid):
        self.writeI32(TBinaryProtocol.VERSION_1 | type)
        self.writeString(name.encode())
        self.writeI32(seqid)

    def writeMessageEnd(self):
        pass

    def writeStructBegin(self, name):
        pass

    def writeStructEnd(self):
        pass

    def writeFieldBegin(self, name, type, id):
        self.writeByte(type)
        self.writeI16(id)

    def writeFieldEnd(self):
        pass

    def writeFieldStop(self):
        self.writeByte(TType.STOP)

    def writeMapBegin(self, ktype, vtype, size):
        self.writeByte(ktype)
        self.writeByte(vtype)
        self.writeI32(size)

    def writeMapEnd(self):
        pass

    def writeListBegin(self, etype, size):
        self.writeByte(etype)
        self.writeI32(size)

    def writeListEnd(self):
        pass

    def writeBool(self, bool_val):
        self.writeByte(1 if bool_val else 0)

    def writeByte(self, byte):
        self.trans.write(struct.pack("!b", byte))

    def writeI16(self, i16):
        self.trans.write(struct.pack("!h", i16))

    def writeI32(self, i32):
        self.trans.write(struct.pack("!i", i32))

    def writeI64(self, i64):
        self.trans.write(struct.pack("!q", i64))

    def writeString(self, s):
        if isinstance(s, str):
            s = s.encode()
        self.writeI32(len(s))
        self.trans.write(s)

    # -- read half ---------------------------------------------------------

    def readMessageBegin(self):
        sz = self.readI32()
        if sz >= 0:
            raise TTransportException("old-style (unstrict) reply")
        version = sz & TBinaryProtocol.VERSION_MASK
        if version != TBinaryProtocol.VERSION_1 & 0xFFFFFFFF and version != (
            TBinaryProtocol.VERSION_1 & TBinaryProtocol.VERSION_MASK
        ):
            raise TTransportException("bad version in readMessageBegin")
        type = sz & 0x000000FF
        name = self.readString().decode()
        seqid = self.readI32()
        return (name, type, seqid)

    def readMessageEnd(self):
        pass

    def readStructBegin(self):
        pass

    def readStructEnd(self):
        pass

    def readFieldBegin(self):
        type = self.readByte()
        if type == TType.STOP:
            return (None, type, 0)
        id = self.readI16()
        return (None, type, id)

    def readFieldEnd(self):
        pass

    def readMapBegin(self):
        ktype = self.readByte()
        vtype = self.readByte()
        size = self.readI32()
        return (ktype, vtype, size)

    def readMapEnd(self):
        pass

    def readListBegin(self):
        etype = self.readByte()
        size = self.readI32()
        return (etype, size)

    def readListEnd(self):
        pass

    def readBool(self):
        return self.readByte() != 0

    def readByte(self):
        return struct.unpack("!b", self._readAll(1))[0]

    def readI16(self):
        return struct.unpack("!h", self._readAll(2))[0]

    def readI32(self):
        return struct.unpack("!i", self._readAll(4))[0]

    def readI64(self):
        return struct.unpack("!q", self._readAll(8))[0]

    def readString(self):
        return self._readAll(self.readI32())

    def _readAll(self, sz):
        buff = b""
        while len(buff) < sz:
            buff += self.trans.read(sz - len(buff))
        return buff

    def skip(self, ttype):
        if ttype == TType.BOOL or ttype == TType.BYTE:
            self.readByte()
        elif ttype == TType.I16:
            self.readI16()
        elif ttype == TType.I32:
            self.readI32()
        elif ttype == TType.I64:
            self.readI64()
        elif ttype == TType.DOUBLE:
            self._readAll(8)
        elif ttype == TType.STRING:
            self.readString()
        elif ttype == TType.STRUCT:
            self.readStructBegin()
            while True:
                _n, ftype, _fid = self.readFieldBegin()
                if ftype == TType.STOP:
                    break
                self.skip(ftype)
                self.readFieldEnd()
            self.readStructEnd()
        elif ttype == TType.MAP:
            ktype, vtype, size = self.readMapBegin()
            for _ in range(size):
                self.skip(ktype)
                self.skip(vtype)
            self.readMapEnd()
        elif ttype == TType.SET or ttype == TType.LIST:
            etype, size = self.readListBegin()
            for _ in range(size):
                self.skip(etype)
            self.readListEnd()
        else:
            raise TTransportException(f"cannot skip type {ttype}")


# ---------------------------------------------------------------------------
# Runtime seam: every test runs over the vendored stack above AND (when
# the pip package is importable) the real Apache `thrift` runtime.
# ---------------------------------------------------------------------------


class _ApacheProtocolAdapter:
    """Byte-level readString/writeString over the real runtime's protocol.

    The Apache Python runtime decodes strings at the protocol layer
    (readString -> str via readBinary); the generated slice in this file
    keeps T_STRING payloads as bytes and decodes at the field site, like
    a binary-typed field.  The adapter pins that convention on top of
    the stock protocol so the SAME generated classes drive both stacks —
    everything below readString/writeString (envelope, framing, varints,
    field headers) is the real runtime's encoding.
    """

    def __init__(self, proto):
        self._proto = proto
        self.trans = proto.trans

    def __getattr__(self, name):
        return getattr(self._proto, name)

    def readString(self):
        return self._proto.readBinary()

    def writeString(self, s):
        if isinstance(s, str):
            s = s.encode()
        self._proto.writeBinary(s)


def make_client_stack(runtime, host, port):
    """(transport, protocol) for the requested client runtime."""
    if runtime == "vendored":
        transport = TFramedTransport(TSocket(host, port))
        return transport, TBinaryProtocol(transport)
    assert runtime == "apache"
    from thrift.protocol import TBinaryProtocol as ApacheBinaryProtocol
    from thrift.transport import TSocket as ApacheSocket
    from thrift.transport import TTransport as ApacheTransport

    sock = ApacheSocket.TSocket(host, port)
    sock.setTimeout(10000)
    transport = ApacheTransport.TFramedTransport(sock)
    protocol = ApacheBinaryProtocol.TBinaryProtocol(transport)
    return transport, _ApacheProtocolAdapter(protocol)


@pytest.fixture(params=["vendored", "apache"])
def client_runtime(request):
    if request.param == "apache":
        pytest.importorskip(
            "thrift",
            reason="real apache thrift pip runtime not installed",
        )
    return request.param


# ---------------------------------------------------------------------------
# `thrift --gen py`-style generated code: the OpenrCtrl kvstore slice
# (openr/if/OpenrCtrl.thrift:398-612; Types.thrift Value/KeySetParams/
# Publication field ids)
# ---------------------------------------------------------------------------

CALL, REPLY, EXCEPTION = 1, 2, 3


class Value_:
    """openr.thrift.Value — ids 1 version, 2 value, 3 originatorId,
    4 ttl, 5 ttlVersion, 6 hash (NOT declaration order)."""

    def __init__(self, version=None, originatorId=None, value=None,
                 ttl=None, ttlVersion=0, hash=None):
        self.version = version
        self.originatorId = originatorId
        self.value = value
        self.ttl = ttl
        self.ttlVersion = ttlVersion
        self.hash = hash

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.I64:
                self.version = iprot.readI64()
            elif fid == 2 and ftype == TType.STRING:
                self.value = iprot.readString()
            elif fid == 3 and ftype == TType.STRING:
                self.originatorId = iprot.readString().decode()
            elif fid == 4 and ftype == TType.I64:
                self.ttl = iprot.readI64()
            elif fid == 5 and ftype == TType.I64:
                self.ttlVersion = iprot.readI64()
            elif fid == 6 and ftype == TType.I64:
                self.hash = iprot.readI64()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()

    def write(self, oprot):
        oprot.writeStructBegin("Value")
        if self.version is not None:
            oprot.writeFieldBegin("version", TType.I64, 1)
            oprot.writeI64(self.version)
            oprot.writeFieldEnd()
        if self.value is not None:
            oprot.writeFieldBegin("value", TType.STRING, 2)
            oprot.writeString(self.value)
            oprot.writeFieldEnd()
        if self.originatorId is not None:
            oprot.writeFieldBegin("originatorId", TType.STRING, 3)
            oprot.writeString(self.originatorId)
            oprot.writeFieldEnd()
        if self.ttl is not None:
            oprot.writeFieldBegin("ttl", TType.I64, 4)
            oprot.writeI64(self.ttl)
            oprot.writeFieldEnd()
        if self.ttlVersion is not None:
            oprot.writeFieldBegin("ttlVersion", TType.I64, 5)
            oprot.writeI64(self.ttlVersion)
            oprot.writeFieldEnd()
        if self.hash is not None:
            oprot.writeFieldBegin("hash", TType.I64, 6)
            oprot.writeI64(self.hash)
            oprot.writeFieldEnd()
        oprot.writeFieldStop()
        oprot.writeStructEnd()


class KeySetParams_:
    """openr.thrift.KeySetParams — 2 keyVals, 3 solicitResponse,
    5 nodeIds, 6 floodRootId, 7 timestamp_ms."""

    def __init__(self, keyVals=None, solicitResponse=True, nodeIds=None,
                 floodRootId=None, timestamp_ms=None):
        self.keyVals = keyVals
        self.solicitResponse = solicitResponse
        self.nodeIds = nodeIds
        self.floodRootId = floodRootId
        self.timestamp_ms = timestamp_ms

    def write(self, oprot):
        oprot.writeStructBegin("KeySetParams")
        if self.keyVals is not None:
            oprot.writeFieldBegin("keyVals", TType.MAP, 2)
            oprot.writeMapBegin(TType.STRING, TType.STRUCT,
                                len(self.keyVals))
            for k, v in self.keyVals.items():
                oprot.writeString(k)
                v.write(oprot)
            oprot.writeMapEnd()
            oprot.writeFieldEnd()
        if self.solicitResponse is not None:
            oprot.writeFieldBegin("solicitResponse", TType.BOOL, 3)
            oprot.writeBool(self.solicitResponse)
            oprot.writeFieldEnd()
        if self.nodeIds is not None:
            oprot.writeFieldBegin("nodeIds", TType.LIST, 5)
            oprot.writeListBegin(TType.STRING, len(self.nodeIds))
            for n in self.nodeIds:
                oprot.writeString(n)
            oprot.writeListEnd()
            oprot.writeFieldEnd()
        oprot.writeFieldStop()
        oprot.writeStructEnd()


class Publication_:
    """openr.thrift.Publication — 2 keyVals, 3 expiredKeys, 4 nodeIds,
    7 area."""

    def __init__(self):
        self.keyVals = {}
        self.expiredKeys = []
        self.nodeIds = None
        self.area = None

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 2 and ftype == TType.MAP:
                _kt, _vt, size = iprot.readMapBegin()
                for _ in range(size):
                    k = iprot.readString().decode()
                    v = Value_()
                    v.read(iprot)
                    self.keyVals[k] = v
                iprot.readMapEnd()
            elif fid == 3 and ftype == TType.LIST:
                _et, size = iprot.readListBegin()
                self.expiredKeys = [
                    iprot.readString().decode() for _ in range(size)
                ]
                iprot.readListEnd()
            elif fid == 4 and ftype == TType.LIST:
                _et, size = iprot.readListBegin()
                self.nodeIds = [
                    iprot.readString().decode() for _ in range(size)
                ]
                iprot.readListEnd()
            elif fid == 7 and ftype == TType.STRING:
                self.area = iprot.readString().decode()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class BinaryAddress_:
    """openr.thrift.BinaryAddress — ids 1 addr, 3 ifName."""

    def __init__(self):
        self.addr = None
        self.ifName = None

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRING:
                self.addr = iprot.readString()
            elif fid == 3 and ftype == TType.STRING:
                self.ifName = iprot.readString().decode()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class IpPrefix_:
    """openr.thrift.IpPrefix — ids 1 prefixAddress, 2 prefixLength."""

    def __init__(self):
        self.prefixAddress = None
        self.prefixLength = None

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRUCT:
                self.prefixAddress = BinaryAddress_()
                self.prefixAddress.read(iprot)
            elif fid == 2 and ftype == TType.I16:
                self.prefixLength = iprot.readI16()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()

    def cidr(self):
        raw = self.prefixAddress.addr
        fam = socket.AF_INET6 if len(raw) == 16 else socket.AF_INET
        return f"{socket.inet_ntop(fam, raw)}/{self.prefixLength}"


class NextHopThrift_:
    """openr.thrift.NextHopThrift — ids 1 address, 2 weight, 51 metric,
    54 neighborNodeName (the fb303/Network.thrift high-id tail)."""

    def __init__(self):
        self.address = None
        self.weight = 0
        self.metric = 0
        self.neighborNodeName = None

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRUCT:
                self.address = BinaryAddress_()
                self.address.read(iprot)
            elif fid == 2 and ftype == TType.I32:
                self.weight = iprot.readI32()
            elif fid == 51 and ftype == TType.I32:
                self.metric = iprot.readI32()
            elif fid == 54 and ftype == TType.STRING:
                self.neighborNodeName = iprot.readString().decode()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class UnicastRoute_:
    """openr.thrift.UnicastRoute — ids 1 dest, 4 nextHops."""

    def __init__(self):
        self.dest = None
        self.nextHops = []

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRUCT:
                self.dest = IpPrefix_()
                self.dest.read(iprot)
            elif fid == 4 and ftype == TType.LIST:
                _et, size = iprot.readListBegin()
                for _ in range(size):
                    nh = NextHopThrift_()
                    nh.read(iprot)
                    self.nextHops.append(nh)
                iprot.readListEnd()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class RouteDatabase_:
    """openr.thrift.RouteDatabase — ids 1 thisNodeName, 4 unicastRoutes,
    5 mplsRoutes (skipped: the dump tests read the unicast half)."""

    def __init__(self):
        self.thisNodeName = None
        self.unicastRoutes = []

    def read(self, iprot):
        iprot.readStructBegin()
        while True:
            _fname, ftype, fid = iprot.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 1 and ftype == TType.STRING:
                self.thisNodeName = iprot.readString().decode()
            elif fid == 4 and ftype == TType.LIST:
                _et, size = iprot.readListBegin()
                for _ in range(size):
                    route = UnicastRoute_()
                    route.read(iprot)
                    self.unicastRoutes.append(route)
                iprot.readListEnd()
            else:
                iprot.skip(ftype)
            iprot.readFieldEnd()
        iprot.readStructEnd()


class OpenrCtrlClient:
    """Generated-client shape: send_*/recv_* pairs over one protocol."""

    def __init__(self, iprot, oprot=None):
        self._iprot = iprot
        self._oprot = oprot or iprot
        self._seqid = 0

    # setKvStoreKeyVals(1: KeySetParams setParams, 2: string area)

    def setKvStoreKeyVals(self, setParams, area):
        self.send_setKvStoreKeyVals(setParams, area)
        self.recv_setKvStoreKeyVals()

    def send_setKvStoreKeyVals(self, setParams, area):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("setKvStoreKeyVals", CALL, self._seqid)
        o.writeStructBegin("setKvStoreKeyVals_args")
        o.writeFieldBegin("setParams", TType.STRUCT, 1)
        setParams.write(o)
        o.writeFieldEnd()
        o.writeFieldBegin("area", TType.STRING, 2)
        o.writeString(area)
        o.writeFieldEnd()
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()

    def recv_setKvStoreKeyVals(self):
        self._recv_void("setKvStoreKeyVals")

    # getKvStoreKeyVals(1: list<string> filterKeys) -> Publication

    def getKvStoreKeyVals(self, filterKeys):
        self.send_getKvStoreKeyVals(filterKeys)
        return self.recv_getKvStoreKeyVals()

    def send_getKvStoreKeyVals(self, filterKeys):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getKvStoreKeyVals", CALL, self._seqid)
        o.writeStructBegin("getKvStoreKeyVals_args")
        o.writeFieldBegin("filterKeys", TType.LIST, 1)
        o.writeListBegin(TType.STRING, len(filterKeys))
        for k in filterKeys:
            o.writeString(k)
        o.writeListEnd()
        o.writeFieldEnd()
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()

    def recv_getKvStoreKeyVals(self):
        i = self._iprot
        _name, mtype, seqid = i.readMessageBegin()
        assert seqid == self._seqid, "seqid mismatch"
        if mtype == EXCEPTION:
            x = TApplicationException()
            x.read(i)
            i.readMessageEnd()
            raise x
        success = None
        i.readStructBegin()
        while True:
            _fname, ftype, fid = i.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 0 and ftype == TType.STRUCT:
                success = Publication_()
                success.read(i)
            else:
                i.skip(ftype)
            i.readFieldEnd()
        i.readStructEnd()
        i.readMessageEnd()
        if success is None:
            raise TApplicationException(
                message="getKvStoreKeyVals failed: unknown result"
            )
        return success

    # getCounters() -> map<string, i64>  (fb303 BaseService.thrift)

    def getCounters(self):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getCounters", CALL, self._seqid)
        o.writeStructBegin("getCounters_args")
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()
        return self._recv_counter_map("getCounters")

    # getRegexCounters(1: string regex) -> map<string, i64>

    def getRegexCounters(self, regex):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getRegexCounters", CALL, self._seqid)
        o.writeStructBegin("getRegexCounters_args")
        o.writeFieldBegin("regex", TType.STRING, 1)
        o.writeString(regex)
        o.writeFieldEnd()
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()
        return self._recv_counter_map("getRegexCounters")

    def _recv_counter_map(self, method):
        i = self._iprot
        _name, mtype, seqid = i.readMessageBegin()
        assert seqid == self._seqid, "seqid mismatch"
        if mtype == EXCEPTION:
            x = TApplicationException()
            x.read(i)
            i.readMessageEnd()
            raise x
        success = None
        i.readStructBegin()
        while True:
            _fname, ftype, fid = i.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 0 and ftype == TType.MAP:
                _kt, _vt, size = i.readMapBegin()
                success = {}
                for _ in range(size):
                    k = i.readString().decode()
                    success[k] = i.readI64()
                i.readMapEnd()
            else:
                i.skip(ftype)
            i.readFieldEnd()
        i.readStructEnd()
        i.readMessageEnd()
        if success is None:
            raise TApplicationException(
                message=f"{method} failed: unknown result"
            )
        return success

    # getRouteDb() -> RouteDatabase   (OpenrCtrl.thrift:298)
    # getRouteDbComputed(1: string nodeName)  (OpenrCtrl.thrift:313)

    def getRouteDb(self):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getRouteDb", CALL, self._seqid)
        o.writeStructBegin("getRouteDb_args")
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()
        return self._recv_route_db("getRouteDb")

    def getRouteDbComputed(self, nodeName):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getRouteDbComputed", CALL, self._seqid)
        o.writeStructBegin("getRouteDbComputed_args")
        o.writeFieldBegin("nodeName", TType.STRING, 1)
        o.writeString(nodeName)
        o.writeFieldEnd()
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()
        return self._recv_route_db("getRouteDbComputed")

    def _recv_route_db(self, method):
        i = self._iprot
        _name, mtype, seqid = i.readMessageBegin()
        assert seqid == self._seqid, "seqid mismatch"
        if mtype == EXCEPTION:
            x = TApplicationException()
            x.read(i)
            i.readMessageEnd()
            raise x
        success = None
        i.readStructBegin()
        while True:
            _fname, ftype, fid = i.readFieldBegin()
            if ftype == TType.STOP:
                break
            if fid == 0 and ftype == TType.STRUCT:
                success = RouteDatabase_()
                success.read(i)
            else:
                i.skip(ftype)
            i.readFieldEnd()
        i.readStructEnd()
        i.readMessageEnd()
        if success is None:
            raise TApplicationException(
                message=f"{method} failed: unknown result"
            )
        return success

    # a method the server does not implement (exception-path probe)

    def getUnsupportedThing(self):
        self._seqid += 1
        o = self._oprot
        o.writeMessageBegin("getUnsupportedThing", CALL, self._seqid)
        o.writeStructBegin("getUnsupportedThing_args")
        o.writeFieldStop()
        o.writeStructEnd()
        o.writeMessageEnd()
        o.trans.flush()
        self._recv_void("getUnsupportedThing")

    def _recv_void(self, name):
        i = self._iprot
        _name, mtype, seqid = i.readMessageBegin()
        assert seqid == self._seqid, "seqid mismatch"
        if mtype == EXCEPTION:
            x = TApplicationException()
            x.read(i)
            i.readMessageEnd()
            raise x
        i.skip(TType.STRUCT)  # empty/void result struct
        i.readMessageEnd()


# ---------------------------------------------------------------------------
# The test: vendored client above, openr_tpu only on the SERVER side
# ---------------------------------------------------------------------------


class TestGeneratedClientInterop:
    @pytest.fixture
    def shim(self):
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from tests.test_system import make_config

        fabric = MockIoProvider()
        daemon = OpenrDaemon(
            make_config("interopd", ctrl_port=0),
            io_provider=fabric.endpoint("interopd"),
            kvstore_transport=InProcessTransport().bind("interopd"),
        )
        daemon.start()
        srv = ThriftBinaryShim(daemon.kvstore, port=0, node_name="interopd")
        srv.run()
        yield daemon, srv
        srv.stop()
        srv.wait_until_stopped(5)
        daemon.stop()

    def _client(self, runtime, port):
        transport, protocol = make_client_stack(runtime, "::1", port)
        transport.open()
        return transport, OpenrCtrlClient(protocol)

    def test_set_then_get_roundtrip(self, shim, client_runtime):
        daemon, srv = shim
        transport, client = self._client(client_runtime, srv.port)
        try:
            client.setKvStoreKeyVals(
                KeySetParams_(
                    keyVals={
                        "interop:gen": Value_(
                            version=7,
                            originatorId="thrift-client",
                            value=b"generated-bytes",
                            ttl=-1,
                        )
                    },
                ),
                "0",
            )
            # server side observed the write through its own store API
            pub = daemon.kvstore.get_key_vals("0", ["interop:gen"])
            assert pub.key_vals["interop:gen"].value == b"generated-bytes"

            # and the generated client parses the Publication reply
            out = client.getKvStoreKeyVals(["interop:gen"])
            got = out.keyVals["interop:gen"]
            assert got.version == 7
            assert got.originatorId == "thrift-client"
            assert got.value == b"generated-bytes"
            assert got.ttl == -1
            assert out.area == "0"
        finally:
            transport.close()

    def test_get_missing_key_is_empty_publication(self, shim, client_runtime):
        _daemon, srv = shim
        transport, client = self._client(client_runtime, srv.port)
        try:
            out = client.getKvStoreKeyVals(["interop:no-such-key"])
            assert out.keyVals == {}
        finally:
            transport.close()

    def test_unknown_method_raises_application_exception(
        self, shim, client_runtime
    ):
        _daemon, srv = shim
        transport, client = self._client(client_runtime, srv.port)
        try:
            with pytest.raises(TApplicationException):
                client.getUnsupportedThing()
        finally:
            transport.close()


# the rewire-family fb303 registry (round-11 tentpole): spelled out
# here rather than imported — this file asserts the WIRE contract, so
# a silent rename in ENGINE_COUNTER_KEYS must fail loudly against the
# names stock monitoring tooling already scrapes
REWIRE_COUNTER_KEYS = (
    "device.engine.rewires",
    "device.engine.rewire_dispatches",
    "device.engine.rewire_slots",
    "device.engine.rewire_rows",
    "device.engine.rewire_bytes_staged",
    "device.engine.rewire_us",
    "device.engine.rewire_fallbacks",
)


class TestGeneratedClientRoutesAndCounters:
    """Route dumps + fb303 getCounters through the SAME vendored
    generated client, against a converged two-daemon pair whose shim is
    wired exactly as production wires it (thrift_shim_port=-1 in the
    daemon config — decision/fib/counters all attached by main.py)."""

    @pytest.fixture(scope="class")
    def pair(self):
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import LinkEvent, PrefixEntry, PrefixType
        from tests.test_system import FIB_CLIENT, make_config, wait_for

        fabric = MockIoProvider()
        kv = InProcessTransport()
        daemons = []
        for name in ("genc-0", "genc-1"):
            cfg = make_config(name, ctrl_port=0)
            if name == "genc-0":
                cfg.thrift_shim_port = -1
            addr = f"fe80::{name}"
            d = OpenrDaemon(
                cfg,
                io_provider=fabric.endpoint(name),
                kvstore_transport=kv.bind(addr),
                spark_v6_addr=addr,
            )
            kv.register(addr, d.kvstore)
            daemons.append(d)
        for d in daemons:
            d.start()
        fabric.connect("genc-0", "veth0", "genc-1", "veth1")
        daemons[0].netlink_events_queue.push(LinkEvent("veth0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("veth1", 1, True))
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc02::/64")]
        )
        assert wait_for(
            lambda: "fc02::/64"
            in daemons[0].fib_agent.unicast.get(FIB_CLIENT, {}),
            timeout=30,
        )
        yield daemons
        for d in daemons:
            d.stop()

    def _client(self, runtime, port):
        transport, protocol = make_client_stack(runtime, "::1", port)
        transport.open()
        return transport, OpenrCtrlClient(protocol)

    def test_route_dump_parses_to_converged_tables(self, pair, client_runtime):
        transport, client = self._client(
            client_runtime, pair[0].thrift_shim.port
        )
        try:
            db = client.getRouteDb()
            assert db.thisNodeName == "genc-0"
            routes = {r.dest.cidr(): r for r in db.unicastRoutes}
            assert "fc02::/64" in routes
            nh = routes["fc02::/64"].nextHops[0]
            assert nh.neighborNodeName == "genc-1"
            # the fixture fabric's spark addr rides BinaryAddress.addr
            assert nh.address.addr == b"fe80::genc-1"
        finally:
            transport.close()

    def test_route_dump_computed_any_node(self, pair, client_runtime):
        transport, client = self._client(
            client_runtime, pair[0].thrift_shim.port
        )
        try:
            db = client.getRouteDbComputed("genc-1")
            assert db.thisNodeName == "genc-1"
            # genc-1 advertises fc02::/64 itself: its own perspective
            # computes, without a route to its own loopback
            assert all(
                r.dest.cidr() != "fc02::/64" for r in db.unicastRoutes
            )
        finally:
            transport.close()

    def test_fb303_counters_include_rewire_family(self, pair, client_runtime):
        transport, client = self._client(
            client_runtime, pair[0].thrift_shim.port
        )
        try:
            counters = client.getCounters()
            missing = [k for k in REWIRE_COUNTER_KEYS if k not in counters]
            assert not missing, missing
            assert all(
                isinstance(counters[k], int) for k in REWIRE_COUNTER_KEYS
            )
            # and the regex surface narrows to exactly that family
            family = client.getRegexCounters(r"device\.engine\.rewire")
            assert set(family) == set(REWIRE_COUNTER_KEYS)
        finally:
            transport.close()
