"""SRLG what-if + TI-LFA kernel tests, verified against the host oracle
(LinkState.run_spf with link exclusions)."""

from __future__ import annotations

import numpy as np
import pytest

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.decision.link_state import LinkState
from openr_tpu.ops.protection import (
    build_reverse_edge_ids,
    srlg_reachability_loss,
    srlg_what_if,
    ti_lfa_backups,
)
from openr_tpu.ops.sssp import INF32
from openr_tpu.utils.topo import grid_topology, random_topology


def build(dbs):
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return ls, CsrTopology.from_link_state(ls)


def to_jnp(csr):
    import jax.numpy as jnp

    return (
        jnp.asarray(csr.edge_src),
        jnp.asarray(csr.edge_dst),
        jnp.asarray(csr.edge_metric),
        jnp.asarray(csr.edge_up),
        jnp.asarray(csr.node_overloaded),
    )


class TestSrlgWhatIf:
    def test_matches_oracle_with_excluded_links(self):
        import jax.numpy as jnp

        ls, csr = build(random_topology(16, 14, seed=3))
        e_src, e_dst, metric, e_up, overloaded = to_jnp(csr)
        sources = jnp.arange(csr.n_nodes, dtype=jnp.int32)

        # scenario f kills directed edges of link f*2 (both directions)
        n_links = csr.n_edges // 2
        scenarios = []
        fail_links = [0, min(3, n_links - 1), min(7, n_links - 1)]
        for link_id in fail_links:
            mask = np.ones(csr.edge_capacity, dtype=bool)
            link, _ = csr.edge_links[2 * link_id]
            for e in range(csr.n_edges):
                if csr.edge_links[e][0] is link:
                    mask[e] = False
            scenarios.append(mask)
        dist = np.asarray(
            srlg_what_if(
                sources, e_src, e_dst, metric, e_up, overloaded,
                jnp.asarray(np.stack(scenarios)),
            )
        )

        for f, link_id in enumerate(fail_links):
            link, _ = csr.edge_links[2 * link_id]
            for s_name in ["n0", "n5", "n11"]:
                oracle = ls.run_spf(s_name, links_to_ignore={link})
                row = dist[f, csr.node_id[s_name]]
                for v in range(csr.n_nodes):
                    name = csr.node_names[v]
                    if name in oracle:
                        assert row[v] == int(oracle[name].metric), (f, s_name, name)
                    else:
                        assert row[v] >= int(INF32)

    def test_reachability_loss_counts(self):
        import jax.numpy as jnp

        ls, csr = build(grid_topology(3))
        e_src, e_dst, metric, e_up, overloaded = to_jnp(csr)
        sources = jnp.arange(csr.n_nodes, dtype=jnp.int32)
        from openr_tpu.ops.sssp import spf_forward

        baseline, _ = spf_forward(sources, e_src, e_dst, metric, e_up, overloaded)

        # scenario: kill nothing vs kill everything
        all_up = np.ones(csr.edge_capacity, dtype=bool)
        all_down = np.zeros(csr.edge_capacity, dtype=bool)
        dist = srlg_what_if(
            sources, e_src, e_dst, metric, e_up, overloaded,
            jnp.asarray(np.stack([all_up, all_down])),
        )
        lost, degraded = srlg_reachability_loss(baseline, dist)
        assert int(lost[0]) == 0 and int(degraded[0]) == 0
        assert int(lost[1]) == 9 * 8  # every (src, other-dst) pair


class TestTiLfa:
    @pytest.mark.parametrize("seed", [0, 4])
    def test_backup_distances_match_oracle(self, seed):
        import jax.numpy as jnp

        ls, csr = build(random_topology(14, 12, seed=seed))
        e_src, e_dst, metric, e_up, overloaded = to_jnp(csr)
        rev = build_reverse_edge_ids(csr.edge_src, csr.edge_dst)

        src_name = "n2"
        src_id = csr.node_id[src_name]
        out_edges = [
            e for e in range(csr.n_edges) if int(csr.edge_src[e]) == src_id
        ]
        max_deg = len(out_edges)
        out_ids = np.full(max_deg, -1, dtype=np.int32)
        out_ids[: len(out_edges)] = out_edges

        dist, dag = ti_lfa_backups(
            jnp.int32(src_id),
            jnp.asarray(out_ids),
            e_src, e_dst, metric, e_up, overloaded,
            rev,
            max_degree=max_deg,
        )
        dist = np.asarray(dist)

        for d, e in enumerate(out_edges):
            link, from_name = csr.edge_links[e]
            oracle = ls.run_spf(src_name, links_to_ignore={link})
            for v in range(csr.n_nodes):
                name = csr.node_names[v]
                if name in oracle:
                    assert dist[d, v] == int(oracle[name].metric), (e, name)
                else:
                    assert dist[d, v] >= int(INF32)

    def test_backup_avoids_failed_first_hop(self):
        """Square: failing 1->2 must leave only the 1->3->4 path to 4."""
        import jax.numpy as jnp

        dbs = grid_topology(2)  # 2x2 grid: node-0-0 .. node-1-1
        ls, csr = build(dbs)
        e_src, e_dst, metric, e_up, overloaded = to_jnp(csr)
        rev = build_reverse_edge_ids(csr.edge_src, csr.edge_dst)
        src_id = csr.node_id["node-0-0"]
        out_edges = [
            e for e in range(csr.n_edges) if int(csr.edge_src[e]) == src_id
        ]
        out_ids = np.asarray(out_edges, dtype=np.int32)
        dist, dag = ti_lfa_backups(
            jnp.int32(src_id), jnp.asarray(out_ids),
            e_src, e_dst, metric, e_up, overloaded, rev,
            max_degree=len(out_edges),
        )
        dag = np.asarray(dag)
        dist = np.asarray(dist)
        dst_id = csr.node_id["node-1-1"]
        for d, e in enumerate(out_edges):
            # failed edge never on the backup DAG; distance via detour = 2
            assert not dag[d, e]
            assert dist[d, dst_id] == 2
