"""Process-level flag surface (reference: openr/common/Flags.cpp +
GflagConfig's flag-over-config precedence)."""

from __future__ import annotations

import json

from openr_tpu.config import OpenrConfig, load_config
from openr_tpu.main import apply_flag_overrides, build_flag_parser


def parse(args):
    return build_flag_parser().parse_args(["--config", "/dev/null", *args])


def base_config() -> OpenrConfig:
    return OpenrConfig(node_name="from-config")


class TestFlagOverrides:
    def test_no_flags_keeps_config(self):
        cfg = base_config()
        apply_flag_overrides(cfg, parse([]))
        assert cfg.node_name == "from-config"
        assert cfg.assume_drained is False
        assert cfg.tls_config is None

    def test_identity_and_port_flags(self):
        cfg = base_config()
        apply_flag_overrides(
            cfg,
            parse(
                ["--node-name", "flagged", "--openr-ctrl-port", "1234",
                 "--fib-agent-port", "60100"]
            ),
        )
        assert cfg.node_name == "flagged"
        assert cfg.openr_ctrl_port == 1234
        assert cfg.fib_agent_port == 60100

    def test_drain_and_feature_flags(self):
        cfg = base_config()
        apply_flag_overrides(
            cfg,
            parse(
                ["--assume-drained", "--dryrun", "--enable-flood-optimization",
                 "--disable-watchdog", "--decision-debounce-min-ms", "1",
                 "--decision-debounce-max-ms", "5"]
            ),
        )
        assert cfg.assume_drained and cfg.dryrun
        assert cfg.enable_watchdog is False
        assert cfg.kvstore_config.enable_flood_optimization
        assert cfg.decision_config.debounce_min_ms == 1
        assert cfg.decision_config.debounce_max_ms == 5

    def test_tls_flags_build_config(self):
        cfg = base_config()
        apply_flag_overrides(
            cfg,
            parse(
                ["--tls-cert-path", "/c", "--tls-key-path", "/k",
                 "--tls-ca-path", "/a", "--tls-acl-regex", "node-.*"]
            ),
        )
        assert cfg.tls_config.cert_path == "/c"
        assert cfg.tls_config.acl_regex == "node-.*"

    def test_config_file_with_tls_section(self, tmp_path):
        path = tmp_path / "conf.json"
        path.write_text(
            json.dumps(
                {
                    "node_name": "n1",
                    "tls_config": {
                        "cert_path": "/c",
                        "key_path": "/k",
                        "ca_path": "/a",
                    },
                }
            )
        )
        cfg = load_config(str(path))
        assert cfg.tls_config.cert_path == "/c"
        assert cfg.tls_config.acl_regex == ".*"
