"""Queue tests (modeled on the reference's
openr/messaging/tests/QueueTest.cpp and ReplicateQueueTest.cpp)."""

import asyncio
import threading
import time

import pytest

from openr_tpu.runtime import (
    QueueClosedError,
    ReplicateQueue,
    RWQueue,
)


def test_fifo_order():
    q = RWQueue()
    for i in range(100):
        assert q.push(i)
    assert q.size() == 100
    assert [q.get() for _ in range(100)] == list(range(100))


def test_try_get():
    q = RWQueue()
    assert q.try_get() is None
    q.push("x")
    assert q.try_get() == "x"
    q.close()
    with pytest.raises(QueueClosedError):
        q.try_get()


def test_blocking_get_across_threads():
    q = RWQueue()
    out = []

    def reader():
        out.append(q.get(timeout=5))

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.05)
    q.push(42)
    t.join(timeout=5)
    assert out == [42]


def test_get_timeout():
    q = RWQueue()
    with pytest.raises(TimeoutError):
        q.get(timeout=0.01)


def test_close_unblocks_getters():
    q = RWQueue()
    errs = []

    def reader():
        try:
            q.get(timeout=5)
        except QueueClosedError as e:
            errs.append(e)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    q.close()
    for t in threads:
        t.join(timeout=5)
    assert len(errs) == 4
    assert not q.push(1)


def test_async_get():
    q = RWQueue()

    async def main():
        async def reader():
            return await q.aget()

        task = asyncio.create_task(reader())
        await asyncio.sleep(0.01)
        # push from another thread while the task is suspended
        threading.Thread(target=lambda: q.push("hello")).start()
        return await asyncio.wait_for(task, timeout=5)

    assert asyncio.run(main()) == "hello"


def test_async_get_closed():
    q = RWQueue()

    async def main():
        async def reader():
            with pytest.raises(QueueClosedError):
                await q.aget()

        task = asyncio.create_task(reader())
        await asyncio.sleep(0.01)
        q.close()
        await asyncio.wait_for(task, timeout=5)

    asyncio.run(main())


def test_mpmc_stress():
    q = RWQueue()
    n_producers, n_consumers, per_producer = 4, 4, 500
    consumed = []
    lock = threading.Lock()

    def producer(pid):
        for i in range(per_producer):
            q.push((pid, i))

    def consumer():
        while True:
            try:
                item = q.get(timeout=5)
            except QueueClosedError:
                return
            with lock:
                consumed.append(item)

    cons = [threading.Thread(target=consumer) for _ in range(n_consumers)]
    prods = [threading.Thread(target=producer, args=(i,)) for i in range(n_producers)]
    for t in cons + prods:
        t.start()
    for t in prods:
        t.join()
    while q.size() > 0:
        time.sleep(0.01)
    q.close()
    for t in cons:
        t.join(timeout=5)
    assert len(consumed) == n_producers * per_producer
    # per-producer order preserved
    for pid in range(n_producers):
        seq = [i for (p, i) in consumed if p == pid]
        assert seq == sorted(seq)


def test_replicate_queue_fanout():
    rq = ReplicateQueue()
    r1 = rq.get_reader()
    rq.push(1)  # only r1 sees this
    r2 = rq.get_reader()
    rq.push(2)
    assert rq.get_num_readers() == 2
    assert rq.get_num_writes() == 2
    assert r1.get(timeout=1) == 1
    assert r1.get(timeout=1) == 2
    assert r2.get(timeout=1) == 2
    rq.close()
    with pytest.raises(QueueClosedError):
        r1.get(timeout=1)
    with pytest.raises(QueueClosedError):
        rq.get_reader()
