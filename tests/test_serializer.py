"""Serializer round-trip + determinism tests."""

from openr_tpu import serializer
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    MplsAction,
    MplsActionCode,
    NextHop,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixMetrics,
    PrefixType,
    Publication,
    UnicastRoute,
    Value,
    adj_key,
    normalize_prefix,
    prefix_key,
)


def _adj_db():
    return AdjacencyDatabase(
        this_node_name="node1",
        adjacencies=[
            Adjacency("node2", "if_1_2", metric=10, adj_label=65001, rtt_us=1500),
            Adjacency("node3", "if_1_3", metric=20, is_overloaded=True),
        ],
        node_label=101,
        area="area1",
    )


def test_roundtrip_adj_db():
    db = _adj_db()
    data = serializer.dumps(db)
    back = serializer.loads(data, AdjacencyDatabase)
    assert back == db
    assert isinstance(back.adjacencies[0], Adjacency)


def test_roundtrip_prefix_db():
    db = PrefixDatabase(
        this_node_name="node1",
        prefix_entries=[
            PrefixEntry(
                prefix="10.0.0.0/24",
                type=PrefixType.LOOPBACK,
                forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                metrics=PrefixMetrics(path_preference=2000),
                tags=("a", "b"),
                min_nexthop=2,
            )
        ],
    )
    back = serializer.loads(serializer.dumps(db), PrefixDatabase)
    assert back == db
    assert back.prefix_entries[0].type is PrefixType.LOOPBACK
    assert back.prefix_entries[0].tags == ("a", "b")


def test_roundtrip_route_with_mpls():
    r = UnicastRoute(
        dest="10.0.0.0/24",
        next_hops=[
            NextHop(
                address="fe80::1",
                if_name="if_1_2",
                metric=10,
                mpls_action=MplsAction(MplsActionCode.PUSH, push_labels=(100, 200)),
            )
        ],
    )
    back = serializer.loads(serializer.dumps(r), UnicastRoute)
    assert back == r
    assert back.next_hops[0].mpls_action.push_labels == (100, 200)


def test_determinism():
    assert serializer.dumps(_adj_db()) == serializer.dumps(_adj_db())


def test_publication_with_values():
    pub = Publication(
        key_vals={
            "adj:node1": Value(3, "node1", serializer.dumps(_adj_db()), ttl_ms=3600000)
        },
        expired_keys=["adj:gone"],
        area="0",
    )
    back = serializer.loads(serializer.dumps(pub), Publication)
    assert back.key_vals["adj:node1"].version == 3
    inner = serializer.loads(back.key_vals["adj:node1"].value, AdjacencyDatabase)
    assert inner == _adj_db()


def test_key_helpers():
    assert adj_key("n1") == "adj:n1"
    assert prefix_key("n1", "10.0.0.1/24", "0") == "prefix:[n1]:[0]:[10.0.0.0/24]"
    assert normalize_prefix("10.0.0.1/24") == "10.0.0.0/24"


def test_pep604_union_fields_round_trip():
    """`X | None` fields (PEP-604 unions carry no __origin__) must decode
    their nested dataclasses, same as typing.Optional[X]."""
    from openr_tpu.decision.rib_policy import (
        RibPolicyConfig,
        RibPolicyStatementConfig,
        RibRouteActionWeight,
    )
    from openr_tpu.serializer import from_wire, to_wire

    cfg = RibPolicyConfig(
        statements=[
            RibPolicyStatementConfig(
                name="s",
                prefixes=["fc00::/64"],
                set_weight=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"0": 2}
                ),
            )
        ],
        ttl_secs=60,
    )
    back = from_wire(to_wire(cfg))
    stmt = back.statements[0]
    assert isinstance(stmt.set_weight, RibRouteActionWeight)
    assert stmt.set_weight.area_to_weight == {"0": 2}
