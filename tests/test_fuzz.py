"""Coverage-guided chaos fuzzer: tier-1 smoke, determinism, shrinker,
and the auto-collected chaos_corpus regression replays.

The smoke is BUDGETED the way bench.py is: a wall budget sheds runs
loudly (`session.shed`) instead of letting a slow box time the whole
suite out — a shed smoke FAILS with a message naming the knob, never
hangs.  The `-m slow` soak logs its seed so any failure replays.
"""

from __future__ import annotations

import glob
import os
import time

import pytest

from openr_tpu.chaos import fuzz as fz

pytestmark = pytest.mark.fuzz

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "chaos_corpus")

# acceptance: >= 25 mutated/crossover timelines in the tier-1 smoke
SMOKE_N = 26
SMOKE_SEED = 20260807
# generous on purpose: ~0.7s/run warm on a 1-CPU box + first-contact
# compiles; the budget exists to shed loudly on a pathological box, not
# to race a healthy one
SMOKE_BUDGET_S = 420.0


def _corpus_entries() -> list:
    return sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def _load(path: str) -> fz.FuzzTimeline:
    with open(path) as fh:
        return fz.FuzzTimeline.loads(fh.read())


class TestFuzzSmoke:
    def test_smoke_oracles_coverage_and_same_seed_replay(self):
        c0 = fz.FUZZ_COUNTERS.get_counters()
        t0 = time.monotonic()
        s1 = fz.fuzz(SMOKE_N, seed=SMOKE_SEED, budget_s=SMOKE_BUDGET_S)
        wall = time.monotonic() - t0
        assert s1.shed == 0, (
            f"fuzz smoke shed {s1.shed}/{SMOKE_N} runs after "
            f"{wall:.0f}s — box too slow for the {SMOKE_BUDGET_S:.0f}s "
            "budget; raise SMOKE_BUDGET_S / OPENR_FUZZ_BUDGET_S"
        )
        assert len(s1.results) == SMOKE_N

        # every timeline composes >= 3 chaos families and every oracle
        # in the bundle holds on every run
        for res in s1.results:
            fams = res.timeline.families()
            assert len(fams) >= 3, (sorted(fams), res.timeline.dumps())
            assert res.ok, (res.failures, res.timeline.dumps())

        # the coverage fingerprint strictly grows over the run:
        # cumulative token count is monotone and the searched part
        # (mutants + crossovers) discovers tokens the seeds didn't
        hist = s1.coverage_history
        assert hist == sorted(hist)
        assert hist[-1] > hist[0]
        assert hist[-1] > hist[2], (
            "mutation/crossover search added no coverage beyond the 3 "
            "seed timelines"
        )

        # novelty + mutation + crossover all actually exercised
        c1 = fz.FUZZ_COUNTERS.get_counters()
        assert c1["chaos.fuzz.runs"] - c0["chaos.fuzz.runs"] == SMOKE_N
        assert c1["chaos.fuzz.mutations"] > c0["chaos.fuzz.mutations"]
        assert c1["chaos.fuzz.crossovers"] > c0["chaos.fuzz.crossovers"]
        assert (
            c1["chaos.fuzz.novel_fingerprints"]
            > c0["chaos.fuzz.novel_fingerprints"]
        )

        # same-seed rerun: identical corpus, identical timelines,
        # identical per-run event logs (ChaosEventLog.matches) and
        # fingerprints — the determinism contract that makes any corpus
        # entry a replayable reproducer
        s2 = fz.fuzz(SMOKE_N, seed=SMOKE_SEED, budget_s=SMOKE_BUDGET_S)
        assert [t.to_json() for t in s1.corpus] == [
            t.to_json() for t in s2.corpus
        ]
        assert len(s2.results) == len(s1.results)
        for a, b in zip(s1.results, s2.results):
            assert a.timeline.to_json() == b.timeline.to_json()
            assert a.log.matches(b.log)
            assert a.fingerprint == b.fingerprint
            assert a.counters == b.counters

    def test_single_timeline_replay_is_deterministic(self):
        t = fz.seed_timeline(5)
        r1 = fz.run_timeline(t)
        r2 = fz.run_timeline(t)
        assert r1.ok and r2.ok, (r1.failures, r2.failures)
        assert r1.log.matches(r2.log)
        assert r1.fingerprint == r2.fingerprint
        assert r1.counters == r2.counters

    def test_corpus_json_round_trips(self):
        t = fz.seed_timeline(9)
        again = fz.FuzzTimeline.loads(t.dumps())
        assert again.to_json() == t.to_json()
        with pytest.raises(ValueError, match="corpus version"):
            fz.FuzzTimeline.from_json({"version": 99, "seed": 0})


class TestShrinker:
    def test_planted_bug_found_and_shrunk_end_to_end(self):
        c0 = fz.FUZZ_COUNTERS.get_counters()
        s = fz.fuzz(6, seed=7, plant=True, stop_on_failure=True)
        assert s.failures, "fuzzer missed the planted kv-ledger bug"
        bad = s.failures[0]
        assert "ledger_kv" in bad.failures

        mini = fz.shrink(bad.timeline, plant=True, oracle="ledger_kv")
        assert len(mini.events) <= 10, mini.dumps()
        assert len(mini.events) < len(bad.timeline.events)
        assert mini.oracle == "ledger_kv"
        c1 = fz.FUZZ_COUNTERS.get_counters()
        assert c1["chaos.fuzz.shrink_steps"] > c0["chaos.fuzz.shrink_steps"]
        assert (
            c1["chaos.fuzz.oracle_failures"] > c0["chaos.fuzz.oracle_failures"]
        )

        # the minimal reproducer reproduces: fails armed, passes unarmed
        armed = fz.run_timeline(mini, plant=True)
        assert not armed.ok and "ledger_kv" in armed.failures
        clean = fz.run_timeline(mini)
        assert clean.ok, clean.failures

    def test_shrink_refuses_a_clean_timeline(self):
        t = fz.FuzzTimeline(
            seed=1, events=[fz.FuzzEvent("engine", "spf", {"off": 0})]
        )
        with pytest.raises(ValueError, match="does not violate"):
            fz.shrink(t)


class TestChaosCorpus:
    """Every checked-in reproducer replays as a tier-1 regression."""

    def test_corpus_directory_is_nonempty(self):
        assert _corpus_entries(), (
            f"no corpus entries under {CORPUS_DIR} — the shrinker's "
            "end-to-end proof entry must stay checked in"
        )

    @pytest.mark.parametrize(
        "path", _corpus_entries(), ids=[os.path.basename(p) for p in _corpus_entries()]
    )
    def test_corpus_entry_replays_clean_unarmed(self, path):
        res = fz.run_timeline(_load(path))
        assert res.ok, (os.path.basename(path), res.failures)

    def test_planted_reproducer_still_fails_armed(self):
        path = os.path.join(CORPUS_DIR, "planted_kv_ledger.json")
        t = _load(path)
        assert t.oracle == "ledger_kv" and len(t.events) <= 10
        res = fz.run_timeline(t, plant=True)
        assert not res.ok and "ledger_kv" in res.failures


class TestFuzzCli:
    def test_cli_fuzz_shrink_and_budget_shed(self, tmp_path, capsys):
        out = tmp_path / "corpus"
        assert fz.main(["--fuzz-n", "2", "--seed", "11", "--out", str(out)]) == 0

        # planted session: finds, shrinks, writes reproducers, rc 1
        # (seed 7's second seed timeline carries a TTL storm, so the
        # planted ledger bug is reachable within two runs)
        rc = fz.main(
            ["--fuzz-n", "2", "--seed", "7", "--plant", "--out", str(out)]
        )
        assert rc == 1
        entries = sorted(out.glob("*.json"))
        assert entries and all("ledger_kv" in e.name for e in entries)

        # --shrink mode writes <entry>.min.json next to the input
        rc = fz.main(["--shrink", str(entries[0]), "--plant"])
        assert rc == 0
        assert (out / (entries[0].name[: -len(".json")] + ".min.json")).exists()

        # an exhausted budget sheds loudly instead of hanging: with a
        # sub-second budget the shed note names the knob on stderr
        capsys.readouterr()
        assert fz.main(["--fuzz-n", "50", "--seed", "11", "--budget-s", "0.01"]) == 0
        err = capsys.readouterr().err
        assert "shedding" in err and "--budget-s" in err


@pytest.mark.slow
class TestFuzzSoak:
    def test_long_fuzz_soak_logs_its_seed(self):
        seed = int(os.environ.get("OPENR_FUZZ_SEED", "0"))
        budget = float(os.environ.get("OPENR_FUZZ_BUDGET_S", "900"))
        print(
            f"chaos.fuzz soak: seed={seed} budget={budget:.0f}s "
            "(reproduce with OPENR_FUZZ_SEED)"
        )
        s = fz.fuzz(200, seed=seed, budget_s=budget)
        for res in s.results:
            assert res.ok, (
                f"seed={seed}",
                res.failures,
                res.timeline.dumps(),
            )
        assert s.coverage_history[-1] >= s.coverage_history[0]
