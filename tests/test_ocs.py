"""OCS reconfiguration chaos family (ISSUE 11).

Three layers:

- OcsController scenario tests: a seeded rolling-rewire schedule over a
  chorded WAN ring, interleaved with metric flaps and one injected
  mid-rewire device fault.  Every round's SPF product — and the
  post-heal all-sources sweep — must be bit-exact against the host
  Dijkstra oracle, bounded rewires must ride the engine's rewire rung
  (full_restages stays at the initial upload except for the scripted
  fault demotion), and a second run from the same seed must produce an
  identical ChaosEventLog.
- Daemon-level rewires: live daemons on the spark fabric with circuits
  connected/retired mid-flight, converging bit-exactly to their own
  host-oracle recompute through hold-based ``wait_converged`` (write
  counters pinned — the 1-CPU full-suite timing-flake pattern).
- A randomized ``-m slow`` soak of the daemon-level loop under a
  CPU-burner load, logging its seed for local replay.
"""

from __future__ import annotations

import os
import random

import pytest

from openr_tpu.chaos import ChaosEventLog, ChaosScenario, OcsController
from openr_tpu.chaos.scenario import fib_unicast_routes, oracle_route_dbs
from openr_tpu.types import LinkEvent

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def ocs_run():
    log = ChaosEventLog()
    result = OcsController(seed=11, log_=log).run()
    return result, log


class TestOcsController:
    def test_rolling_rewires_ride_the_rewire_rung(self, ocs_run):
        result, _ = ocs_run
        # one rewire delta per round, minus the round the injected
        # fault demoted to a restage
        assert result.rewires == result.rounds - 1
        assert result.rewire_dispatches == result.rounds - 1
        assert result.rewire_fallbacks == 1  # the scripted fault
        # initial upload + the fault demotion; nothing else restages
        assert result.full_restages == 2
        assert result.links_swapped == 2 * result.rounds
        assert result.counters["device.engine.rewire_bytes_staged"] > 0

    def test_bit_exact_every_round_and_post_heal(self, ocs_run):
        result, _ = ocs_run
        assert result.bit_exact
        assert all(result.round_exact), result.round_exact

    def test_fault_round_is_in_the_log(self, ocs_run):
        _, log = ocs_run
        events = log.scenario()
        assert any(e.startswith("ocs:fault:armed:") for e in events)
        assert any(e.startswith("ocs:fault:fired:") for e in events)
        assert events[-1] == "ocs:settled:exact"

    def test_same_seed_replays_bit_for_bit(self, ocs_run):
        _, log = ocs_run
        relog = ChaosEventLog()
        OcsController(seed=11, log_=relog).run()
        assert log.matches(relog), (log.scenario(), relog.scenario())

    def test_different_seed_diverges(self, ocs_run):
        _, log = ocs_run
        other = ChaosEventLog()
        OcsController(seed=12, log_=other).run()
        assert not log.matches(other)

    def test_unfaulted_run_keeps_single_restage(self):
        result = OcsController(
            seed=3, n=24, rounds=6, fault_round=-1
        ).run()
        assert result.bit_exact
        assert result.full_restages == 1  # the acceptance invariant
        assert result.rewire_fallbacks == 0
        assert result.rewires == 6


# -- daemon-level rewires -----------------------------------------------------


def _chord_events(ring, a: int, b: int, *, up: bool, if_index: int) -> None:
    """Announce (or retire) the chord interfaces on both endpoints."""
    ring.daemons[a].netlink_events_queue.push(
        LinkEvent(f"if-{a}-{b}", if_index, up)
    )
    ring.daemons[b].netlink_events_queue.push(
        LinkEvent(f"if-{b}-{a}", if_index, up)
    )


def run_daemon_rewires(seed: int, rounds: int = 2):
    """Rolling daemon-level rewires: per round, program a chord circuit
    and retire a ring link, hold-converge, then heal back.  Returns the
    log, the per-wait verdicts and the final (fib, oracle) tables."""
    from test_chaos import ChaosRing

    ring = ChaosRing(4, seed=seed)
    try:
        ring.advertise_loopbacks()
        scenario = ChaosScenario(ring.log)
        ok = scenario.wait("initial-mesh", ring.full_mesh, 45)
        ok &= scenario.wait_converged(ring.daemons, 45)

        for r in range(rounds):
            # program the 0-2 chord circuit (edge-set add)
            scenario.step(
                f"ocs:connect:0-2:{r}",
                lambda: ring.spark_fabric.connect(
                    "openr-0", "if-0-2", "openr-2", "if-2-0"
                ),
            )
            _chord_events(ring, 0, 2, up=True, if_index=7)
            ok &= scenario.wait_converged(ring.daemons, 45)

            # retire the 1-2 ring link (edge-set remove): traffic now
            # rides the programmed chord
            scenario.step(
                f"ocs:retire:1-2:{r}",
                lambda: ring.spark_fabric.disconnect(
                    "openr-1", "if-1-2", "openr-2", "if-2-1"
                ),
            )
            ok &= scenario.wait_converged(ring.daemons, 45)

            # heal: restore the ring link, retire the chord
            scenario.step(
                f"ocs:heal:{r}",
                lambda: ring.spark_fabric.connect(
                    "openr-1", "if-1-2", "openr-2", "if-2-1"
                ),
            )
            scenario.step(
                f"ocs:unprogram:0-2:{r}",
                lambda: ring.spark_fabric.disconnect(
                    "openr-0", "if-0-2", "openr-2", "if-2-0"
                ),
            )
            _chord_events(ring, 0, 2, up=False, if_index=7)
            ok &= scenario.wait_converged(ring.daemons, 45)

        ok &= scenario.wait("post-heal-mesh", ring.full_mesh, 45)
        tables = {
            d.config.node_name: fib_unicast_routes(d) for d in ring.daemons
        }
        oracle = {
            d.config.node_name: oracle_route_dbs(d) for d in ring.daemons
        }
        return ring.log, ok, tables, oracle
    finally:
        ring.stop()


class TestOcsDaemonRewires:
    def test_rolling_circuit_swaps_converge_bit_exact(self):
        log, ok, tables, oracle = run_daemon_rewires(seed=20260805)
        assert ok, log.scenario()
        assert tables == oracle  # bit-exact host-oracle convergence
        assert len(tables) == 4 and all(tables.values())


@pytest.mark.slow
class TestOcsSoak:
    def test_randomized_rewire_soak_under_cpu_burn(self, cpu_burner):
        """The daemon-level rewire loop on a loaded box: the shared CPU
        burners (tests/conftest.py) steal cycles so scenario waits only
        pass through the hold-based convergence gate, never a lucky
        instantaneous poll."""
        seed = int(
            os.environ.get(
                "OPENR_OCS_SEED", random.SystemRandom().randrange(2**31)
            )
        )
        try:
            log, ok, tables, oracle = run_daemon_rewires(seed, rounds=4)
            assert ok, log.scenario()
            assert tables == oracle
            # controller soak rides along under the same load
            result = OcsController(seed=seed, rounds=16).run()
            assert result.bit_exact
            assert result.rewire_fallbacks == 1  # the scripted fault
        except AssertionError as exc:
            raise AssertionError(
                f"ocs soak failed; replay with OPENR_OCS_SEED={seed}: {exc}"
            ) from exc
