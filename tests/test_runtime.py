"""OpenrEventBase / debounce / throttle / backoff / step-detector tests
(modeled on openr/common/tests/UtilTest.cpp + OpenrEventBase usage)."""

import asyncio
import threading
import time

import pytest

from openr_tpu.runtime import (
    AsyncDebounce,
    AsyncThrottle,
    OpenrEventBase,
    RWQueue,
)
from openr_tpu.utils import ExponentialBackoff, StepDetector


def test_eventbase_lifecycle():
    evb = OpenrEventBase("test")
    evb.run()
    assert evb.wait_until_running(2)
    assert evb.is_running
    got = evb.run_in_event_base_thread(lambda: threading.current_thread().name)
    assert got.result(timeout=2) == "test"
    evb.stop()
    assert evb.wait_until_stopped(2)
    assert not evb.is_running


def test_eventbase_fiber_task_queue_read():
    evb = OpenrEventBase("reader")
    q = RWQueue()
    seen = []
    done = threading.Event()

    async def reader():
        while True:
            item = await q.aget()
            seen.append(item)
            if len(seen) == 3:
                done.set()

    evb.run()
    evb.add_fiber_task(reader())
    for i in range(3):
        q.push(i)
    assert done.wait(5)
    assert seen == [0, 1, 2]
    evb.stop()


def test_eventbase_timestamp_advances():
    evb = OpenrEventBase("hb")
    evb.run()
    t0 = evb.get_timestamp()
    time.sleep(0.3)
    assert evb.get_timestamp() > t0
    evb.stop()


def test_debounce_coalesces():
    fires = []

    async def main():
        deb = AsyncDebounce(0.02, 0.1, lambda: fires.append(time.monotonic()))
        t0 = time.monotonic()
        for _ in range(5):
            deb()
            await asyncio.sleep(0.005)
        await asyncio.sleep(0.3)
        return t0

    t0 = asyncio.run(main())
    assert len(fires) == 1
    # fired no earlier than min, no later than max (+slack)
    assert 0.015 <= fires[0] - t0 <= 0.2


def test_debounce_max_bound():
    """A continuous stream of invocations must still fire by backoff_max."""
    fires = []

    async def main():
        deb = AsyncDebounce(0.01, 0.05, lambda: fires.append(time.monotonic()))
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.2:
            deb()
            await asyncio.sleep(0.002)
        await asyncio.sleep(0.1)

    asyncio.run(main())
    assert len(fires) >= 2  # kept firing despite constant invocation


def test_throttle():
    fires = []

    async def main():
        thr = AsyncThrottle(0.02, lambda: fires.append(1))
        for _ in range(10):
            thr()
        await asyncio.sleep(0.05)
        thr()
        await asyncio.sleep(0.05)

    asyncio.run(main())
    assert len(fires) == 2


def test_exponential_backoff():
    now = [0.0]
    bo = ExponentialBackoff(1.0, 8.0, clock=lambda: now[0])
    assert bo.can_try_now()
    bo.report_error()
    assert not bo.can_try_now()
    assert bo.get_current_backoff() == 1.0
    bo.report_error()
    assert bo.get_current_backoff() == 2.0
    for _ in range(5):
        bo.report_error()
    assert bo.get_current_backoff() == 8.0
    assert bo.at_max_backoff()
    now[0] += 8.0
    assert bo.can_try_now()
    # success resets unconditionally (reference ExponentialBackoff.cpp:41-45)
    bo.report_success()
    assert bo.get_current_backoff() == 0.0
    assert bo.can_try_now()
    bo.report_error()
    assert bo.get_current_backoff() == 1.0


def test_exponential_backoff_abort_at_max():
    from openr_tpu.utils.backoff import MaxBackoffAbortError

    now = [0.0]
    bo = ExponentialBackoff(1.0, 2.0, is_abort_at_max=True, clock=lambda: now[0])
    bo.report_error()
    bo.report_error()
    assert bo.at_max_backoff()
    with pytest.raises(MaxBackoffAbortError):
        bo.report_error()


def test_eventbase_stop_from_own_loop():
    """stop() called from the module's own loop must not deadlock."""
    evb = OpenrEventBase("selfstop")
    evb.run()
    evb.add_fiber_task(_self_stop(evb))
    assert evb.wait_until_stopped(5)


async def _self_stop(evb):
    evb.stop()


def test_step_detector():
    sd = StepDetector(
        fast_window_size=4,
        slow_window_size=16,
        lower_threshold_pct=0.4,
        upper_threshold_pct=0.6,
        abs_threshold=100.0,
    )
    steps = []
    # stable around 1000us
    for v in [1000, 1010, 990, 1000, 1005, 995, 1000]:
        if sd.add_value(v):
            steps.append(v)
    assert steps == []
    assert sd.baseline is not None
    # jitter below threshold
    for v in [1050, 1040, 1060, 1050]:
        sd.add_value(v)
    assert sd.baseline == pytest.approx(1000, rel=0.02)
    # genuine step to ~2000us
    detected = False
    for v in [2000, 2010, 1990, 2000, 2005, 1995, 2000, 2000]:
        detected = sd.add_value(v) or detected
    assert detected
    assert sd.baseline == pytest.approx(2000, rel=0.05)
