"""ReplicaRouter unit tests over hand-controlled fake replicas.

Every test drives the router through the same handle protocol real
replicas use (submit/epoch/get_counters) with failure modes flipped by
hand, so the routing decisions — round-robin spread, epoch pinning,
failover, hedging, loud sheds — are asserted without any engine in the
loop.  The fleet-with-real-schedulers path lives in
tests/test_replicafleet.py.
"""

from __future__ import annotations

import concurrent.futures
import time

import pytest

from openr_tpu.device.engine import EpochMismatchError
from openr_tpu.serving import (
    QueryResult,
    QueryShedError,
    ReplicaRouter,
    ReplicaUnavailableError,
    ROUTER_COUNTER_KEYS,
)


class FakeReplica:
    """Handle whose behavior is a mode flag: ok | shed | unavailable |
    sync_raise | hold (futures parked for manual resolution)."""

    def __init__(self, name: str, epoch: int = 1, mode: str = "ok") -> None:
        self.name = name
        self.epoch_value = epoch
        self.mode = mode
        self.submits: list = []
        self.held: list = []

    def submit(self, op: str, **kw) -> "concurrent.futures.Future":
        if self.mode == "sync_raise":
            raise ReplicaUnavailableError(f"{self.name} down hard")
        self.submits.append((op, kw))
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        if self.mode == "ok":
            fut.set_result(self._result())
        elif self.mode == "shed":
            fut.set_exception(QueryShedError(f"{self.name} overloaded"))
        elif self.mode == "unavailable":
            fut.set_exception(ReplicaUnavailableError(f"{self.name} down"))
        else:  # hold
            self.held.append(fut)
        return fut

    def _result(self) -> QueryResult:
        return QueryResult(
            value={"from": self.name},
            latency_us=1,
            batch_size=1,
            epoch=self.epoch_value,
        )

    def release(self) -> None:
        for fut in self.held:
            if not fut.done():
                fut.set_result(self._result())
        self.held = []

    def epoch(self, area: str = "0") -> int:
        if self.mode in ("unavailable", "sync_raise"):
            raise ReplicaUnavailableError(f"{self.name} down")
        return self.epoch_value

    def get_counters(self) -> dict:
        return {"serving.admitted": 1, "serving.p99_us": 100}


def make_router(reps, **kw):
    kw.setdefault("hedge_after_s", None)  # hedging off unless the test asks
    router = ReplicaRouter(reps, **kw)
    return router


def ledger_redispatches(c: dict) -> int:
    return (
        c["serving.router.retries"]
        + c["serving.router.hedges"]
        + c["serving.router.failovers"]
        + c["serving.router.epoch_reroutes"]
    )


def assert_ledger(router: ReplicaRouter, submitted: int) -> None:
    c = router.get_counters()
    assert c["serving.router.dispatches"] == (
        submitted - c["serving.router.sheds"]
    ) + ledger_redispatches(c), c


class TestDispatchSpread:
    def test_round_robin_across_replicas(self):
        reps = [FakeReplica(f"r{i}") for i in range(3)]
        router = make_router(reps)
        for _ in range(6):
            assert router.submit("paths", sources=("a",)).result(5)
        assert [len(r.submits) for r in reps] == [2, 2, 2]
        c = router.get_counters()
        assert c["serving.router.dispatches"] == 6
        assert_ledger(router, 6)
        router.stop()

    def test_counter_rollup_sums_replicas_and_maxes_gauges(self):
        reps = [FakeReplica("a"), FakeReplica("b")]
        router = make_router(reps)
        c = router.get_counters()
        assert c["serving.admitted"] == 2  # summed across replicas
        assert c["serving.p99_us"] == 100  # gauge: max, not sum
        for key in ROUTER_COUNTER_KEYS:
            assert key in c  # pre-seeded: dumpable before first bump
        router.stop()

    def test_all_router_keys_preseeded_at_zero(self):
        router = make_router([FakeReplica("a")])
        assert set(ROUTER_COUNTER_KEYS) <= set(router.counters)
        assert all(router.counters[k] == 0 for k in ROUTER_COUNTER_KEYS)
        router.stop()


class TestFailover:
    def test_async_unavailable_fails_over_and_marks_death(self):
        down, up = FakeReplica("down", mode="unavailable"), FakeReplica("up")
        router = make_router([down, up])
        res = router.submit("paths", sources=("a",)).result(5)
        assert res.value["from"] == "up"
        c = router.get_counters()
        assert c["serving.router.failovers"] == 1
        assert c["serving.router.replica_deaths"] == 1
        assert c["serving.router.dispatches"] == 2
        assert router.alive_replicas() == 1
        assert_ledger(router, 1)
        router.stop()

    def test_sync_refusal_is_not_a_ledger_dispatch(self):
        hard, up = FakeReplica("hard", mode="sync_raise"), FakeReplica("up")
        router = make_router([hard, up])
        res = router.submit("paths", sources=("a",)).result(5)
        assert res.value["from"] == "up"
        c = router.get_counters()
        # the refusing replica never received a dispatch: death recorded,
        # ledger untouched
        assert c["serving.router.dispatches"] == 1
        assert c["serving.router.failovers"] == 0
        assert c["serving.router.replica_deaths"] == 1
        assert_ledger(router, 1)
        router.stop()

    def test_probe_revives_a_healed_replica(self):
        rep = FakeReplica("r", mode="unavailable")
        router = make_router([rep], initial_backoff_s=0.005)
        assert router.probe_replicas() == 0
        c = router.get_counters()
        assert c["serving.router.probe_failures"] >= 1
        assert c["serving.router.replica_deaths"] == 1
        rep.mode = "ok"
        time.sleep(0.02)  # let the backoff window expire
        assert router.probe_replicas() == 1
        assert router.alive_replicas() == 1
        router.stop()


class TestRetriesAndSheds:
    def test_replica_shed_retries_on_another(self):
        shedding, up = FakeReplica("shedding", mode="shed"), FakeReplica("up")
        router = make_router([shedding, up])
        res = router.submit("paths", sources=("a",)).result(5)
        assert res.value["from"] == "up"
        c = router.get_counters()
        assert c["serving.router.retries"] == 1
        assert c["serving.router.failovers"] == 0  # overload, not death
        assert router.alive_replicas() == 2
        assert_ledger(router, 1)
        router.stop()

    def test_fleetwide_shed_propagates_loudly(self):
        reps = [FakeReplica(f"r{i}", mode="shed") for i in range(2)]
        router = make_router(reps)
        fut = router.submit("paths", sources=("a",))
        with pytest.raises(QueryShedError):
            fut.result(5)
        # dispatched at least once, so this is the replicas' shed, not
        # the router's own admission shed
        c = router.get_counters()
        assert c["serving.router.sheds"] == 0
        assert c["serving.router.dispatches"] >= 1
        assert_ledger(router, 1)
        router.stop()

    def test_no_replicas_sheds_at_admission(self):
        router = make_router([])
        fut = router.submit("paths", sources=("a",))
        with pytest.raises(QueryShedError):
            fut.result(5)
        c = router.get_counters()
        assert c["serving.router.sheds"] == 1
        assert c["serving.router.dispatches"] == 0
        assert_ledger(router, 1)
        router.stop()

    def test_stopped_router_sheds_at_admission(self):
        router = make_router([FakeReplica("r")])
        router.stop()
        fut = router.submit("paths", sources=("a",))
        with pytest.raises(QueryShedError):
            fut.result(5)
        assert router.get_counters()["serving.router.sheds"] == 1


class TestEpochPinning:
    def test_stale_reply_reroutes_to_caught_up_replica(self):
        ahead = FakeReplica("ahead", epoch=5)
        behind = FakeReplica("behind", epoch=3)
        router = make_router([ahead, behind])
        router.pin_trace = []
        # first query pins the session at the ahead replica's epoch
        res = router.submit("paths", sources=("a",), session="s").result(5)
        assert res.epoch == 5
        assert router.session_pin("s") == 5
        # round-robin hands the next query to the behind replica: its
        # stale answer must be re-routed, never delivered
        res = router.submit("paths", sources=("a",), session="s").result(5)
        assert res.epoch == 5
        assert res.value["from"] == "ahead"
        c = router.get_counters()
        assert c["serving.router.epoch_reroutes"] == 1
        epochs = [e for (s, e) in router.pin_trace if s == "s"]
        assert epochs == sorted(epochs)  # monotonically non-decreasing
        assert_ledger(router, 2)
        router.stop()

    def test_stale_answer_never_delivered_even_without_caught_up_peer(self):
        ahead = FakeReplica("ahead", epoch=5)
        behind = FakeReplica("behind", epoch=3)
        router = make_router([ahead, behind], max_attempts=4)
        assert (
            router.submit("paths", sources=("a",), session="s").result(5).epoch
            == 5
        )
        ahead.mode = "unavailable"  # only the behind replica remains
        fut = router.submit("paths", sources=("a",), session="s")
        # bounded re-routes exhaust and fail loudly — a stale answer is
        # never the fallback
        with pytest.raises(Exception) as exc_info:
            fut.result(5)
        assert not isinstance(exc_info.value, concurrent.futures.TimeoutError)
        assert router.session_pin("s") == 5
        router.stop()

    def test_sessionless_queries_have_no_pin(self):
        behind = FakeReplica("behind", epoch=3)
        router = make_router([behind])
        res = router.submit("paths", sources=("a",)).result(5)
        assert res.epoch == 3
        assert router.get_counters()["serving.router.epoch_reroutes"] == 0
        router.stop()

    def test_pin_only_moves_forward(self):
        rep = FakeReplica("r", epoch=5)
        router = make_router([rep])
        router.submit("paths", sources=("a",), session="s").result(5)
        rep.epoch_value = 9
        router.submit("paths", sources=("a",), session="s").result(5)
        assert router.session_pin("s") == 9
        router.stop()


class TestHedging:
    def test_hedge_wins_when_primary_stalls(self):
        slow = FakeReplica("slow", mode="hold")
        fast = FakeReplica("fast")
        router = ReplicaRouter([slow, fast], hedge_after_s=0.01)
        res = router.submit("paths", sources=("a",)).result(10)
        assert res.value["from"] == "fast"
        c = router.get_counters()
        assert c["serving.router.hedges"] == 1
        assert c["serving.router.hedge_wins"] == 1
        assert_ledger(router, 1)
        # the loser resolves late: observed for health, answer dropped
        slow.release()
        time.sleep(0.02)
        assert router.alive_replicas() == 2
        assert_ledger(router, 1)
        router.stop()

    def test_no_hedge_when_reply_beats_deadline(self):
        reps = [FakeReplica("a"), FakeReplica("b")]
        router = ReplicaRouter(reps, hedge_after_s=5.0)
        assert router.submit("paths", sources=("x",)).result(5)
        time.sleep(0.02)
        assert router.get_counters()["serving.router.hedges"] == 0
        router.stop()


class TestEpochMismatchRetry:
    def test_mismatch_from_replica_is_retried_not_failed(self):
        class MismatchOnce(FakeReplica):
            def __init__(self):
                super().__init__("flappy", epoch=2)
                self.first = True

            def submit(self, op, **kw):
                if self.first:
                    self.first = False
                    fut = concurrent.futures.Future()
                    fut.set_exception(EpochMismatchError(1, 2))
                    self.submits.append((op, kw))
                    return fut
                return super().submit(op, **kw)

        router = make_router([MismatchOnce()])
        res = router.submit("paths", sources=("a",)).result(5)
        assert res.epoch == 2
        c = router.get_counters()
        assert c["serving.router.retries"] == 1
        assert c["serving.router.replica_deaths"] == 0  # healthy, just moved
        assert_ledger(router, 1)
        router.stop()


class TestLoadGenIntegration:
    def test_open_loop_ledger_reconciles_over_fakes(self):
        from openr_tpu.chaos import OpenLoopLoadGen

        reps = [FakeReplica(f"r{i}", epoch=4) for i in range(3)]
        router = make_router(reps)
        gen = OpenLoopLoadGen(
            router, ["a", "b", "c"], seed=3, clients=4, sessions=True
        )
        report = gen.run_burst(25)
        assert report.submitted == 100
        assert report.accounted == report.submitted
        assert report.replied == 100
        assert_ledger(router, report.submitted)
        router.stop()
