"""Device-batched KSP2 conformance: DeviceSpfBackend.get_kth_paths /
prefetch_kth_paths must reproduce LinkState.get_kth_paths (the reference's
sequential per-destination recursion, LinkState.cpp:763-793) exactly, and
the KSP2 route-selection path must produce identical RIBs on both
backends."""

from __future__ import annotations

import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
from openr_tpu.types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)
from openr_tpu.utils.topo import grid_topology, random_topology


def build_ls(dbs) -> LinkState:
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return ls


def canon(paths):
    """Order-insensitive canonical form of a path set (ECMP tie order may
    differ between host heap order and device DAG order)."""
    return sorted(
        tuple((link.n1, link.n2) for link in path) for path in paths
    )


class TestKthPathsConformance:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_topologies(self, seed):
        dbs = random_topology(n_nodes=80, n_extra_edges=120, seed=seed)
        ls_host = build_ls(dbs)
        ls_dev = build_ls(dbs)
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)

        nodes = sorted(ls_host.node_names)
        src = nodes[0]
        dests = nodes[1:25]
        backend.prefetch_kth_paths(ls_dev, src, dests)
        for dest in dests:
            for k in (1, 2):
                host = ls_host.get_kth_paths(src, dest, k)
                dev = backend.get_kth_paths(ls_dev, src, dest, k)
                assert canon(dev) == canon(host), (seed, src, dest, k)

    def test_grid(self):
        dbs = grid_topology(6)
        ls_host = build_ls(dbs)
        ls_dev = build_ls(dbs)
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        src = "node-0-0"
        dests = ["node-5-5", "node-0-5", "node-3-2", "node-1-0"]
        for dest in dests:
            for k in (1, 2):
                host = ls_host.get_kth_paths(src, dest, k)
                dev = backend.get_kth_paths(ls_dev, src, dest, k)
                assert canon(dev) == canon(host), (dest, k)

    def test_src_equals_dest_and_unknown(self):
        dbs = grid_topology(4)
        ls = build_ls(dbs)
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        assert backend.get_kth_paths(ls, "node-0-0", "node-0-0", 1) == []
        assert backend.get_kth_paths(ls, "node-0-0", "node-0-0", 2) == []

    def test_cache_invalidated_on_topology_change(self):
        dbs = grid_topology(4)
        ls = build_ls(dbs)
        backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        before = backend.get_kth_paths(ls, "node-0-0", "node-3-3", 1)
        assert before
        # fail a link on the first path: results must change
        link = before[0][0]
        db = next(
            d for d in dbs if d.this_node_name == link.n1
        )
        db.adjacencies = [
            a for a in db.adjacencies if a.other_node_name != link.n2
        ]
        ls.update_adjacency_database(db)
        after = backend.get_kth_paths(ls, "node-0-0", "node-3-3", 1)
        host = ls.get_kth_paths("node-0-0", "node-3-3", 1)
        assert canon(after) == canon(host)


class TestKsp2RouteParity:
    def _route_db(self, backend, dbs, algo_nodes):
        ls = build_ls(dbs)
        ps = PrefixState()
        for node in algo_nodes:
            ps.update_prefix(
                node,
                "0",
                PrefixEntry(
                    prefix="fc00:dead::/64",
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            )
        solver = SpfSolver("node-0-0", spf_backend=backend)
        return solver.build_route_db({"0": ls}, ps)

    def test_grid_rib_identical(self):
        dbs = grid_topology(5)
        algo_nodes = ["node-4-4", "node-2-3"]
        host_rdb = self._route_db(None, grid_topology(5), algo_nodes)
        dev_rdb = self._route_db(
            DeviceSpfBackend(min_device_nodes=1, min_device_sources=1), grid_topology(5), algo_nodes
        )
        assert host_rdb.unicast_routes == dev_rdb.unicast_routes
