"""Banded relax kernel + reduced all-sources product vs the oracle.

The band-augmented kernel (ops.banded) must be bit-identical to the
bucketed-ELL kernel / host Dijkstra on every semantic axis: metrics,
drain (overload) transit rules incl. the own-source exception, down
links, per-row exclusion masks, uint16 distance mode, and the
convergence verdict.  The reduced all-sources product (ops.allsources)
must reproduce forward per-source distances and the reference's
LFA-free ECMP next-hop sets from ONE reverse-SSSP call.

Reference semantics anchored at openr/decision/LinkState.cpp:809-878
(runSpf) and Decision.cpp:1296-1300 (getNextHopsThrift ECMP condition).
"""

from __future__ import annotations

import numpy as np
import pytest

from benchmarks import synthetic
from openr_tpu.ops import banded as bd
from openr_tpu.ops import sssp as ops
from openr_tpu.ops.sssp import INF32


def to_i32(dist) -> np.ndarray:
    """Normalize a reduced-product distance matrix to int32/INF32: the
    product returns raw uint16 (INF16 sentinel) when the banded kernel
    runs in small-distance mode (ops.allsources contract)."""
    from openr_tpu.decision.fleet import _row_i32

    return _row_i32(np.asarray(dist))


def oracle(topo, sources, extra_mask=None):
    import jax.numpy as jnp

    if extra_mask is None:
        dist, dag = ops.spf_forward_ell(
            np.asarray(sources, np.int32),
            topo.ell,
            topo.edge_src,
            topo.edge_dst,
            topo.edge_metric,
            topo.edge_up,
            topo.node_overloaded,
        )
    else:
        dist, dag = ops.spf_forward_ell_masked(
            np.asarray(sources, np.int32),
            topo.ell,
            topo.edge_src,
            topo.edge_dst,
            topo.edge_metric,
            topo.edge_up,
            topo.node_overloaded,
            jnp.asarray(extra_mask),
        )
    return np.asarray(dist), np.asarray(dag)


def assert_matches_oracle(topo, sources, extra_mask=None):
    odist, odag = oracle(topo, sources, extra_mask)
    dist, dag = topo.runner.forward(
        np.asarray(sources, np.int32), extra_edge_mask=extra_mask
    )
    n, e = topo.n_nodes, topo.n_edges
    np.testing.assert_array_equal(dist[:, :n], odist[:, :n])
    np.testing.assert_array_equal(dag[:, :e], odag[:, :e])


class TestBandedKernel:
    def test_grid_all_bands(self):
        g = synthetic.grid(8)
        assert g.banded is not None
        assert set(g.banded.offsets) == {1, 8, 56, 63}
        assert_matches_oracle(g, np.arange(16))

    def test_wan_ring_chords(self):
        w = synthetic.wan(512, chords=2, seed=3)
        assert w.banded is not None
        assert w.banded.resid_nbr.shape[1] == 4  # uniform chord degree
        assert_matches_oracle(w, np.arange(24))

    def test_fattree_falls_back_to_ell(self):
        ft = synthetic.fat_tree(
            pods=4, planes=2, ssw_per_plane=4, rsw_per_pod=8
        )
        assert ft.banded is None
        assert_matches_oracle(ft, np.arange(12))  # ELL fixed-sweep path

    def test_drain_semantics_and_down_links(self):
        w = synthetic.wan(256, chords=2, seed=5)
        w.node_overloaded[[3, 77, 130]] = True
        w.edge_up[np.arange(0, w.n_edges, 17)] = False
        # sources include an overloaded node (the own-source exception)
        assert_matches_oracle(w, np.asarray([0, 3, 77, 9]))

    def test_masked_rows(self):
        w = synthetic.wan(256, chords=2, seed=5)
        rng = np.random.default_rng(0)
        mask = np.ones((6, w.edge_capacity), dtype=bool)
        for r in range(6):
            mask[r, rng.integers(0, w.n_edges, 5)] = False
        assert_matches_oracle(w, np.zeros(6, np.int32), extra_mask=mask)

    def test_uint16_mode_engages_and_matches(self):
        w = synthetic.wan(512, chords=2, seed=3)
        assert w.runner.small_dist  # metrics 1..10 qualify
        assert_matches_oracle(w, np.arange(16))

    def test_large_metrics_disable_uint16(self):
        w = synthetic.wan(256, chords=2, seed=1)
        w.edge_metric[: w.n_edges] = 10_000  # above the uint16 gate
        assert not w.runner.small_dist
        assert_matches_oracle(w, np.arange(8))

    def test_insufficient_sweeps_detected(self):
        w = synthetic.wan(512, chords=2, seed=3)
        _, _, ok = w.runner.run_once(np.arange(4, dtype=np.int32), 1)
        assert not bool(ok)

    def test_hint_doubles_until_converged(self):
        w = synthetic.wan(512, chords=2, seed=4)
        w.runner.hint = 1
        assert_matches_oracle(w, np.arange(4))
        assert w.runner.hint > 1

    def test_chord_mode_auto_pick(self):
        """Chord-rich small worlds run the two-pass Jacobi supersweep;
        band-dominated grids keep the sequential sweep with composed
        levels (round-5 tune).  The oracle tests above exercise BOTH
        supersweeps (wan picks chord mode, grid sequential) — this pins
        the auto-pick itself."""
        w = synthetic.wan(512, chords=2, seed=3)
        assert w.runner.chord_mode
        assert w.runner.depth == 0
        g = synthetic.grid(8)
        assert not g.runner.chord_mode
        assert g.runner.depth == 2
        # explicit depth bypasses the auto-pick
        from openr_tpu.ops.banded import SpfRunner

        r = SpfRunner(
            w.ell,
            w.banded,
            w.edge_src,
            w.edge_dst,
            w.edge_metric,
            w.edge_up,
            w.node_overloaded,
            w.n_edges,
            depth=1,
        )
        assert not r.chord_mode and r.depth == 1

    def test_parallel_band_links_demoted_to_residual(self):
        # duplicate ring links (parallel edges on the same band offset)
        # must not collide in the band table
        n = 128
        ids = np.arange(n, dtype=np.int32)
        ring = np.stack([ids, (ids + 1) % n], axis=1)
        links = np.concatenate([ring, ring, ring[:, ::-1]])
        metrics = np.concatenate(
            [
                np.full(n, 5, np.int32),
                np.full(n, 3, np.int32),  # parallel, cheaper
                np.full(n, 4, np.int32),
            ]
        )
        topo = synthetic.Topology.from_links("ringpar", n, links, metrics)
        if topo.banded is not None:
            assert_matches_oracle(topo, np.arange(8))


class TestCsrRunnerIntegration:
    def test_csr_banded_matches_host(self):
        """CsrTopology on a ring topology picks up bands and reproduces
        the host-oracle SpfResults through run_batched_spf."""
        from openr_tpu.decision import LinkState
        from openr_tpu.decision.csr import CsrTopology
        from openr_tpu.utils.topo import ring_topology

        dbs = ring_topology(64)
        ls = LinkState()
        for db in dbs:
            ls.update_adjacency_database(db)
        csr = CsrTopology.from_link_state(ls)
        assert csr.banded is not None
        sources = [dbs[i].this_node_name for i in (0, 7, 33)]
        dist, dag = csr.run_batched_spf(sources)
        results = csr.to_spf_results(sources, dist, dag)
        for src in sources:
            host = ls.run_spf(src)
            got = results[src]
            assert set(got) == set(host)
            for node, res in host.items():
                assert got[node].metric == res.metric


class TestReducedAllSources:
    def _setup(self, topo, n_prefixes=24, seed=11):
        from openr_tpu.ops import allsources as asrc

        rng = np.random.default_rng(seed)
        dests = np.sort(
            rng.choice(topo.n_nodes, size=n_prefixes, replace=False)
        ).astype(np.int32)
        rev = synthetic.reversed_topology(topo)
        out = asrc.build_out_ell(
            topo.edge_src, topo.edge_dst, topo.n_edges, topo.n_nodes
        )
        return asrc, dests, rev, out

    def test_reverse_distances_match_forward(self):
        w = synthetic.wan(256, chords=2, seed=9)
        asrc, dests, rev, out = self._setup(w)
        dist, bitmap, ok = asrc.reduced_all_sources(
            dests, rev.runner, out, w.edge_metric, w.edge_up,
            w.node_overloaded,
        )
        assert bool(ok)
        dist = to_i32(dist)  # [N, P] native layout
        # forward oracle over a sample of routers
        sample = np.asarray([0, 3, 100, 255], np.int32)
        odist, _ = oracle(w, sample)
        for i, v in enumerate(sample):
            np.testing.assert_array_equal(dist[v], odist[i, dests])

    def test_reverse_respects_drain_semantics(self):
        w = synthetic.wan(256, chords=2, seed=9)
        w.node_overloaded[[5, 60]] = True
        w.edge_up[np.arange(0, w.n_edges, 13)] = False
        asrc, dests, rev, out = self._setup(w)
        # overloaded nodes appear BOTH as routers (origin exception) and
        # among the destinations
        dests = np.unique(np.concatenate([dests, [5, 60]])).astype(np.int32)
        dist, _, ok = asrc.reduced_all_sources(
            dests, rev.runner, out, w.edge_metric, w.edge_up,
            w.node_overloaded,
        )
        assert bool(ok)
        dist = to_i32(dist)  # [N, P]
        sample = np.asarray([0, 5, 60, 200], np.int32)
        odist, _ = oracle(w, sample)
        for i, v in enumerate(sample):
            np.testing.assert_array_equal(dist[v], odist[i, dests])

    def test_non_banded_topology_uses_ell_fallback(self):
        """reduced_all_sources must work when build_banded returns None
        (ELL fallback pads dist to node_capacity — regression: shape
        mismatch crash in the bitmap pass)."""
        ft = synthetic.fat_tree(
            pods=4, planes=2, ssw_per_plane=4, rsw_per_pod=8
        )
        assert ft.banded is None
        asrc, dests, rev, out = self._setup(ft, n_prefixes=8)
        dist, bitmap, ok = asrc.reduced_all_sources(
            dests, rev.runner, out, ft.edge_metric, ft.edge_up,
            ft.node_overloaded,
        )
        assert bool(ok)
        assert np.asarray(bitmap).shape[0] == ft.n_nodes
        dist = np.asarray(dist)  # [N_cap, P]
        sample = np.asarray([0, 9, 30], np.int32)
        odist, _ = oracle(ft, sample)
        for i, v in enumerate(sample):
            np.testing.assert_array_equal(dist[v], odist[i, dests])

    def test_bitmap_excludes_drained_neighbor(self):
        """Ring with an overloaded node: the coincidental distance
        equality through the drained neighbor must NOT set its bit —
        the reference draws ECMP neighbors from the drain-respecting
        source tree (Decision.cpp:1182-1260).  Regression for the
        round-4 review repro (bitmap said {1, 63}, SP-DAG says {63})."""
        from openr_tpu.ops import allsources as asrc

        n = 64
        ids = np.arange(n, dtype=np.int32)
        links = np.stack([ids, (ids + 1) % n], axis=1)
        w = synthetic.Topology.from_links(
            "ring64", n, links, np.ones(len(links), np.int32)
        )
        w.node_overloaded[1] = True
        dests = np.asarray([32], np.int32)
        rev = synthetic.reversed_topology(w)
        out = asrc.build_out_ell(w.edge_src, w.edge_dst, w.n_edges, n)
        dist, bitmap, ok = asrc.reduced_all_sources(
            dests, rev.runner, out, w.edge_metric, w.edge_up,
            w.node_overloaded,
        )
        assert bool(ok)
        # router 0 -> dest 32: only the counter-clockwise neighbor (63)
        bits = int(np.asarray(bitmap)[0, 0, 0])
        slots = {b for b in range(32) if bits & (1 << b)}
        slot_names = sorted({1, 63})  # sorted unique out-neighbors of 0
        hops = {slot_names[s] for s in slots}
        assert hops == {63}, hops
        # and the drained node as DESTINATION still gets next-hops
        dests2 = np.asarray([1], np.int32)
        _, bm2, ok2 = asrc.reduced_all_sources(
            dests2, rev.runner, out, w.edge_metric, w.edge_up,
            w.node_overloaded,
        )
        assert bool(ok2)
        bits2 = int(np.asarray(bm2)[0, 0, 0])
        assert {slot_names[b] for b in range(32) if bits2 & (1 << b)} == {1}

    def test_bitmap_matches_reference_ecmp_condition(self):
        """Bit s set for (v, p) iff out-slot s satisfies
        metric(v,u) + dist(u,p) == dist(v,p) — decoded against a direct
        numpy evaluation of the same condition from forward distances."""
        w = synthetic.wan(128, chords=2, seed=13)
        asrc, dests, rev, out = self._setup(w, n_prefixes=12)
        dist, bitmap, ok = asrc.reduced_all_sources(
            dests, rev.runner, out, w.edge_metric, w.edge_up,
            w.node_overloaded,
        )
        assert bool(ok)
        dist = to_i32(dist)  # [N, P] native layout
        bitmap = np.asarray(bitmap)  # [N, P, W]
        e = w.n_edges
        src = w.edge_src[:e]
        dst = w.edge_dst[:e]
        met = w.edge_metric[:e]
        # expected slots per (v, p) from the forward-distance identity
        from openr_tpu.decision.csr import _build_out_slots

        out_slot, _ = _build_out_slots(w.edge_src, w.edge_dst, e)
        for p_i in range(len(dests)):
            d = dist[:, p_i]  # dist(x -> dest p)
            on = (d[src] < INF32 * 0 + (1 << 30)) & (
                met + d[dst] == d[src]
            )
            for v in (0, 17, 63, 90):
                want = {
                    int(out_slot[ei])
                    for ei in np.flatnonzero(on & (src == v))
                }
                got = set()
                for wd in range(bitmap.shape[2]):
                    bits = int(bitmap[v, p_i, wd])
                    for b in range(32):
                        if bits & (1 << b):
                            got.add(32 * wd + b)
                assert got == want, (v, dests[p_i])
