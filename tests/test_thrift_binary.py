"""Thrift binary codec + wire shim (openr_tpu/interop/).

Golden byte vectors are HAND-COMPUTED from the reference IDL field ids
(openr/if/Types.thrift:555 Value, :683 KeyGetParams) so the encoding is
pinned to the IDL, not to our own encoder; the shim test then drives a
framed thrift-binary getKvStoreKeyVals/setKvStoreKeyVals exchange
against a live daemon's KvStore over real TCP (the cross-stack
demonstration scoped by docs/ARCHITECTURE.md's decision record)."""

from __future__ import annotations

import socket
import struct as _s

import pytest

from openr_tpu.interop import thrift_binary as tb
from openr_tpu.interop.shim import ThriftBinaryShim
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    PerfEvent,
    PerfEvents,
    Publication,
    RouteDatabase,
    UnicastRoute,
    Value,
)


class TestGoldenVectors:
    def test_value_encoding_matches_idl_field_ids(self):
        # Types.thrift:555 — NOTE ids are NOT in declaration order:
        # 1: i64 version, 3: string originatorId, 2: optional binary
        # value, 4: i64 ttl, 5: i64 ttlVersion, 6: optional i64 hash
        v = Value(
            version=5,
            originator_id="n1",
            value=b"ab",
            ttl_ms=3_600_000,
            ttl_version=2,
        )
        expected = bytes.fromhex(
            "0a0001" + "0000000000000005"  # 1: i64 version = 5
            + "0b0003" + "00000002" + "6e31"  # 3: string "n1"
            + "0b0002" + "00000002" + "6162"  # 2: binary b"ab"
            + "0a0004" + "000000000036ee80"  # 4: i64 ttl = 3600000
            + "0a0005" + "0000000000000002"  # 5: i64 ttlVersion = 2
            + "00"  # T_STOP (hash unset -> omitted)
        )
        assert tb.encode_struct(tb.VALUE, v) == expected
        assert tb.decode_struct(tb.VALUE, expected) == v

    def test_key_get_params_golden(self):
        # Types.thrift:683 KeyGetParams {1: list<string> keys}
        expected = bytes.fromhex(
            "0f0001"  # field 1, T_LIST
            + "0b" + "00000002"  # elem T_STRING, 2 items
            + "00000001" + "61"  # "a"
            + "00000002" + "6262"  # "bb"
            + "00"
        )
        enc = tb.encode_struct(tb.KEY_GET_PARAMS, {"keys": ["a", "bb"]})
        assert enc == expected
        assert tb.decode_struct(tb.KEY_GET_PARAMS, expected) == {
            "keys": ["a", "bb"]
        }

    def test_strict_call_envelope_golden(self):
        msg = tb.encode_message("ping", tb.MSG_CALL, 7, b"\x00")
        assert msg == bytes.fromhex(
            "80010001" + "00000004" + "70696e67" + "00000007" + "00"
        )
        name, mtype, seqid, r = tb.decode_message(msg)
        assert (name, mtype, seqid) == ("ping", tb.MSG_CALL, 7)


class TestRoundTrips:
    def test_publication(self):
        pub = Publication(
            key_vals={
                "adj:n1": Value(2, "n1", b"payload", 300_000, 1),
                "prefix:[n2]": Value(9, "n2", None, -1, 0),
            },
            expired_keys=["gone"],
            node_ids=["n1", "n2"],
            area="spine",
        )
        data = tb.encode_struct(tb.PUBLICATION, pub)
        back = tb.decode_struct(tb.PUBLICATION, data)
        assert back == pub

    def test_adjacency_database_with_binary_addresses(self):
        db = AdjacencyDatabase(
            this_node_name="r1",
            adjacencies=[
                Adjacency(
                    other_node_name="r2",
                    if_name="eth0",
                    metric=10,
                    adj_label=50001,
                    next_hop_v6="fe80::2",
                    next_hop_v4="10.0.0.2",
                    other_if_name="eth9",
                    rtt_us=1200,
                    weight=1,
                )
            ],
            is_overloaded=True,
            node_label=101,
            area="0",
            perf_events=PerfEvents(events=[PerfEvent("r1", "ADJ_UP", 123)]),
        )
        data = tb.encode_struct(tb.ADJACENCY_DATABASE, db)
        back = tb.decode_struct(tb.ADJACENCY_DATABASE, data)
        assert back == db

    def test_key_set_and_dump_params(self):
        ksp = {
            "key_vals": {"k": Value(1, "me", b"v", -1, 0)},
            "solicit_response": True,
            "node_ids": ["me"],
            "flood_root_id": None,
            "timestamp_ms": None,
        }
        back = tb.decode_struct(
            tb.KEY_SET_PARAMS, tb.encode_struct(tb.KEY_SET_PARAMS, ksp)
        )
        assert back["key_vals"] == ksp["key_vals"]
        assert back["node_ids"] == ["me"]

        kdp = {
            "prefix": "adj:",
            "originator_ids": {"n1", "n2"},
            "ignore_ttl": False,
            "do_not_publish_value": True,
            "key_val_hashes": None,
            "oper": None,
            "keys": ["adj:n1"],
        }
        back = tb.decode_struct(
            tb.KEY_DUMP_PARAMS, tb.encode_struct(tb.KEY_DUMP_PARAMS, kdp)
        )
        assert back["originator_ids"] == {"n1", "n2"}
        assert back["keys"] == ["adj:n1"]
        assert back["do_not_publish_value"] is True

    def test_encode_fills_declared_defaults(self):
        """A minimal dict omitting defaulted non-optional fields must
        encode (the default fills in, mirroring the decode side) — a
        client issuing setKvStoreKeyVals with only key_vals exercises
        this."""
        minimal = {"key_vals": {"k": Value(1, "me", b"v", -1, 0)}}
        back = tb.decode_struct(
            tb.KEY_SET_PARAMS, tb.encode_struct(tb.KEY_SET_PARAMS, minimal)
        )
        assert back["key_vals"] == minimal["key_vals"]
        assert back["solicit_response"] is True  # declared default

    def test_peer_spec(self):
        ps = {
            "peer_addr": "fe80::1",
            "cmd_url": None,
            "ctrl_port": 2018,
            "state": 2,
        }
        back = tb.decode_struct(
            tb.PEER_SPEC, tb.encode_struct(tb.PEER_SPEC, ps)
        )
        assert back["peer_addr"] == "fe80::1"
        assert back["ctrl_port"] == 2018 and back["state"] == 2

    def test_unknown_fields_skipped(self):
        # forward compatibility: a newer peer adds field 99 (i32) — our
        # decoder must skip it and still decode the rest
        w = tb._Writer()
        w.u8(tb.T_I32)
        w.i16(99)
        w.i32(1234)
        body = w.getvalue() + tb.encode_struct(
            tb.VALUE, Value(1, "x", b"y", -1, 0)
        )
        back = tb.decode_struct(tb.VALUE, body)
        assert back == Value(1, "x", b"y", -1, 0)


def _thrift_call(port: int, name: str, seqid: int, args: bytes) -> tuple:
    """Framed strict-binary call over a plain TCP socket — exactly the
    bytes a thrift TFramedTransport+TBinaryProtocol client produces."""
    msg = tb.encode_message(name, tb.MSG_CALL, seqid, args)
    with socket.create_connection(("::1", port), timeout=10) as sock:
        sock.sendall(tb.frame(msg))
        head = b""
        while len(head) < 4:
            head += sock.recv(4 - len(head))
        (length,) = _s.unpack("!i", head)
        data = b""
        while len(data) < length:
            data += sock.recv(length - len(data))
    return tb.decode_message(data)


def _result_spec(success_spec, dec=None):
    """Reply struct: the success value at field 0."""
    return tb.StructSpec(
        "result", None, (tb.Field(0, "success", success_spec, dec=dec),)
    )


def _call_ok(port, name, seqid, args, success_spec, dec=None):
    """Framed call + MSG_REPLY assert + decoded success value."""
    got_name, mtype, got_seqid, r = _thrift_call(port, name, seqid, args)
    assert (got_name, mtype, got_seqid) == (name, tb.MSG_REPLY, seqid)
    return tb.read_struct(r, _result_spec(success_spec, dec))["success"]


class TestShimExchange:
    @pytest.fixture
    def shim(self):
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from tests.test_system import make_config

        fabric = MockIoProvider()
        daemon = OpenrDaemon(
            make_config("shimd", ctrl_port=0),
            io_provider=fabric.endpoint("shimd"),
            kvstore_transport=InProcessTransport().bind("shimd"),
        )
        daemon.start()
        shim = ThriftBinaryShim(daemon.kvstore, port=0, node_name="shimd")
        shim.run()
        yield daemon, shim
        shim.stop()
        shim.wait_until_stopped(5)
        daemon.stop()

    def test_set_then_get_over_the_wire(self, shim):
        daemon, shim_srv = shim
        # 1. setKvStoreKeyVals(1: KeySetParams, 2: area) — raw bytes in
        set_args = tb.encode_struct(
            tb.StructSpec(
                "args",
                None,
                (
                    tb.Field(1, "set_params", ("struct", tb.KEY_SET_PARAMS)),
                    tb.Field(2, "area", tb.T_STRING),
                ),
            ),
            {
                "set_params": {
                    "key_vals": {
                        "interop-key": Value(3, "ext", b"from-thrift", -1, 0)
                    },
                    "solicit_response": True,
                    "node_ids": None,
                    "flood_root_id": None,
                    "timestamp_ms": None,
                },
                "area": "0",
            },
        )
        name, mtype, seqid, _ = _thrift_call(
            shim_srv.port, "setKvStoreKeyVals", 1, set_args
        )
        assert (name, mtype, seqid) == ("setKvStoreKeyVals", tb.MSG_REPLY, 1)
        # the value landed in the daemon's CRDT store
        pub = daemon.kvstore.get_key_vals("0", ["interop-key"])
        assert pub.key_vals["interop-key"].value == b"from-thrift"

        # 2. getKvStoreKeyVals(1: filterKeys) -> Publication
        get_args = tb.encode_struct(
            tb.StructSpec(
                "args",
                None,
                (tb.Field(1, "filter_keys", ("list", tb.T_STRING)),),
            ),
            {"filter_keys": ["interop-key"]},
        )
        name, mtype, seqid, r = _thrift_call(
            shim_srv.port, "getKvStoreKeyVals", 2, get_args
        )
        assert (name, mtype) == ("getKvStoreKeyVals", tb.MSG_REPLY)
        reply = tb.read_struct(
            r,
            tb.StructSpec(
                "result",
                None,
                (tb.Field(0, "success", ("struct", tb.PUBLICATION)),),
            ),
        )
        out = reply["success"]
        assert out.key_vals["interop-key"].value == b"from-thrift"
        assert out.key_vals["interop-key"].version == 3
        assert out.key_vals["interop-key"].originator_id == "ext"

    def test_unknown_method_gets_application_exception(self, shim):
        _daemon, shim_srv = shim
        name, mtype, _seqid, _r = _thrift_call(
            shim_srv.port, "noSuchRpc", 5, b"\x00"
        )
        assert name == "noSuchRpc" and mtype == tb.MSG_EXCEPTION

    def test_meta_and_dump_methods(self, shim):
        """getMyNodeName / getOpenrVersion / filtered dumps / peers —
        reference signatures OpenrCtrl.thrift:412-492, 560, 612."""
        daemon, shim_srv = shim
        port = shim_srv.port
        daemon.kvstore.set_key_vals(
            "0", {"snoop:k1": Value(1, "shimd", b"a", -1, 0)}
        )
        filter_args = tb.StructSpec(
            "args",
            None,
            (
                tb.Field(1, "filter", ("struct", tb.KEY_DUMP_PARAMS)),
                tb.Field(2, "area", tb.T_STRING, optional=True),
            ),
        )

        # getMyNodeName() -> string
        got = _call_ok(port, "getMyNodeName", 7, b"\x00", tb.T_STRING)
        assert got == b"shimd"

        # getOpenrVersion() -> OpenrVersions
        ver = _call_ok(
            port,
            "getOpenrVersion",
            8,
            b"\x00",
            ("struct", tb.OPENR_VERSIONS),
        )
        assert ver["version"] >= ver["lowest_supported_version"] > 0

        # getKvStoreKeyValsFilteredArea(1: KeyDumpParams, 2: area)
        pub = _call_ok(
            port,
            "getKvStoreKeyValsFilteredArea",
            9,
            tb.encode_struct(
                filter_args, {"filter": {"keys": ["snoop:"]}, "area": "0"}
            ),
            ("struct", tb.PUBLICATION),
        )
        assert pub.key_vals["snoop:k1"].value == b"a"

        # deprecated comma-separated prefix field (reference
        # KvStore.cpp:649 folly::split; legacy breeze comma-joins)
        pub = _call_ok(
            port,
            "getKvStoreKeyValsFiltered",
            13,
            tb.encode_struct(
                filter_args, {"filter": {"prefix": "nomatch:,snoop:"}}
            ),
            ("struct", tb.PUBLICATION),
        )
        assert "snoop:k1" in pub.key_vals

        # doNotPublishValue=true withholds values (hash-only dump)
        pub = _call_ok(
            port,
            "getKvStoreKeyValsFiltered",
            14,
            tb.encode_struct(
                filter_args,
                {
                    "filter": {
                        "keys": ["snoop:"],
                        "do_not_publish_value": True,
                    }
                },
            ),
            ("struct", tb.PUBLICATION),
        )
        assert pub.key_vals["snoop:k1"].value is None
        assert pub.key_vals["snoop:k1"].hash != 0

        # getKvStoreHashFiltered(1: KeyDumpParams) — hash dump: no values
        pub = _call_ok(
            port,
            "getKvStoreHashFiltered",
            10,
            tb.encode_struct(filter_args, {"filter": {"keys": ["snoop:"]}}),
            ("struct", tb.PUBLICATION),
        )
        assert pub.key_vals["snoop:k1"].value is None
        assert pub.key_vals["snoop:k1"].hash != 0

        # filtered KeyVals dump rides the peer full-sync path: TTLs come
        # back DECREMENTED to time remaining (a dump_all reply would
        # re-arm full TTLs on the remote peer every sync)
        daemon.kvstore.set_key_vals(
            "0", {"snoop:ttl": Value(1, "shimd", b"t", 30000, 1)}
        )
        pub = _call_ok(
            port,
            "getKvStoreKeyValsFilteredArea",
            12,
            tb.encode_struct(
                filter_args,
                {"filter": {"keys": ["snoop:ttl"]}, "area": "0"},
            ),
            ("struct", tb.PUBLICATION),
        )
        assert 0 < pub.key_vals["snoop:ttl"].ttl_ms < 30000

        # getKvStorePeersArea(1: area) -> map<string, PeerSpec>
        peers = _call_ok(
            port,
            "getKvStorePeersArea",
            11,
            tb.encode_struct(
                tb.StructSpec(
                    "args", None, (tb.Field(1, "area", tb.T_STRING),)
                ),
                {"area": "0"},
            ),
            ("map", tb.T_STRING, ("struct", tb.PEER_SPEC)),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        )
        assert peers == {}  # single-node daemon: no peers


class TestShimLongPoll:
    """longPollKvStoreAdjArea / deprecated longPollKvStoreAdj over the
    wire (reference OpenrCtrl.thrift:424-431): the client sends its
    adj-key version snapshot; the shim answers true immediately when the
    snapshot is stale, blocks on the daemon's kvstore publication queue
    when it is current, and resolves true the moment an adj key
    advances — false only at timeout.  Mirrors the native ctrl server's
    _long_poll_adj plus the shim-only timeout."""

    ARGS = tb.StructSpec(
        "args",
        None,
        (tb.Field(1, "snapshot", ("map", tb.T_STRING, ("struct", tb.VALUE))),),
    )
    AREA_ARGS = tb.StructSpec(
        "args",
        None,
        (
            tb.Field(1, "area", tb.T_STRING),
            tb.Field(
                2, "snapshot", ("map", tb.T_STRING, ("struct", tb.VALUE))
            ),
        ),
    )

    @pytest.fixture
    def shim(self):
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.serializer import dumps
        from openr_tpu.spark import MockIoProvider
        from tests.test_system import make_config

        fabric = MockIoProvider()
        daemon = OpenrDaemon(
            make_config("lpd", ctrl_port=0),
            io_provider=fabric.endpoint("lpd"),
            kvstore_transport=InProcessTransport().bind("lpd"),
        )
        daemon.start()
        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="lpd",
            kvstore_updates_queue=daemon.kvstore_updates_queue,
            long_poll_timeout_s=1.0,
        )
        shim.run()
        # a real serialized AdjacencyDatabase so the daemon's own
        # decision reader digests the injected key without complaint
        adj_payload = dumps(
            AdjacencyDatabase(
                this_node_name="peerx", adjacencies=[], area="0"
            )
        )
        daemon.kvstore.set_key_vals(
            "0", {"adj:peerx": Value(1, "peerx", adj_payload, -1, 0)}
        )
        yield daemon, shim, adj_payload
        shim.stop()
        shim.wait_until_stopped(5)
        daemon.stop()

    def _current_snapshot(self, daemon):
        pub = daemon.kvstore.dump_all("0", key_prefixes=["adj:"])
        return {
            k: Value(v.version, v.originator_id, None, -1, 0)
            for k, v in pub.key_vals.items()
        }

    def test_stale_snapshot_resolves_immediately(self, shim):
        daemon, shim_srv, _ = shim
        import time

        # deprecated area-less variant, empty snapshot: adj:peerx is news
        t0 = time.monotonic()
        changed = _call_ok(
            shim_srv.port,
            "longPollKvStoreAdj",
            31,
            tb.encode_struct(self.ARGS, {"snapshot": {}}),
            tb.T_BOOL,
        )
        assert changed is True
        # area variant with a wrong-version snapshot: also immediate
        stale = {"adj:peerx": Value(99, "peerx", None, -1, 0)}
        changed = _call_ok(
            shim_srv.port,
            "longPollKvStoreAdjArea",
            32,
            tb.encode_struct(
                self.AREA_ARGS, {"area": "0", "snapshot": stale}
            ),
            tb.T_BOOL,
        )
        assert changed is True
        assert time.monotonic() - t0 < 1.0  # neither call waited out

    def test_current_snapshot_times_out_false(self, shim):
        daemon, shim_srv, _ = shim
        import threading
        import time

        snap = self._current_snapshot(daemon)
        assert snap  # the injected adj key is in it
        out = []
        th = threading.Thread(
            target=lambda: out.append(
                _call_ok(
                    shim_srv.port,
                    "longPollKvStoreAdjArea",
                    33,
                    tb.encode_struct(
                        self.AREA_ARGS, {"area": "0", "snapshot": snap}
                    ),
                    tb.T_BOOL,
                )
            )
        )
        t0 = time.monotonic()
        th.start()
        # a non-adj publication mid-poll must NOT resolve the poll
        time.sleep(0.2)
        daemon.kvstore.set_key_vals(
            "0", {"snoop:noise": Value(1, "lpd", b"x", -1, 0)}
        )
        th.join(10)
        assert not th.is_alive()
        assert out == [False]
        assert time.monotonic() - t0 >= 0.9  # waited out the full window

    def test_adj_version_bump_triggers_mid_poll(self, shim):
        daemon, shim_srv, adj_payload = shim
        import threading
        import time

        snap = self._current_snapshot(daemon)
        out = []
        th = threading.Thread(
            target=lambda: out.append(
                _call_ok(
                    shim_srv.port,
                    "longPollKvStoreAdj",
                    34,
                    tb.encode_struct(self.ARGS, {"snapshot": snap}),
                    tb.T_BOOL,
                )
            )
        )
        t0 = time.monotonic()
        th.start()
        time.sleep(0.2)
        daemon.kvstore.set_key_vals(
            "0", {"adj:peerx": Value(2, "peerx", adj_payload, -1, 0)}
        )
        th.join(10)
        assert not th.is_alive()
        assert out == [True]
        assert time.monotonic() - t0 < 1.0  # resolved before the timeout


class TestDaemonShimWiring:
    def test_daemon_starts_shim_from_config(self):
        """thrift_shim_port=-1 starts the interop listener with the
        daemon (ephemeral port) and tears it down with it."""
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from tests.test_system import make_config

        cfg = make_config("shimw", ctrl_port=0)
        cfg.thrift_shim_port = -1
        fabric = MockIoProvider()
        daemon = OpenrDaemon(
            cfg,
            io_provider=fabric.endpoint("shimw"),
            kvstore_transport=InProcessTransport().bind("shimw"),
        )
        daemon.start()
        try:
            assert daemon.thrift_shim is not None
            name, mtype, _s_, r = _thrift_call(
                daemon.thrift_shim.port, "getMyNodeName", 1, b"\x00"
            )
            assert mtype == tb.MSG_REPLY
            reply = tb.read_struct(
                r,
                tb.StructSpec(
                    "result", None, (tb.Field(0, "success", tb.T_STRING),)
                ),
            )
            assert reply["success"] == b"shimw"
        finally:
            daemon.stop()


class TestRouteStructRoundTrips:
    """Round-5 shim extension: Network.thrift route structs
    (IpPrefix/NextHopThrift/UnicastRoute/MplsRoute/RouteDatabase)."""

    def test_ip_prefix_golden(self):
        # IpPrefix{BinaryAddress{addr=4B v4}, prefixLength=24}: field 1
        # struct (inner: field 1 string 4 bytes), field 2 i16
        enc = tb.encode_struct(
            tb.UNICAST_ROUTE,
            UnicastRoute(dest="10.1.2.0/24"),
        )
        want = (
            b"\x0c\x00\x01"  # field 1 (dest) struct
            b"\x0c\x00\x01"  # IpPrefix field 1 (prefixAddress) struct
            b"\x0b\x00\x01\x00\x00\x00\x04\x0a\x01\x02\x00"  # addr
            b"\x00"  # end BinaryAddress
            b"\x06\x00\x02\x00\x18"  # field 2 (prefixLength) i16 = 24
            b"\x00"  # end IpPrefix
            b"\x0f\x00\x04\x0c\x00\x00\x00\x00"  # field 4 nextHops: empty
            b"\x00"  # end UnicastRoute
        )
        assert enc == want

    def test_unicast_route_round_trip_with_mpls_push(self):
        route = UnicastRoute(
            dest="fc00:1::/64",
            next_hops=[
                NextHop(
                    address="fe80::1",
                    if_name="eth0",
                    metric=20,
                    weight=0,
                    area="0",
                    neighbor_node_name="peer-1",
                    mpls_action=MplsAction(
                        action=MplsActionCode.PUSH,
                        push_labels=(100, 200),
                    ),
                ),
                NextHop(address="fe80::2", metric=30),
            ],
        )
        back = tb.decode_struct(
            tb.UNICAST_ROUTE, tb.encode_struct(tb.UNICAST_ROUTE, route)
        )
        assert back == route

    def test_mpls_route_round_trip_swap_and_php(self):
        for action in (
            MplsAction(action=MplsActionCode.SWAP, swap_label=77),
            MplsAction(action=MplsActionCode.PHP),
        ):
            route = MplsRoute(
                top_label=1201,
                next_hops=[
                    NextHop(
                        address="fe80::9", if_name="po1", mpls_action=action
                    )
                ],
            )
            back = tb.decode_struct(
                tb.MPLS_ROUTE, tb.encode_struct(tb.MPLS_ROUTE, route)
            )
            assert back == route

    def test_route_database_round_trip(self):
        db = RouteDatabase(
            this_node_name="nodeA",
            unicast_routes=[
                UnicastRoute(
                    dest="192.168.0.0/16",
                    next_hops=[NextHop(address="10.0.0.1", metric=1)],
                )
            ],
            mpls_routes=[
                MplsRoute(
                    top_label=5,
                    next_hops=[NextHop(address="10.0.0.2")],
                )
            ],
        )
        back = tb.decode_struct(
            tb.ROUTE_DATABASE, tb.encode_struct(tb.ROUTE_DATABASE, db)
        )
        assert back == db


class TestShimRouteExchange:
    """The Decision/Fib query surface over the wire: a converged
    two-daemon pair answers stock-shaped thrift-binary route calls."""

    @pytest.fixture
    def pair(self):
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import LinkEvent, PrefixEntry, PrefixType
        from tests.test_system import FIB_CLIENT, make_config, wait_for

        fabric = MockIoProvider()
        kv = InProcessTransport()
        daemons = []
        for name in ("rshim-0", "rshim-1"):
            cfg = make_config(name, ctrl_port=0)
            if name == "rshim-0":
                cfg.thrift_shim_port = -1
            addr = f"fe80::{name}"
            d = OpenrDaemon(
                cfg,
                io_provider=fabric.endpoint(name),
                kvstore_transport=kv.bind(addr),
                spark_v6_addr=addr,
            )
            kv.register(addr, d.kvstore)
            daemons.append(d)
        for d in daemons:
            d.start()
        fabric.connect("rshim-0", "veth0", "rshim-1", "veth1")
        daemons[0].netlink_events_queue.push(LinkEvent("veth0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("veth1", 1, True))
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc01::/64")]
        )
        assert wait_for(
            lambda: "fc01::/64"
            in daemons[0].fib_agent.unicast.get(FIB_CLIENT, {}),
            timeout=30,
        )
        yield daemons
        for d in daemons:
            d.stop()

    def test_get_route_db_over_the_wire(self, pair):
        port = pair[0].thrift_shim.port
        db = _call_ok(
            port, "getRouteDb", 7, b"\x00", ("struct", tb.ROUTE_DATABASE)
        )
        assert db.this_node_name == "rshim-0"
        dests = {r.dest for r in db.unicast_routes}
        assert "fc01::/64" in dests
        route = next(r for r in db.unicast_routes if r.dest == "fc01::/64")
        assert route.next_hops[0].neighbor_node_name == "rshim-1"
        # node labels -> MPLS routes present with real actions
        assert any(m.next_hops for m in db.mpls_routes) or not db.mpls_routes

    def test_get_route_db_computed_any_node(self, pair):
        port = pair[0].thrift_shim.port
        args = tb.encode_struct(
            tb.StructSpec(
                "node_args",
                None,
                (tb.Field(1, "node_name", tb.T_STRING),),
            ),
            {"node_name": "rshim-1"},
        )
        db = _call_ok(
            port,
            "getRouteDbComputed",
            8,
            args,
            ("struct", tb.ROUTE_DATABASE),
        )
        assert db.this_node_name == "rshim-1"
        # rshim-1 advertises fc01::/64 itself: no unicast route to it,
        # but its own perspective must still compute (possibly empty)
        assert all(r.dest != "fc01::/64" for r in db.unicast_routes)

    def test_get_unicast_routes_filtered(self, pair):
        port = pair[0].thrift_shim.port
        args = tb.encode_struct(
            tb.StructSpec(
                "prefixes_args",
                None,
                (tb.Field(1, "prefixes", ("list", tb.T_STRING)),),
            ),
            {"prefixes": ["fc01::/64"]},
        )
        routes = _call_ok(
            port,
            "getUnicastRoutesFiltered",
            9,
            args,
            ("list", ("struct", tb.UNICAST_ROUTE)),
        )
        assert [r.dest for r in routes] == ["fc01::/64"]
        # and the unfiltered variant returns at least as much
        all_routes = _call_ok(
            port,
            "getUnicastRoutes",
            10,
            b"\x00",
            ("list", ("struct", tb.UNICAST_ROUTE)),
        )
        assert {r.dest for r in routes} <= {r.dest for r in all_routes}

    def test_get_unicast_routes_filtered_longest_prefix(self, pair):
        # Fib.cpp:268 semantics, not exact dict-key lookup: the filter
        # entries are NORMALIZED (non-canonical spellings hit), host
        # addresses return their COVERING route by longest-prefix
        # match, malformed entries match nothing, duplicates collapse
        port = pair[0].thrift_shim.port
        spec = tb.StructSpec(
            "prefixes_args",
            None,
            (tb.Field(1, "prefixes", ("list", tb.T_STRING)),),
        )
        queries = [
            "fc01:0:0:0::/64",  # non-canonical spelling of fc01::/64
            "fc01::1/128",  # host address inside the advertised /64
            "not-a-prefix",  # malformed: skipped, not an error
            "fc01::/64",  # duplicate of the first (normalized)
            "fc02::/64",  # no covering route
        ]
        routes = _call_ok(
            port,
            "getUnicastRoutesFiltered",
            21,
            tb.encode_struct(spec, {"prefixes": queries}),
            ("list", ("struct", tb.UNICAST_ROUTE)),
        )
        assert [r.dest for r in routes] == ["fc01::/64"]

    def test_get_counters_over_the_wire(self, pair):
        port = pair[0].thrift_shim.port
        counters = _call_ok(
            port,
            "getCounters",
            13,
            b"\x00",
            ("map", tb.T_STRING, tb.T_I64),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        )
        assert counters.get("decision.adj_db_update", 0) >= 1
        # regex variant filters server-side (fb303 getRegexCounters)
        args = tb.encode_struct(
            tb.StructSpec(
                "regex_args",
                None,
                (tb.Field(1, "regex", tb.T_STRING),),
            ),
            {"regex": "^decision\\."},
        )
        filtered = _call_ok(
            port,
            "getRegexCounters",
            14,
            args,
            ("map", tb.T_STRING, tb.T_I64),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        )
        assert filtered and all(
            k.startswith("decision.") for k in filtered
        )
        assert set(filtered) <= set(counters)

    def test_get_regex_counters_bounded(self, pair):
        # pathological client patterns must answer as thrift application
        # exceptions (shim.MAX_COUNTER_REGEX_LEN cap + guarded compile),
        # never pin or kill the shim event loop — and the connection
        # stays serviceable afterwards
        from openr_tpu.interop.shim import MAX_COUNTER_REGEX_LEN

        port = pair[0].thrift_shim.port
        spec = tb.StructSpec(
            "regex_args", None, (tb.Field(1, "regex", tb.T_STRING),)
        )

        def call(regex, seqid):
            return _thrift_call(
                port,
                "getRegexCounters",
                seqid,
                tb.encode_struct(spec, {"regex": regex}),
            )

        _, mtype, _, _ = call("(" * 50, 22)  # unbalanced: re.error
        assert mtype == tb.MSG_EXCEPTION
        _, mtype, _, _ = call("a" * (MAX_COUNTER_REGEX_LEN + 1), 23)
        assert mtype == tb.MSG_EXCEPTION
        # a sane pattern still answers on the same shim
        filtered = _call_ok(
            port,
            "getRegexCounters",
            24,
            tb.encode_struct(spec, {"regex": "^decision\\."}),
            ("map", tb.T_STRING, tb.T_I64),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        )
        assert filtered

    def test_get_mpls_routes_matches_fib(self, pair):
        port = pair[0].thrift_shim.port
        mpls = _call_ok(
            port,
            "getMplsRoutes",
            11,
            b"\x00",
            ("list", ("struct", tb.MPLS_ROUTE)),
        )
        _, fib_mpls = pair[0].fib.get_route_db()
        assert {m.top_label for m in mpls} == {
            m.top_label for m in fib_mpls
        }
        if mpls:
            one = mpls[0].top_label
            args = tb.encode_struct(
                tb.StructSpec(
                    "labels_args",
                    None,
                    (tb.Field(1, "labels", ("list", tb.T_I32)),),
                ),
                {"labels": [one]},
            )
            filtered = _call_ok(
                port,
                "getMplsRoutesFiltered",
                12,
                args,
                ("list", ("struct", tb.MPLS_ROUTE)),
            )
            assert [m.top_label for m in filtered] == [one]
