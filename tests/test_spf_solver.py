"""SpfSolver conformance tests.

Modeled on the reference's DecisionTest route-level assertions
(openr/decision/tests/DecisionTest.cpp): ECMP sets, KSP2 label stacks,
best-route selection, drained filtering, MPLS label routes, static overlays,
and route-delta computation.
"""

from __future__ import annotations

import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb
from openr_tpu.decision.spf_solver import (
    DeviceSpfBackend,
    SpfSolver,
    select_best_node_area,
    select_best_prefix_metrics,
)
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
    PrefixType,
    UnicastRoute,
)


def adj(me: str, other: str, metric: int = 10) -> Adjacency:
    return Adjacency(
        other_node_name=other,
        if_name=f"{me}/{other}",
        other_if_name=f"{other}/{me}",
        metric=metric,
        next_hop_v6=f"fe80::{other}",
        next_hop_v4=f"10.0.0.{other}",
    )


def build_link_state(
    adj_map: dict[str, list[Adjacency]],
    labels: dict[str, int] | None = None,
    overloaded: set[str] = frozenset(),
    area: str = "0",
) -> LinkState:
    ls = LinkState(area)
    for node, adjs in adj_map.items():
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=node,
                adjacencies=adjs,
                is_overloaded=node in overloaded,
                node_label=(labels or {}).get(node, 0),
                area=area,
            )
        )
    return ls


def square() -> LinkState:
    """1 -- 2
       |    |
       3 -- 4   all metric 10."""
    return build_link_state(
        {
            "1": [adj("1", "2"), adj("1", "3")],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1"), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        },
        labels={"1": 101, "2": 102, "3": 103, "4": 104},
    )


def prefix_state_with(
    *entries: tuple[str, str, PrefixEntry],
) -> PrefixState:
    ps = PrefixState()
    for node, area, entry in entries:
        ps.update_prefix(node, area, entry)
    return ps


PFX = "::1:0/112"


class TestEcmp:
    def test_single_advertiser_ecmp_paths(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("1")
        db = solver.build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # two equal-cost paths from 1 to 4: via 2 and via 3
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2", "3"}
        assert all(nh.metric == 20 for nh in route.nexthops)
        assert all(nh.mpls_action is None for nh in route.nexthops)

    def test_asymmetric_metric_single_path(self):
        adj_map = {
            "1": [adj("1", "2"), adj("1", "3", metric=50)],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1", metric=50), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        }
        ls = build_link_state(adj_map)
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2"}

    def test_anycast_two_advertisers(self):
        ls = square()
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("3", "0", PrefixEntry(prefix=PFX)),
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # both advertisers one hop away: ECMP across both neighbors
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2", "3"}
        assert all(nh.metric == 10 for nh in route.nexthops)

    def test_self_advertised_prefix_not_programmed(self):
        ls = square()
        ps = prefix_state_with(("1", "0", PrefixEntry(prefix=PFX)))
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        assert PFX not in db.unicast_routes

    def test_v4_disabled_drops_v4_prefix(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix="10.1.0.0/24")))
        db = SpfSolver("1", enable_v4=False).build_route_db({"0": ls}, ps)
        assert "10.1.0.0/24" not in db.unicast_routes
        db = SpfSolver("1", enable_v4=True).build_route_db({"0": ls}, ps)
        assert "10.1.0.0/24" in db.unicast_routes
        route = db.unicast_routes["10.1.0.0/24"]
        assert all(nh.address.startswith("10.0.0.") for nh in route.nexthops)


class TestBestRouteSelection:
    def test_metrics_ordering(self):
        entries = {
            ("a", "0"): PrefixEntry(
                prefix=PFX, metrics=PrefixMetrics(path_preference=1000)
            ),
            ("b", "0"): PrefixEntry(
                prefix=PFX, metrics=PrefixMetrics(path_preference=2000)
            ),
            ("c", "0"): PrefixEntry(
                prefix=PFX, metrics=PrefixMetrics(path_preference=2000)
            ),
        }
        assert select_best_prefix_metrics(entries) == {("b", "0"), ("c", "0")}

    def test_source_preference_then_distance(self):
        e = lambda sp, d: PrefixEntry(
            prefix=PFX,
            metrics=PrefixMetrics(source_preference=sp, distance=d),
        )
        entries = {
            ("a", "0"): e(100, 5),
            ("b", "0"): e(200, 9),
            ("c", "0"): e(200, 2),
        }
        assert select_best_prefix_metrics(entries) == {("c", "0")}

    def test_best_node_area_prefers_self(self):
        nas = {("b", "0"), ("a", "0"), ("me", "1")}
        assert select_best_node_area(nas, "me") == ("me", "1")
        assert select_best_node_area(nas, "zz") == ("a", "0")

    def test_best_route_selection_limits_ecmp(self):
        ls = square()
        ps = prefix_state_with(
            (
                "2",
                "0",
                PrefixEntry(
                    prefix=PFX, metrics=PrefixMetrics(path_preference=2000)
                ),
            ),
            (
                "3",
                "0",
                PrefixEntry(
                    prefix=PFX, metrics=PrefixMetrics(path_preference=1000)
                ),
            ),
        )
        db = SpfSolver("1", enable_best_route_selection=True).build_route_db(
            {"0": ls}, ps
        )
        route = db.unicast_routes[PFX]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2"}
        assert route.best_area == "0"
        assert route.best_prefix_entry.metrics.path_preference == 2000

    def test_drained_node_filtered(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "4")],
                "3": [adj("3", "1"), adj("3", "4")],
                "4": [adj("4", "2"), adj("4", "3")],
            },
            overloaded={"2"},
        )
        ps = prefix_state_with(
            ("2", "0", PrefixEntry(prefix=PFX)),
            ("4", "0", PrefixEntry(prefix=PFX)),
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # advertiser 2 is drained -> only 4 counts; 2 offers no transit so
        # the only path is via 3
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"3"}

    def test_all_drained_advertisers_kept(self):
        ls = build_link_state(
            {
                "1": [adj("1", "2")],
                "2": [adj("2", "1"), adj("2", "3")],
                "3": [adj("3", "2")],
            },
            overloaded={"3"},
        )
        ps = prefix_state_with(("3", "0", PrefixEntry(prefix=PFX)))
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        # sole advertiser drained: route still programmed (reference
        # maybeFilterDrainedNodes falls back to unfiltered set)
        assert PFX in db.unicast_routes

    def test_min_nexthop_requirement_drops_route(self):
        ls = square()
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX, min_nexthop=3))
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        assert PFX not in db.unicast_routes  # only 2 ECMP nexthops < 3
        ps = prefix_state_with(
            ("4", "0", PrefixEntry(prefix=PFX, min_nexthop=2))
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        assert PFX in db.unicast_routes


class TestSrMpls:
    def test_sp_ecmp_sr_mpls_pushes_node_label(self):
        ls = square()
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                ),
            )
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        # dst 4 is not a neighbor: push its node label on both paths
        for nh in route.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(104,)
            )

    def test_sr_mpls_no_push_to_neighbor(self):
        ls = square()
        ps = prefix_state_with(
            (
                "2",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                ),
            )
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2"}
        assert all(nh.mpls_action is None for nh in route.nexthops)

    def test_node_label_routes(self):
        ls = square()
        db = SpfSolver("1").build_route_db({"0": ls}, PrefixState())
        # own label: POP_AND_LOOKUP
        own = db.mpls_routes[101]
        (nh,) = own.nexthops
        assert nh.mpls_action.action == MplsActionCode.POP_AND_LOOKUP
        # neighbor label: PHP (pop at penultimate hop)
        r2 = db.mpls_routes[102]
        (nh2,) = [nh for nh in r2.nexthops]
        assert nh2.neighbor_node_name == "2"
        assert nh2.mpls_action.action == MplsActionCode.PHP
        # remote label: SWAP via both ECMP neighbors
        r4 = db.mpls_routes[104]
        assert {nh.neighbor_node_name for nh in r4.nexthops} == {"2", "3"}
        for nh in r4.nexthops:
            assert nh.mpls_action == MplsAction(
                MplsActionCode.SWAP, swap_label=104
            )

    def test_adjacency_label_routes(self):
        adj12 = adj("1", "2")
        adj12.adj_label = 50001
        ls = build_link_state(
            {
                "1": [adj12],
                "2": [adj("2", "1")],
            }
        )
        db = SpfSolver("1").build_route_db({"0": ls}, PrefixState())
        route = db.mpls_routes[50001]
        (nh,) = route.nexthops
        assert nh.neighbor_node_name == "2"
        assert nh.mpls_action.action == MplsActionCode.PHP

    def test_invalid_node_label_skipped(self):
        ls = build_link_state(
            {"1": [adj("1", "2")], "2": [adj("2", "1")]},
            labels={"1": 101, "2": 5},  # 5 < MPLS_LABEL_MIN
        )
        db = SpfSolver("1").build_route_db({"0": ls}, PrefixState())
        assert 5 not in db.mpls_routes


class TestKsp2:
    def test_two_edge_disjoint_paths_with_label_stacks(self):
        """Diamond: 1-2-4 and 1-3-4; KSP2 yields both paths."""
        ls = square()
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            )
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"2", "3"}
        for nh in route.nexthops:
            assert nh.metric == 20
            # intermediate hop's label removed for PHP; only dest label left
            assert nh.mpls_action == MplsAction(
                MplsActionCode.PUSH, push_labels=(104,)
            )

    def test_ksp2_longer_second_path(self):
        """1-2 and 1-3-2: second path is longer but edge-disjoint."""
        ls = build_link_state(
            {
                "1": [adj("1", "2"), adj("1", "3")],
                "2": [adj("2", "1"), adj("2", "3")],
                "3": [adj("3", "1"), adj("3", "2")],
            },
            labels={"1": 101, "2": 102, "3": 103},
        )
        ps = prefix_state_with(
            (
                "2",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            )
        )
        db = SpfSolver("1").build_route_db({"0": ls}, ps)
        route = db.unicast_routes[PFX]
        by_neighbor = {nh.neighbor_node_name: nh for nh in route.nexthops}
        assert set(by_neighbor) == {"2", "3"}
        assert by_neighbor["2"].metric == 10
        assert by_neighbor["2"].mpls_action is None  # direct, PHP'd away
        assert by_neighbor["3"].metric == 20
        assert by_neighbor["3"].mpls_action == MplsAction(
            MplsActionCode.PUSH, push_labels=(102,)
        )

    def test_ksp2_requires_sr_mpls(self):
        ls = square()
        ps = prefix_state_with(
            (
                "4",
                "0",
                PrefixEntry(
                    prefix=PFX,
                    forwarding_type=PrefixForwardingType.IP,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            )
        )
        solver = SpfSolver("1")
        db = solver.build_route_db({"0": ls}, ps)
        assert PFX not in db.unicast_routes
        assert solver.counters["decision.incompatible_forwarding_type"] == 1


class TestStaticRoutes:
    def test_static_unicast_overlay(self):
        ls = square()
        solver = SpfSolver("1")
        solver.update_static_unicast_routes(
            [UnicastRoute("::2:0/112", [NextHop(address="fe80::9")])], []
        )
        db = solver.build_route_db({"0": ls}, PrefixState())
        assert "::2:0/112" in db.unicast_routes
        # computed route wins over static for the same prefix
        solver.update_static_unicast_routes(
            [UnicastRoute(PFX, [NextHop(address="fe80::9")])], []
        )
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        db = solver.build_route_db({"0": ls}, ps)
        assert {nh.neighbor_node_name for nh in db.unicast_routes[PFX].nexthops} == {
            "2",
            "3",
        }
        solver.update_static_unicast_routes([], ["::2:0/112"])
        db = solver.build_route_db({"0": ls}, PrefixState())
        assert "::2:0/112" not in db.unicast_routes

    def test_static_mpls(self):
        ls = square()
        solver = SpfSolver("1")
        solver.update_static_mpls_routes(
            [MplsRoute(top_label=60000, next_hops=[NextHop(address="fe80::9")])],
            [],
        )
        db = solver.build_route_db({"0": ls}, PrefixState())
        assert 60000 in db.mpls_routes
        solver.update_static_mpls_routes([], [60000])
        db = solver.build_route_db({"0": ls}, PrefixState())
        assert 60000 not in db.mpls_routes


class TestRouteDelta:
    def test_calculate_update(self):
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("1")
        db1 = solver.build_route_db({"0": ls}, ps)

        # no change -> empty delta
        db2 = solver.build_route_db({"0": ls}, ps)
        assert db1.calculate_update(db2).empty()

        # withdraw prefix -> delete
        delta = db1.calculate_update(solver.build_route_db({"0": ls}, PrefixState()))
        assert delta.unicast_routes_to_delete == [PFX]

        # metric change -> update
        adj_map = {
            "1": [adj("1", "2"), adj("1", "3", metric=50)],
            "2": [adj("2", "1"), adj("2", "4")],
            "3": [adj("3", "1", metric=50), adj("3", "4")],
            "4": [adj("4", "2"), adj("4", "3")],
        }
        ls2 = build_link_state(adj_map, labels={"1": 101, "2": 102, "3": 103, "4": 104})
        db3 = solver.build_route_db({"0": ls2}, ps)
        delta = db1.calculate_update(db3)
        assert PFX in delta.unicast_routes_to_update
        applied = DecisionRouteDb(
            unicast_routes=dict(db1.unicast_routes),
            mpls_routes=dict(db1.mpls_routes),
        )
        applied.update(delta)
        assert applied.unicast_routes == db3.unicast_routes
        assert applied.mpls_routes == db3.mpls_routes

    def test_build_route_db_unknown_node(self):
        ls = square()
        assert SpfSolver("nope").build_route_db({"0": ls}, PrefixState()) is None

    def test_source_parameterized(self):
        """getDecisionRouteDb can compute any node's routes
        (reference: OpenrCtrlHandler -> buildRouteDb(targetNode))."""
        ls = square()
        ps = prefix_state_with(("4", "0", PrefixEntry(prefix=PFX)))
        solver = SpfSolver("1")
        db_from_2 = solver.build_route_db({"0": ls}, ps, my_node_name="2")
        route = db_from_2.unicast_routes[PFX]
        assert {nh.neighbor_node_name for nh in route.nexthops} == {"4"}
        assert solver.my_node_name == "1"  # restored


class TestDispatchPolicy:
    """The measured batch-size dispatch policy (round 4): single
    questions go to the host memo, batches to the device — see
    DeviceSpfBackend docstring for the numbers behind the defaults."""

    @staticmethod
    def _state(n_side=16):
        from openr_tpu.utils.topo import grid_topology

        dbs = grid_topology(n_side)
        ls = LinkState()
        for db in dbs:
            ls.update_adjacency_database(db)
        return dbs, ls

    def test_single_question_served_by_host(self):
        dbs, ls = self._state()
        be = DeviceSpfBackend()  # shipped defaults
        res = be.get_spf_result(ls, dbs[0].this_node_name)
        host = ls.run_spf(dbs[0].this_node_name)
        assert {n: r.metric for n, r in res.items()} == {
            n: r.metric for n, r in host.items()
        }
        # no device mirror was built for a single-question flow
        assert len(be._mirrors) == 0

    def test_batch_prefetch_uses_device_and_serves_singles(self):
        dbs, ls = self._state()
        be = DeviceSpfBackend()
        sources = [d.this_node_name for d in dbs[:64]]
        be.prefetch(ls, sources)
        assert len(be._mirrors) == 1  # device mirror built
        # a later single question hits the batch-populated cache
        res = be.get_spf_result(ls, sources[3])
        host = ls.run_spf(sources[3])
        assert {n: r.metric for n, r in res.items()} == {
            n: r.metric for n, r in host.items()
        }

    def test_small_batch_prefetch_falls_back_to_host(self):
        dbs, ls = self._state()
        be = DeviceSpfBackend()
        be.prefetch(ls, [d.this_node_name for d in dbs[:4]])
        assert len(be._mirrors) == 0  # below min_device_sources
        # but the cache still serves the host-computed results
        res = be.get_spf_result(ls, dbs[1].this_node_name)
        host = ls.run_spf(dbs[1].this_node_name)
        assert {n: r.metric for n, r in res.items()} == {
            n: r.metric for n, r in host.items()
        }

    def test_tiny_topology_always_host(self):
        dbs, ls = self._state(4)  # 16 nodes < min_device_nodes
        be = DeviceSpfBackend()
        be.prefetch(ls, [d.this_node_name for d in dbs])
        assert len(be._mirrors) == 0

    def test_forced_device_overrides_policy(self):
        dbs, ls = self._state()
        be = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        res = be.get_spf_result(ls, dbs[0].this_node_name)
        assert len(be._mirrors) == 1
        host = ls.run_spf(dbs[0].this_node_name)
        assert {n: r.metric for n, r in res.items()} == {
            n: r.metric for n, r in host.items()
        }


class TestDeviceBackendParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_topology_same_routes(self, seed):
        from openr_tpu.utils.topo import random_topology

        dbs = random_topology(24, 30, seed=seed)
        ls = LinkState()
        for db in dbs:
            ls.update_adjacency_database(db)
        ps = PrefixState()
        for i, node in enumerate(["n3", "n7", "n11"]):
            ps.update_prefix(node, "0", PrefixEntry(prefix=f"::{i+1}:0/112"))
        ps.update_prefix("n5", "0", PrefixEntry(prefix="::a:0/112"))
        ps.update_prefix("n9", "0", PrefixEntry(prefix="::a:0/112"))

        host = SpfSolver("n0").build_route_db({"0": ls}, ps)
        dev = SpfSolver(
            "n0", spf_backend=DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
        ).build_route_db({"0": ls}, ps)
        assert host.unicast_routes == dev.unicast_routes
        assert host.mpls_routes == dev.mpls_routes
