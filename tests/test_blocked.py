"""Blocked min-plus APSP (openr_tpu/parallel/blocked.py) on the virtual
8-device CPU mesh — the node-axis sharding rung.

Covers: per-phase unit parity against a numpy reference, full-closure
parity against scipy's host APSP and against the masked-FW drain oracle,
bit-exact agreement with the unblocked fused product (reduced_all_sources)
on ring / grid / fattree / wan-shaped topologies including the 1-device
degenerate mesh and odd-N padding, the fleet dispatch rung (threshold +
OPENR_NODE_SHARD engagement, graceful fallback on mesh-shape mismatch,
chaos partition mid-run), the make_mesh ValueError contract, the
software-pipelined loop (pipelined-vs-bulk bit-exactness on every
family, chaos fault mid-pipeline demoting to bulk, pipeline_* counter
semantics), and the compile-only async-span evidence that the
lookahead panel all-gathers legally bracket the outer-update while
(parallel.hlo_async on the lowered scheduled module).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openr_tpu.decision.csr import CsrTopology
from openr_tpu.decision.fleet import FleetViewCache, _reverse_runner, _row_i32
from openr_tpu.decision.link_state import LinkState
from openr_tpu.device.engine import DeviceResidencyEngine
from openr_tpu.ops import allsources as asrc
from openr_tpu.parallel import blocked as blk
from openr_tpu.utils.topo import (
    fat_tree_topology,
    grid_topology,
    ring_topology,
)

INF = 1 << 30


@pytest.fixture(scope="module")
def eight_cpu_devices():
    devices = jax.devices("cpu")
    if len(devices) < 8:
        pytest.skip("needs xla_force_host_platform_device_count=8")
    return devices[:8]


def _overload(dbs, name):
    """Mark one node drained (is_overloaded) in a topo-builder output."""
    for db in dbs:
        if db.this_node_name == name:
            db.is_overloaded = True
            return dbs
    raise AssertionError(f"no node {name!r} in fixture")


def _csr(dbs) -> CsrTopology:
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    return CsrTopology.from_link_state(ls)


def _dense(csr) -> np.ndarray:
    """[N, N] int64 usable-edge adjacency (min over parallel edges)."""
    n = int(csr.n_nodes)
    d = np.full((n, n), INF, dtype=np.int64)
    np.fill_diagonal(d, 0)
    e = int(csr.n_edges)
    src = np.asarray(csr.edge_src[:e])
    dst = np.asarray(csr.edge_dst[:e])
    met = np.asarray(csr.edge_metric[:e], dtype=np.int64)
    up = np.asarray(csr.edge_up[:e], dtype=bool)
    for s, t, w, u in zip(src, dst, met, up):
        if u and 0 <= s < n and 0 <= t < n and s != t:
            d[s, t] = min(d[s, t], w)
    return d


def _masked_fw(d: np.ndarray, ov: np.ndarray) -> np.ndarray:
    """Host drain oracle: FW with overloaded nodes excluded as
    intermediates (endpoints stay valid) — the relax-kernel rule
    'blocked as transit unless its distance is 0' for positive metrics."""
    d = d.copy()
    for k in range(d.shape[0]):
        if ov[k]:
            continue
        d = np.minimum(d, np.minimum(d[:, k : k + 1] + d[k : k + 1, :], INF))
    return d


def _out_ell(topo):
    return asrc.build_out_ell(
        topo.edge_src,
        topo.edge_dst,
        int(topo.n_edges),
        int(topo.n_nodes),
        out_slot=getattr(topo, "out_slot", None),
    )


def _blocked_full(csr, mesh, tile) -> np.ndarray:
    """[N, N] int64 closure through the engine's staging + kernels."""
    eng = blk.BlockedApspEngine(tile=tile, mesh=mesh)
    n = int(csr.n_nodes)
    dist, _, ok = eng.fleet_product(
        csr, np.arange(n, dtype=np.int32), _out_ell(csr)
    )
    assert ok
    return np.asarray(jax.device_get(dist)).astype(np.int64)


def _fused_product(topo, dest_ids):
    """(dist [N, P] int32-normalized, bitmap [N, P, W]) via the unblocked
    dest-sharded fused product — the bit-exact reference the rung must
    match.  `topo` is a CsrTopology or a benchmarks.synthetic.Topology
    (same array contract)."""
    from benchmarks import synthetic

    if isinstance(topo, CsrTopology):
        runner = _reverse_runner(topo)
    else:
        runner = synthetic.reversed_topology(topo).runner
    out = _out_ell(topo)
    maps = (
        asrc.build_epilogue_maps(runner.bg, out)
        if runner.bg is not None
        else None
    )
    dist, bitmap, ok = asrc.reduced_all_sources(
        np.asarray(dest_ids, dtype=np.int32),
        runner,
        out,
        topo.edge_metric,
        topo.edge_up,
        topo.node_overloaded,
        maps=maps,
    )
    assert ok
    n = int(topo.n_nodes)
    dist = _row_i32(np.asarray(jax.device_get(dist)))[:n]
    bitmap = np.asarray(jax.device_get(bitmap))[:n]
    return dist, bitmap


def _blocked_product(topo, dest_ids, mesh, tile=None):
    eng = blk.BlockedApspEngine(tile=tile, mesh=mesh)
    dist, bitmap, ok = eng.fleet_product(
        topo, np.asarray(dest_ids, dtype=np.int32), _out_ell(topo)
    )
    assert ok
    return (
        np.asarray(jax.device_get(dist)),
        np.asarray(jax.device_get(bitmap)),
        eng,
    )


class TestMeshValidation:
    def test_make_mesh_indivisible_raises_valueerror(self, eight_cpu_devices):
        from openr_tpu.parallel.mesh import make_mesh

        with pytest.raises(ValueError, match=r"8 devices.*batch axis of\s*3"):
            make_mesh(eight_cpu_devices, batch_axis=3)
        with pytest.raises(ValueError):
            make_mesh(eight_cpu_devices, batch_axis=0)
        # divisible request still builds
        mesh = make_mesh(eight_cpu_devices, batch_axis=4)
        assert dict(mesh.shape) == {"batch": 4, "node": 2}

    def test_make_blocked_mesh_shapes_and_errors(self, eight_cpu_devices):
        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        assert dict(mesh.shape) == {"batch": 1, "row": 2, "col": 4}
        mesh2 = blk.make_blocked_mesh(eight_cpu_devices, batch=2)
        assert dict(mesh2.shape) == {"batch": 2, "row": 2, "col": 2}
        with pytest.raises(ValueError, match=r"rows=7 x cols=3 != 8"):
            blk.make_blocked_mesh(eight_cpu_devices, rows=7, cols=3)
        with pytest.raises(ValueError, match=r"batch axis\s*of 3"):
            blk.make_blocked_mesh(eight_cpu_devices, batch=3)
        with pytest.raises(ValueError, match=r"cols=5"):
            blk.make_blocked_mesh(eight_cpu_devices, cols=5)

    def test_tile_must_divide_by_mesh_lanes(self, eight_cpu_devices):
        eng = blk.BlockedApspEngine(
            tile=6, mesh=blk.make_blocked_mesh(eight_cpu_devices)
        )
        with pytest.raises(ValueError, match=r"lcm\(rows=2, cols=4\)"):
            eng.tile_for(64, 2, 4)


class TestPhaseUnits:
    """Each phase kernel against a literal numpy transcription of one
    blocked-FW round, drain mask included."""

    def test_three_phases_match_numpy_round(self, eight_cpu_devices):
        rng = np.random.default_rng(5)
        t, b, k = 3, 4, 1
        n = t * b
        d = rng.integers(1, 60, size=(n, n)).astype(np.int64)
        d[rng.random((n, n)) < 0.3] = INF
        np.fill_diagonal(d, 0)
        ov = rng.random(n) < 0.2
        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        dist4 = jnp.asarray(d.astype(np.uint32).reshape(1, t, b, t, b))
        ovd = jnp.asarray(ov)
        kk = jnp.int32(k)
        sl = slice(k * b, (k + 1) * b)

        # phase 1: masked closure of the diagonal tile
        diag = d[sl, sl].copy()
        for m in range(b):
            if ov[k * b + m]:
                continue
            diag = np.minimum(
                diag, np.minimum(diag[:, m : m + 1] + diag[m : m + 1, :], INF)
            )
        closed = blk.blocked_diag(dist4, ovd, kk, mesh=mesh)
        got = np.asarray(jax.device_get(closed)).astype(np.int64)[0]
        assert np.array_equal(got, diag)

        # phase 2: panel updates through the closed tile (contractions
        # read the ORIGINAL panels — `closed` is transitively closed, so
        # one application suffices)
        row = d[sl, :].copy()
        col = d[:, sl].copy()
        row_ref, col_ref = row.copy(), col.copy()
        for m in range(b):
            if ov[k * b + m]:
                continue
            row_ref = np.minimum(
                row_ref,
                np.minimum(diag[:, m : m + 1] + row[m : m + 1, :], INF),
            )
            col_ref = np.minimum(
                col_ref,
                np.minimum(col[:, m : m + 1] + diag[m : m + 1, :], INF),
            )
        row_p, col_p = blk.blocked_panels(dist4, closed, ovd, kk, mesh=mesh)
        got_row = (
            np.asarray(jax.device_get(row_p)).astype(np.int64).reshape(b, n)
        )
        got_col = (
            np.asarray(jax.device_get(col_p)).astype(np.int64).reshape(n, b)
        )
        assert np.array_equal(got_row, row_ref)
        assert np.array_equal(got_col, col_ref)

        # phase 3: panel write-back + masked rank-B outer update
        ref = d.copy()
        ref[sl, :] = row_ref
        ref[:, sl] = col_ref
        out = ref.copy()
        for m in range(b):
            if ov[k * b + m]:
                continue
            g = k * b + m
            out = np.minimum(
                out, np.minimum(ref[:, g : g + 1] + ref[g : g + 1, :], INF)
            )
        dist_new = blk.blocked_outer(dist4, row_p, col_p, ovd, kk, mesh=mesh)
        got_d = (
            np.asarray(jax.device_get(dist_new)).astype(np.int64).reshape(n, n)
        )
        assert np.array_equal(got_d, out)


class TestClosureParity:
    """Full blocked closure vs scipy's host APSP and the drain oracle."""

    def test_seeded_random_graph_matches_scipy(self, eight_cpu_devices):
        import scipy.sparse as sp
        import scipy.sparse.csgraph as csg

        rng = np.random.default_rng(0)
        n = 23  # odd: exercises the padding path (tile 4 -> Np = 24)
        mask = rng.random((n, n)) < 0.25
        np.fill_diagonal(mask, False)
        src, dst = np.nonzero(mask)
        met = rng.integers(1, 50, size=len(src)).astype(np.int32)
        eng = blk.BlockedApspEngine(
            tile=4, mesh=blk.make_blocked_mesh(eight_cpu_devices)
        )
        n_pad = 24
        d0 = eng.dense_dist0(
            n, n_pad, src, dst, met, np.ones(len(src), bool), len(src)
        )
        dist, b = eng.run_apsp(d0[None], np.zeros(n_pad, bool))
        ids = np.arange(n, dtype=np.int32)
        got = np.asarray(
            jax.device_get(
                blk.blocked_extract(
                    dist, ids // b, ids % b, n=n, mesh=eng.mesh()
                )
            )
        ).astype(np.int64)
        g = sp.csr_matrix((met.astype(np.float64), (src, dst)), shape=(n, n))
        ref = csg.shortest_path(g, method="D", directed=True)
        ref = np.where(np.isinf(ref), INF, ref).astype(np.int64)
        assert np.array_equal(got, ref)

    @pytest.mark.parametrize(
        "dbs_fn",
        [
            lambda: ring_topology(17),  # odd N again, via the link state
            lambda: grid_topology(4),
            lambda: fat_tree_topology(2),
        ],
        ids=["ring17", "grid4x4", "fattree"],
    )
    def test_topologies_match_host_oracle(self, eight_cpu_devices, dbs_fn):
        csr = _csr(dbs_fn())
        got = _blocked_full(
            csr, blk.make_blocked_mesh(eight_cpu_devices), tile=4
        )
        n = int(csr.n_nodes)
        ov = np.asarray(csr.node_overloaded[:n], dtype=bool)
        ref = _masked_fw(_dense(csr), ov)
        assert np.array_equal(got, ref)

    def test_drain_semantics_match_oracle(self, eight_cpu_devices):
        """An overloaded node drops out as an intermediate but stays a
        valid endpoint — the grid center going into drain must reroute
        every through-path and keep its own rows/columns finite."""
        csr = _csr(_overload(grid_topology(4), "node-1-1"))
        n = int(csr.n_nodes)
        ov = np.asarray(csr.node_overloaded[:n], dtype=bool)
        assert ov.any(), "fixture lost its overloaded node"
        got = _blocked_full(
            csr, blk.make_blocked_mesh(eight_cpu_devices), tile=4
        )
        ref = _masked_fw(_dense(csr), ov)
        assert np.array_equal(got, ref)
        i = int(np.nonzero(ov)[0][0])
        assert got[i, i] == 0 and (got[i] < INF).sum() > 1


class TestFusedProductParity:
    """Bit-exact agreement with the unblocked fused product (dist after
    the int32 normalization, bitmap verbatim), including the 1-device
    degenerate mesh."""

    @pytest.mark.parametrize(
        "dbs_fn",
        [
            lambda: ring_topology(17),
            lambda: grid_topology(4),
            lambda: fat_tree_topology(2),
            lambda: _overload(grid_topology(4), "node-1-1"),
        ],
        ids=["ring17", "grid4x4", "fattree", "grid-drained"],
    )
    def test_matches_fused_product(self, eight_cpu_devices, dbs_fn):
        csr = _csr(dbs_fn())
        n = int(csr.n_nodes)
        dests = np.asarray(sorted({0, n // 3, n - 1}), dtype=np.int32)
        ref_dist, ref_bitmap = _fused_product(csr, dests)
        got_dist, got_bitmap, _ = _blocked_product(
            csr, dests, blk.make_blocked_mesh(eight_cpu_devices)
        )
        assert np.array_equal(got_dist, ref_dist)
        assert np.array_equal(got_bitmap, ref_bitmap)

    def test_wan_shaped_and_degenerate_mesh(self, eight_cpu_devices):
        """wan-shaped (ring + chords) topology from benchmarks.synthetic:
        the 8-device blocked product, the 1-device degenerate mesh and
        the fused product must all agree bit-exactly."""
        from benchmarks import synthetic

        topo = synthetic.wan(96, chords=2, seed=3)
        rng = np.random.default_rng(4)
        dests = np.sort(
            rng.choice(topo.n_nodes, size=8, replace=False).astype(np.int32)
        )
        ref_dist, ref_bitmap = _fused_product(topo, dests)
        d8, b8, _ = _blocked_product(
            topo, dests, blk.make_blocked_mesh(eight_cpu_devices)
        )
        d1, b1, _ = _blocked_product(
            topo, dests, blk.make_blocked_mesh(eight_cpu_devices[:1])
        )
        assert np.array_equal(d8, ref_dist)
        assert np.array_equal(b8, ref_bitmap)
        assert np.array_equal(d1, d8)
        assert np.array_equal(b1, b8)

    def test_batch_axis_composes(self, eight_cpu_devices):
        """S=2 identical variants over a 2x2x2 mesh: the batch axis must
        stay independent — both slices equal the host closure."""
        csr = _csr(ring_topology(12))
        n = int(csr.n_nodes)
        eng = blk.BlockedApspEngine(
            tile=4, mesh=blk.make_blocked_mesh(eight_cpu_devices, batch=2)
        )
        d0 = eng.dense_dist0(
            n,
            n,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            int(csr.n_edges),
        )
        dist, _ = eng.run_apsp(np.stack([d0, d0]), np.zeros(n, bool))
        full = np.asarray(jax.device_get(dist)).astype(np.int64)
        flat0 = full[0].reshape(n, n)
        flat1 = full[1].reshape(n, n)
        ref = _masked_fw(_dense(csr), np.zeros(n, bool))
        assert np.array_equal(flat0, ref)
        assert np.array_equal(flat0, flat1)


class TestDispatchRung:
    """fleet.py / DeviceResidencyEngine select the blocked rung by
    threshold or OPENR_NODE_SHARD, fall back gracefully, and keep the
    mesh.blocked.* registry pre-seeded."""

    def _ls(self):
        ls = LinkState()
        for db in grid_topology(4):
            ls.update_adjacency_database(db)
        return ls

    def test_counters_preseeded_before_first_dispatch(self):
        eng = DeviceResidencyEngine()
        counters = eng.blocked.get_counters()
        assert set(blk.BLOCKED_COUNTER_KEYS) <= set(counters)
        assert all(v == 0 for v in counters.values())

    def test_threshold_and_env_engagement(self, monkeypatch):
        monkeypatch.delenv("OPENR_NODE_SHARD", raising=False)
        eng = DeviceResidencyEngine()
        assert not eng.blocked.should_engage(64)  # default ceiling 2^15
        eng.blocked.node_shard_threshold = 0
        assert eng.blocked.should_engage(64)
        monkeypatch.setenv("OPENR_NODE_SHARD", "0")
        assert not eng.blocked.should_engage(64)  # forced off
        monkeypatch.setenv("OPENR_NODE_SHARD", "1")
        eng.blocked.node_shard_threshold = 1 << 15
        assert eng.blocked.should_engage(64)  # forced on

    def test_rung_serves_view_and_matches_fused(self, monkeypatch):
        monkeypatch.delenv("OPENR_NODE_SHARD", raising=False)
        monkeypatch.delenv("OPENR_BLOCKED_MESH", raising=False)
        ls = self._ls()
        nodes = sorted(ls.node_names)
        dests = [nodes[0], nodes[5], nodes[-1]]
        engine = DeviceResidencyEngine()
        engine.blocked.node_shard_threshold = 0
        vb = FleetViewCache().view(ls, dests, engine=engine)
        assert vb.converged and vb.node_sharded
        assert engine.blocked.counters["mesh.blocked.products"] == 1
        assert engine.blocked.counters["mesh.blocked.rounds"] > 0
        assert engine.blocked.counters["mesh.blocked.fallbacks"] == 0
        vf = FleetViewCache().view(self._ls(), dests)
        assert vf.converged and not vf.node_sharded
        for node in nodes:
            assert np.array_equal(vb._row(node), vf._row(node))
        assert np.array_equal(
            np.asarray(jax.device_get(vb._bitmap_dev)),
            np.asarray(jax.device_get(vf._bitmap_dev)),
        )

    def test_mesh_mismatch_falls_back_gracefully(self, monkeypatch):
        monkeypatch.delenv("OPENR_NODE_SHARD", raising=False)
        monkeypatch.setenv("OPENR_BLOCKED_MESH", "7x3")  # != 8 devices
        ls = self._ls()
        nodes = sorted(ls.node_names)
        dests = [nodes[0], nodes[-1]]
        engine = DeviceResidencyEngine()
        engine.blocked.node_shard_threshold = 0
        view = FleetViewCache().view(ls, dests, engine=engine)
        assert view.converged and not view.node_sharded
        assert engine.blocked.counters["mesh.blocked.fallbacks"] == 1
        monkeypatch.delenv("OPENR_BLOCKED_MESH")
        vf = FleetViewCache().view(self._ls(), dests)
        for node in nodes:
            assert np.array_equal(view._row(node), vf._row(node))

    def test_chaos_partition_mid_run_falls_back(self, monkeypatch):
        """Partition-during-blocked-run seam: a chaos fault injected at
        the per-round gate (engine:blocked_round) aborts the blocked
        closure mid-flight; the fleet rung must absorb it — fallback
        counter bumped, view served bit-exactly by the fused product."""
        from types import SimpleNamespace

        from openr_tpu.chaos.chaos import ChaosSpfBackend

        monkeypatch.delenv("OPENR_NODE_SHARD", raising=False)
        monkeypatch.delenv("OPENR_BLOCKED_MESH", raising=False)
        ls = self._ls()
        nodes = sorted(ls.node_names)
        dests = [nodes[0], nodes[-1]]
        engine = DeviceResidencyEngine()
        engine.blocked.node_shard_threshold = 0
        chaos = ChaosSpfBackend(
            SimpleNamespace(engine=engine),
            seed=7,
            fail_prob=1.0,
            fail_ops={"engine:blocked_round"},
        )
        view = FleetViewCache().view(ls, dests, engine=engine)
        assert view.converged and not view.node_sharded
        assert engine.blocked.counters["mesh.blocked.fallbacks"] == 1
        spf_stream = chaos.log.streams().get("spf", [])
        assert any("engine:blocked_round:fail" in e for e in spf_stream)
        chaos.disarm()
        vf = FleetViewCache().view(self._ls(), dests)
        for node in nodes:
            assert np.array_equal(view._row(node), vf._row(node))


def _blocked_product_mode(topo, dest_ids, mesh, tile, pipeline_mode):
    """_blocked_product with the pipeline override pinned on the engine
    (the same no-env-leak discipline the program auditor uses)."""
    eng = blk.BlockedApspEngine(tile=tile, mesh=mesh)
    eng.pipeline_mode = pipeline_mode
    dist, bitmap, ok = eng.fleet_product(
        topo, np.asarray(dest_ids, dtype=np.int32), _out_ell(topo)
    )
    assert ok
    return (
        np.asarray(jax.device_get(dist)),
        np.asarray(jax.device_get(bitmap)),
        eng,
    )


class TestPipelinedParity:
    """The software-pipelined loop (auto-on default for multi-round
    closures) against the bulk-synchronous loop: bit-exact on every
    topology family, correct pipeline_* counter semantics, 1-device
    degenerate mesh parity, chaos fault mid-pipeline demoting to bulk
    with `mesh.blocked.pipeline_fallbacks` accounted."""

    @pytest.mark.parametrize(
        "dbs_fn",
        [
            lambda: ring_topology(17),  # odd N: drags the padding tail
            lambda: grid_topology(4),
            lambda: fat_tree_topology(2),
            lambda: _overload(grid_topology(4), "node-1-1"),
        ],
        ids=["ring17", "grid4x4", "fattree", "grid-drained"],
    )
    def test_pipelined_matches_bulk(self, eight_cpu_devices, dbs_fn):
        csr = _csr(dbs_fn())
        n = int(csr.n_nodes)
        dests = np.asarray(sorted({0, n // 3, n - 1}), dtype=np.int32)
        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        # tile 4 forces a genuinely multi-round closure
        d_bulk, b_bulk, e_bulk = _blocked_product_mode(
            csr, dests, mesh, 4, "0"
        )
        d_pipe, b_pipe, e_pipe = _blocked_product_mode(
            csr, dests, mesh, 4, "1"
        )
        assert np.array_equal(d_pipe, d_bulk)
        assert np.array_equal(b_pipe, b_bulk)
        t = e_pipe.counters["mesh.blocked.rounds"]
        assert t >= 2
        assert e_pipe.counters["mesh.blocked.pipeline_prefetch_issues"] == t - 1
        assert (
            e_pipe.counters["mesh.blocked.pipeline_rounds_overlapped"] == t - 1
        )
        assert e_pipe.counters["mesh.blocked.pipeline_overlap_frac_est"] > 0
        assert e_pipe.counters["mesh.blocked.pipeline_fallbacks"] == 0
        # the bulk engine never touches the pipeline family
        for key in blk.BLOCKED_COUNTER_KEYS:
            if "pipeline" in key:
                assert e_bulk.counters[key] == 0, key

    def test_wan_and_degenerate_mesh_parity(self, eight_cpu_devices):
        """wan-shaped family plus the 1-device degenerate mesh: the
        pipelined prefetch on one device is pure compute reordering —
        still bit-exact, and the overlap counters must say so."""
        from benchmarks import synthetic

        topo = synthetic.wan(96, chords=2, seed=3)
        rng = np.random.default_rng(4)
        dests = np.sort(
            rng.choice(topo.n_nodes, size=8, replace=False).astype(np.int32)
        )
        mesh8 = blk.make_blocked_mesh(eight_cpu_devices)
        d_bulk, b_bulk, _ = _blocked_product_mode(topo, dests, mesh8, 8, "0")
        d_pipe, b_pipe, _ = _blocked_product_mode(topo, dests, mesh8, 8, "1")
        assert np.array_equal(d_pipe, d_bulk)
        assert np.array_equal(b_pipe, b_bulk)
        mesh1 = blk.make_blocked_mesh(eight_cpu_devices[:1])
        d1, b1, e1 = _blocked_product_mode(topo, dests, mesh1, 8, "1")
        assert np.array_equal(d1, d_bulk)
        assert np.array_equal(b1, b_bulk)
        t = e1.counters["mesh.blocked.rounds"]
        assert e1.counters["mesh.blocked.pipeline_prefetch_issues"] == t - 1
        assert e1.counters["mesh.blocked.pipeline_rounds_overlapped"] == 0
        assert e1.counters["mesh.blocked.pipeline_overlap_frac_est"] == 0

    def test_env_knob_forces_bulk(self, eight_cpu_devices, monkeypatch):
        """OPENR_BLOCKED_PIPELINE=0 forces the bulk loop; unset or any
        other value keeps the pipelined default for t >= 2."""
        eng = blk.BlockedApspEngine(
            tile=4, mesh=blk.make_blocked_mesh(eight_cpu_devices)
        )
        monkeypatch.delenv("OPENR_BLOCKED_PIPELINE", raising=False)
        assert eng.pipeline_enabled(2)
        assert not eng.pipeline_enabled(1)  # nothing to prefetch
        monkeypatch.setenv("OPENR_BLOCKED_PIPELINE", "0")
        assert not eng.pipeline_enabled(4)
        monkeypatch.setenv("OPENR_BLOCKED_PIPELINE", "1")
        assert eng.pipeline_enabled(4)
        # the pinned override outranks the env (auditor discipline)
        eng.pipeline_mode = "0"
        assert not eng.pipeline_enabled(4)

    def test_chaos_fault_mid_pipeline_demotes_to_bulk(self, monkeypatch):
        """A chaos fault at the per-round gate lands inside the
        pipelined loop first: the rung must account the demotion
        (`pipeline_fallbacks`), retry bulk-synchronously, and — with
        the fault still armed — surface the failure to the fleet rung,
        which serves the view via the fused product as before."""
        from types import SimpleNamespace

        from openr_tpu.chaos.chaos import ChaosSpfBackend

        monkeypatch.delenv("OPENR_NODE_SHARD", raising=False)
        monkeypatch.delenv("OPENR_BLOCKED_MESH", raising=False)
        monkeypatch.delenv("OPENR_BLOCKED_PIPELINE", raising=False)
        ls = LinkState()
        for db in grid_topology(4):
            ls.update_adjacency_database(db)
        nodes = sorted(ls.node_names)
        dests = [nodes[0], nodes[-1]]
        engine = DeviceResidencyEngine()
        engine.blocked.node_shard_threshold = 0
        engine.blocked.tile = 4  # multi-round closure -> pipeline engages
        chaos = ChaosSpfBackend(
            SimpleNamespace(engine=engine),
            seed=7,
            fail_prob=1.0,
            fail_ops={"engine:blocked_round"},
        )
        view = FleetViewCache().view(ls, dests, engine=engine)
        assert view.converged and not view.node_sharded
        assert engine.blocked.counters["mesh.blocked.pipeline_fallbacks"] == 1
        assert engine.blocked.counters["mesh.blocked.fallbacks"] == 1
        spf_stream = chaos.log.streams().get("spf", [])
        assert any("engine:blocked_round:fail" in e for e in spf_stream)
        chaos.disarm()
        ls2 = LinkState()
        for db in grid_topology(4):
            ls2.update_adjacency_database(db)
        vf = FleetViewCache().view(ls2, dests)
        for node in nodes:
            assert np.array_equal(view._row(node), vf._row(node))

    def test_transient_fault_recovers_on_bulk_retry(self, eight_cpu_devices):
        """A fault that fires exactly once demotes the pipelined
        attempt and the bulk retry completes — the product is served
        by the blocked rung itself, bit-exact, with the demotion
        accounted."""
        csr = _csr(grid_topology(4))
        n = int(csr.n_nodes)
        dests = np.asarray([0, n - 1], dtype=np.int32)
        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        ref_dist, ref_bitmap, _ = _blocked_product_mode(
            csr, dests, mesh, 4, "0"
        )
        eng = blk.BlockedApspEngine(tile=4, mesh=mesh)
        eng.pipeline_mode = "1"
        fired = []

        def hook(op):
            if op == "blocked_round" and not fired:
                fired.append(op)
                raise RuntimeError("injected: partition mid-pipeline")

        eng.fault_hook = hook
        dist, bitmap, ok = eng.fleet_product(csr, dests, _out_ell(csr))
        assert ok
        assert eng.counters["mesh.blocked.pipeline_fallbacks"] == 1
        assert eng.counters["mesh.blocked.products"] == 1
        assert np.array_equal(np.asarray(jax.device_get(dist)), ref_dist)
        assert np.array_equal(np.asarray(jax.device_get(bitmap)), ref_bitmap)


class TestPipelineHloEvidence:
    """Compile-only evidence on the virtual mesh: the lowered
    `blocked_round_pipelined` module schedules the round-(k+1) panel
    all-gathers with no data dependence on the round-k outer-update
    while, so their async start/done spans legally bracket it —
    materialized and verified by parallel.hlo_async from the compiled
    module's real def-use chains."""

    def _lowered_text(self, eight_cpu_devices, s=1, t=3, b=8):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        sds = jax.ShapeDtypeStruct
        args = (
            sds(
                (s, t, b, t, b),
                jnp.uint32,
                sharding=NamedSharding(
                    mesh, P("batch", None, "row", None, "col")
                ),
            ),
            sds(
                (s, b, t, b),
                jnp.uint32,
                sharding=NamedSharding(mesh, P("batch", None, None, "col")),
            ),
            sds(
                (s, t, b, b),
                jnp.uint32,
                sharding=NamedSharding(mesh, P("batch", None, "row", None)),
            ),
            sds((t * b,), jnp.bool_, sharding=NamedSharding(mesh, P())),
            sds((), jnp.int32, sharding=NamedSharding(mesh, P())),
        )
        return (
            blk.blocked_round_pipelined.lower(*args, mesh=mesh)
            .compile()
            .as_text()
        )

    def test_async_spans_bracket_outer_update(self, eight_cpu_devices):
        from openr_tpu.parallel import hlo_async

        txt = self._lowered_text(eight_cpu_devices)
        header = txt.split("\n", 1)[0]
        assert "is_scheduled=true" in header
        # donation survives the double-buffered carry: dist aliases
        # output 0 in the compiled module
        assert "input_output_alias={ {0}: (0" in header
        rep = hlo_async.async_report(txt)
        # the outer update is identifiable: the only rank-5 u32 while
        assert rep["outer_update"] is not None
        # row panel + col panel + diagonal replication
        assert rep["n_collectives"] >= 3
        # every span is legal per the def-use graph (checked, not
        # assumed from the scheduler's construction)
        assert all(s["legal"] for s in rep["spans"]), rep["spans"]
        # headline: both PANEL gathers' spans bracket the outer update
        assert rep["panel_overlap_ok"], rep["spans"]
        spanning = [s for s in rep["spans"] if s["spans_outer_update"]]
        assert len(spanning) >= 2
        for s in spanning:
            # the pair brackets real compute, not an empty window
            assert len(s["compute_in_span"]) >= 1, s
        assert rep["collective_bytes"] > 0
        assert rep["overlap_frac_est"] > 0

    def test_materialized_pairs_bracket_while_textually(
        self, eight_cpu_devices
    ):
        from openr_tpu.parallel import hlo_async

        txt = self._lowered_text(eight_cpu_devices)
        rep = hlo_async.async_report(txt)
        mat = rep["materialized"]
        assert mat.count("all-gather-start(") == rep["n_collectives"]
        assert mat.count("all-gather-done(") == rep["n_collectives"]
        lines = mat.splitlines()
        w = next(
            i
            for i, l in enumerate(lines)
            if l.lstrip().startswith(f"%{rep['outer_update']} =")
        )
        spanning = [s for s in rep["spans"] if s["spans_outer_update"]]
        for s in spanning:
            si = next(
                i
                for i, l in enumerate(lines)
                if l.lstrip().startswith(f"%{s['name']}-start =")
            )
            di = next(
                i
                for i, l in enumerate(lines)
                if l.lstrip().startswith(f"%{s['name']} = ")
            )
            assert si < w < di, (s["name"], si, w, di)

    def test_rejects_unscheduled_module(self, eight_cpu_devices):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from openr_tpu.parallel import hlo_async

        mesh = blk.make_blocked_mesh(eight_cpu_devices)
        sds = jax.ShapeDtypeStruct
        lowered = blk.blocked_diag.lower(
            sds(
                (1, 2, 8, 2, 8),
                jnp.uint32,
                sharding=NamedSharding(
                    mesh, P("batch", None, "row", None, "col")
                ),
            ),
            sds((16,), jnp.bool_, sharding=NamedSharding(mesh, P())),
            sds((), jnp.int32, sharding=NamedSharding(mesh, P())),
            mesh=mesh,
        )
        with pytest.raises(ValueError, match="is_scheduled"):
            hlo_async.parse_entry(lowered.as_text())
