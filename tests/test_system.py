"""Whole-system multi-node tests.

Modeled on the reference's OpenrSystemTest.cpp + OpenrWrapper
(openr/tests/OpenrSystemTest.cpp:245 SimpleRingTopologyFixture): N full
daemons in one process wired through a MockIoProvider fabric, asserting
cross-node route convergence; plus a two-daemon test over REAL TCP (ctrl
servers as the KvStore transport) exercised end-to-end through the breeze
CLI.
"""

from __future__ import annotations

import contextlib
import io
import time

import pytest

from openr_tpu.cli import breeze
from openr_tpu.config import (
    AreaConf,
    DecisionConf,
    KvStoreConf,
    OpenrConfig,
    SparkConf,
    config_from_dict,
)
from openr_tpu.ctrl import CtrlClient
from openr_tpu.kvstore import InProcessTransport
from openr_tpu.main import OpenrDaemon
from openr_tpu.spark import MockIoProvider
from openr_tpu.types import LinkEvent, PrefixEntry, PrefixType, normalize_prefix

FIB_CLIENT = 786

FAST_SPARK = SparkConf(
    hello_time_s=0.3,
    fastinit_hello_time_ms=20,
    keepalive_time_s=0.05,
    hold_time_s=0.5,
    graceful_restart_time_s=1.0,
)


def make_config(
    name: str, ctrl_port: int = 0, flood_optimization: bool = False
) -> OpenrConfig:
    return OpenrConfig(
        node_name=name,
        areas=[AreaConf()],
        openr_ctrl_port=ctrl_port,
        spark_config=FAST_SPARK,
        decision_config=DecisionConf(debounce_min_ms=5, debounce_max_ms=20),
        kvstore_config=KvStoreConf(
            enable_flood_optimization=flood_optimization
        ),
        enable_watchdog=False,
        node_label=0,
    ).validate()


def wait_for(cond, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


class RingFixture:
    """N daemons in a ring over mock fabrics (reference:
    SimpleRingTopologyFixture)."""

    def __init__(self, n: int, flood_optimization: bool = False):
        self.spark_fabric = MockIoProvider()
        self.kv_fabric = InProcessTransport()
        self.daemons: list[OpenrDaemon] = []
        for i in range(n):
            name = f"openr-{i}"
            addr = f"fe80::{name}"
            daemon = OpenrDaemon(
                make_config(name, flood_optimization=flood_optimization),
                io_provider=self.spark_fabric.endpoint(name),
                kvstore_transport=self.kv_fabric.bind(addr),
                spark_v6_addr=addr,
            )
            self.kv_fabric.register(addr, daemon.kvstore)
            self.daemons.append(daemon)
        for daemon in self.daemons:
            daemon.start()
        # ring links via mock fabric + netlink link events
        for i in range(n):
            j = (i + 1) % n
            if n == 2 and i == 1:
                break  # single link for a 2-ring
            self.spark_fabric.connect(
                f"openr-{i}", f"if-{i}-{j}", f"openr-{j}", f"if-{j}-{i}"
            )
        for i in range(n):
            j, k = (i + 1) % n, (i - 1) % n
            daemon = self.daemons[i]
            daemon.netlink_events_queue.push(LinkEvent(f"if-{i}-{j}", 1, True))
            if n > 2 or i == 0:
                daemon.netlink_events_queue.push(
                    LinkEvent(f"if-{i}-{k}", 2, True)
                )

    def prefix_exists(self, daemon: OpenrDaemon, prefix: str) -> bool:
        table = daemon.fib_agent.unicast.get(FIB_CLIENT, {})
        return normalize_prefix(prefix) in table

    def stop(self):
        for daemon in self.daemons:
            daemon.stop()


@pytest.fixture
def ring3():
    fixture = RingFixture(3)
    yield fixture
    fixture.stop()


class TestRingConvergence:
    def test_three_node_ring(self, ring3):
        daemons = ring3.daemons
        # every node advertises a loopback prefix
        for i, daemon in enumerate(daemons):
            daemon.prefix_manager.advertise_prefixes(
                PrefixType.LOOPBACK,
                [PrefixEntry(prefix=f"fc00:{i}::/64")],
            )
        # every node programs routes to every OTHER node's prefix
        for i, daemon in enumerate(daemons):
            for j in range(len(daemons)):
                if i == j:
                    continue
                assert wait_for(
                    lambda d=daemon, p=f"fc00:{j}::/64": ring3.prefix_exists(d, p)
                ), f"node {i} missing route to fc00:{j}::/64"

    def test_link_failure_reroutes(self, ring3):
        daemons = ring3.daemons
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:1::/64")]
        )
        assert wait_for(lambda: ring3.prefix_exists(daemons[0], "fc00:1::/64"))

        # direct link 0-1 dies: route must survive via node 2
        ring3.spark_fabric.disconnect("openr-0", "if-0-1", "openr-1", "if-1-0")
        deadline = time.monotonic() + 20

        def rerouted() -> bool:
            table = daemons[0].fib_agent.unicast.get(FIB_CLIENT, {})
            route = table.get(normalize_prefix("fc00:1::/64"))
            if route is None:
                return False
            return {nh.neighbor_node_name for nh in route.next_hops} == {
                "openr-2"
            }

        assert wait_for(rerouted), daemons[0].fib_agent.unicast

    def test_drain_node_diverts_traffic(self, ring3):
        daemons = ring3.daemons
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc00:1::/64")]
        )
        assert wait_for(lambda: ring3.prefix_exists(daemons[2], "fc00:1::/64"))
        # node 0 drains: node 2 must reach node 1 directly, not via 0
        daemons[0].link_monitor.set_node_overload(True)

        def direct_only() -> bool:
            table = daemons[2].fib_agent.unicast.get(FIB_CLIENT, {})
            route = table.get("fc00:1::/64")
            return route is not None and {
                nh.neighbor_node_name for nh in route.next_hops
            } == {"openr-1"}

        assert wait_for(direct_only)


class TestRingDualFloodTopo:
    """Ring convergence with DUAL flood-topology on: the reference's
    flood-optimization posture (KvStoreDb extends DualNode, KvStore.h:191)
    exercised through full daemons."""

    def test_ring_converges_with_spt_flooding(self):
        fixture = RingFixture(3, flood_optimization=True)
        try:
            daemons = fixture.daemons
            # routes still converge with SPT-constrained flooding
            for i, daemon in enumerate(daemons):
                daemon.prefix_manager.advertise_prefixes(
                    PrefixType.LOOPBACK,
                    [PrefixEntry(prefix=f"fc00:{i}::/64")],
                )
            for i, daemon in enumerate(daemons):
                for j in range(3):
                    if i == j:
                        continue  # no route to self
                    assert wait_for(
                        lambda d=daemon, p=f"fc00:{j}::/64": fixture.prefix_exists(d, p)
                    ), f"{daemon.config.node_name} missing fc00:{j}::/64"

            # all three are flood roots; smallest id openr-0 wins
            def spt_done() -> bool:
                for daemon in daemons:
                    infos = daemon.kvstore.get_flood_topo("0")
                    if infos.flood_root_id != "openr-0":
                        return False
                return True

            assert wait_for(spt_done), [
                d.kvstore.get_flood_topo("0") for d in daemons
            ]
            # flood fanout: the SPT rooted at openr-0 covers the ring with 2
            # edges, so every node floods to <= its SPT neighbors, and the
            # two non-root nodes flood towards a single parent
            for daemon in daemons[1:]:
                infos = daemon.kvstore.get_flood_topo("0")
                spt = infos.infos["openr-0"]
                assert spt.parent is not None
                assert len(infos.flood_peers) <= 2
            total_spt_edges = sum(
                len(d.kvstore.get_flood_topo("0").flood_peers) for d in daemons
            )
            # an SPT over 3 nodes has 2 edges -> 4 directed flood slots;
            # full-mesh on a 3-ring would be 6
            assert total_spt_edges == 4, total_spt_edges
        finally:
            fixture.stop()


class TestTcpSystem:
    """Two daemons over REAL TCP: ctrl servers double as the KvStore peer
    transport; driven end-to-end through the breeze CLI."""

    @pytest.fixture
    def pair(self):
        spark_fabric = MockIoProvider()
        ports = (28018, 28019)
        daemons = []
        for i, port in enumerate(ports):
            name = f"tcp-{i}"
            daemon = OpenrDaemon(
                make_config(name, ctrl_port=port),
                io_provider=spark_fabric.endpoint(name),
                spark_v6_addr="::1",
            )
            daemon.start()
            daemons.append(daemon)
        spark_fabric.connect("tcp-0", "veth0", "tcp-1", "veth1")
        daemons[0].netlink_events_queue.push(LinkEvent("veth0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("veth1", 1, True))
        yield daemons, ports
        for daemon in daemons:
            daemon.stop()

    def breeze(self, port: int, *argv: str) -> str:
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = breeze.main(["-p", str(port), *argv])
        assert rc == 0, out.getvalue()
        return out.getvalue()

    def test_tcp_convergence_and_cli(self, pair):
        daemons, ports = pair
        daemons[1].prefix_manager.advertise_prefixes(
            PrefixType.LOOPBACK, [PrefixEntry(prefix="fc01::/64")]
        )
        assert wait_for(
            lambda: "fc01::/64"
            in daemons[0].fib_agent.unicast.get(FIB_CLIENT, {}),
            timeout=30,
        )

        # breeze against daemon 0
        out = self.breeze(ports[0], "kvstore", "peers")
        assert "tcp-1" in out and "INITIALIZED" in out
        out = self.breeze(ports[0], "kvstore", "keys")
        assert "adj:tcp-0" in out and "prefix:[tcp-1]" in out
        out = self.breeze(ports[0], "decision", "routes")
        assert "fc01::/64" in out
        # any-node query (fleet-product path when warm) and the
        # fleet-wide dump RPC (getFleetRoutes over ops.allsources)
        out = self.breeze(ports[0], "decision", "routes", "--node", "tcp-1")
        assert "Unicast Routes" in out
        out = self.breeze(ports[0], "decision", "fleet-routes")
        assert "tcp-0" in out and "tcp-1" in out and "fc01::/64" in out
        out = self.breeze(ports[0], "decision", "adj")
        assert "tcp-0" in out and "tcp-1" in out
        out = self.breeze(ports[0], "fib", "routes")
        assert "fc01::/64" in out
        out = self.breeze(ports[0], "spark", "neighbors")
        assert "tcp-1" in out and "ESTABLISHED" in out
        out = self.breeze(ports[0], "decision", "path", "tcp-1")
        assert "tcp-0 -> tcp-1" in out
        out = self.breeze(ports[0], "monitor", "counters")
        assert "decision.adj_db_update" in out
        out = self.breeze(ports[0], "version")
        assert "20" in out
        out = self.breeze(ports[0], "prefixmgr", "view")
        out = self.breeze(ports[1], "prefixmgr", "view")
        assert "fc01::/64" in out
        # failure-protection analysis (SRLG what-if + TI-LFA surfaces).
        # Impact defaults to this router's view: tcp-0 loses tcp-1 (1 pair)
        out = self.breeze(ports[0], "decision", "what-if", "tcp-0/tcp-1")
        assert "tcp-0/tcp-1" in out
        row = out.splitlines()[2]
        assert row.split()[2] == "1", out
        out = self.breeze(ports[0], "decision", "tilfa", "tcp-0", "-v")
        assert "node: tcp-0" in out
        assert "tcp-1" in out  # the (unprotectable) adjacency is listed

        # drain via CLI and observe the overload bit propagate
        self.breeze(ports[0], "lm", "set-node-overload")
        out = self.breeze(ports[0], "lm", "links")
        assert "node overloaded: True" in out
