"""Fib tests (modeled on openr/fib/tests/FibTest.cpp): incremental
programming, failure -> full resync with backoff, agent-restart detection,
doNotInstall, perf events."""

from __future__ import annotations

import time

import pytest

from openr_tpu.decision.rib import DecisionRouteUpdate, RibMplsEntry, RibUnicastEntry
from openr_tpu.fib import Fib, MockFibAgent, longest_prefix_match
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.types import NextHop, PerfEvents

CLIENT = 786


def route(prefix: str, nh: str = "fe80::1") -> RibUnicastEntry:
    return RibUnicastEntry(
        prefix=prefix, nexthops=frozenset({NextHop(address=nh)})
    )


def update(*routes: RibUnicastEntry, delete=(), mpls=(), mpls_del=(), perf=None):
    u = DecisionRouteUpdate(perf_events=perf)
    for r in routes:
        u.add_route_to_update(r)
    u.unicast_routes_to_delete.extend(delete)
    u.mpls_routes_to_update.extend(mpls)
    u.mpls_routes_to_delete.extend(mpls_del)
    return u


def wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


@pytest.fixture
def harness():
    routeq: ReplicateQueue = ReplicateQueue()
    fibq: ReplicateQueue = ReplicateQueue()
    agent = MockFibAgent()
    fib = Fib(
        "node1",
        routeq.get_reader(),
        agent,
        fib_updates_queue=fibq,
        keepalive_interval_s=0.1,
        sync_initial_backoff_s=0.02,
        sync_max_backoff_s=0.2,
    )
    fib.run()
    yield routeq, agent, fib, fibq.get_reader()
    routeq.close()
    fibq.close()
    fib.stop()
    fib.wait_until_stopped(5)


class TestLongestPrefixMatch:
    def test_basic(self):
        prefixes = ["10.0.0.0/8", "10.1.0.0/16", "10.1.1.0/24", "::/0"]
        assert longest_prefix_match("10.1.1.5", prefixes) == "10.1.1.0/24"
        assert longest_prefix_match("10.2.0.1", prefixes) == "10.0.0.0/8"
        assert longest_prefix_match("2001::1", prefixes) == "::/0"
        assert longest_prefix_match("192.168.0.1", prefixes) is None


class TestFib:
    def test_initial_sync_then_incremental(self, harness):
        routeq, agent, fib, _ = harness
        assert wait_for(lambda: agent.counters["sync_fib"] >= 1)
        routeq.push(update(route("::1:0/112")))
        assert wait_for(
            lambda: "::1:0/112" in agent.unicast.get(CLIENT, {})
        )
        assert agent.counters["add_unicast"] == 1
        # delete
        routeq.push(update(delete=["::1:0/112"]))
        assert wait_for(lambda: "::1:0/112" not in agent.unicast.get(CLIENT, {}))

    def test_mpls_programming(self, harness):
        routeq, agent, fib, _ = harness
        assert wait_for(lambda: agent.counters["sync_fib"] >= 1)
        routeq.push(
            update(
                mpls=[
                    RibMplsEntry(
                        label=100, nexthops=frozenset({NextHop(address="fe80::2")})
                    )
                ]
            )
        )
        assert wait_for(lambda: 100 in agent.mpls.get(CLIENT, {}))
        routeq.push(update(mpls_del=[100]))
        assert wait_for(lambda: 100 not in agent.mpls.get(CLIENT, {}))

    def test_failure_triggers_resync(self, harness):
        routeq, agent, fib, _ = harness
        assert wait_for(lambda: agent.counters["sync_fib"] >= 1)
        agent.fail = True
        routeq.push(update(route("::2:0/112")))
        time.sleep(0.2)
        assert "::2:0/112" not in agent.unicast.get(CLIENT, {})
        agent.fail = False
        # backoff'd syncFib reconciles the full state
        assert wait_for(lambda: "::2:0/112" in agent.unicast.get(CLIENT, {}))

    def test_agent_restart_resync(self, harness):
        routeq, agent, fib, _ = harness
        routeq.push(update(route("::3:0/112")))
        assert wait_for(lambda: "::3:0/112" in agent.unicast.get(CLIENT, {}))
        agent.restart()  # wipes table, bumps aliveSince
        assert wait_for(lambda: "::3:0/112" in agent.unicast.get(CLIENT, {}))
        assert fib.counters.get("fib.agent_restarts", 0) >= 1

    def test_do_not_install(self, harness):
        routeq, agent, fib, _ = harness
        assert wait_for(lambda: agent.counters["sync_fib"] >= 1)
        r = RibUnicastEntry(
            prefix="::4:0/112",
            nexthops=frozenset({NextHop(address="fe80::1")}),
            do_not_install=True,
        )
        routeq.push(update(r))
        time.sleep(0.2)
        assert "::4:0/112" not in agent.unicast.get(CLIENT, {})
        # still tracked in Fib's own state
        unicast, _mpls = fib.get_route_db()
        assert any(u.dest == "::4:0/112" for u in unicast)

    def test_perf_events_and_fib_stream(self, harness):
        routeq, agent, fib, fib_reader = harness
        assert wait_for(lambda: agent.counters["sync_fib"] >= 1)
        perf = PerfEvents()
        perf.add("node1", "DECISION_RECEIVED")
        routeq.push(update(route("::5:0/112"), perf=perf))
        programmed = fib_reader.get(timeout=5)
        names = [e.event_name for e in programmed.perf_events.events]
        assert names[0] == "DECISION_RECEIVED"
        assert "OPENR_FIB_ROUTES_PROGRAMMED" in names
        assert fib.get_perf_db()


class TestWedgedAgent:
    def test_wedged_agent_trips_keepalive_and_recovery_resyncs(self):
        """An agent that ACCEPTS connections but never replies (wedged,
        not crashed) must trip Fib's keepalive/backoff machinery — and a
        healthy agent appearing on the same port must receive a full
        resync (reference: keepAliveCheck + syncRouteDbDebounced,
        openr/fib/Fib.h:161-181; FibTest agent-restart coverage)."""
        import socket as _socket
        import threading

        from openr_tpu.platform import FibAgentServer, TcpFibAgent
        from tests.test_platform_agent import free_port

        port = free_port()

        # wedge server: accept + read, never write
        wedge = _socket.socket(_socket.AF_INET6, _socket.SOCK_STREAM)
        wedge.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        wedge.bind(("::1", port))
        wedge.listen(8)
        wedged_conns = []
        stop_wedge = threading.Event()

        def wedge_loop():
            wedge.settimeout(0.2)
            while not stop_wedge.is_set():
                try:
                    conn, _ = wedge.accept()
                    wedged_conns.append(conn)  # hold open, never reply
                except OSError:
                    continue

        wedge_thread = threading.Thread(target=wedge_loop, daemon=True)
        wedge_thread.start()

        routeq: ReplicateQueue = ReplicateQueue()
        agent_client = TcpFibAgent(port=port, timeout_s=0.3)
        fib = Fib(
            "node1",
            routeq.get_reader(),
            agent_client,
            keepalive_interval_s=0.1,
            sync_initial_backoff_s=0.02,
            sync_max_backoff_s=0.2,
        )
        fib.run()
        try:
            routeq.push(update(route("::9:0/112")))
            # wedged agent: keepalive calls time out and are COUNTED, the
            # route state never reaches synced
            assert wait_for(
                lambda: fib.counters.get("fib.thrift.failure.keepalive", 0)
                >= 2,
                timeout=10,
            ), fib.counters
            assert not fib.route_state.synced

            # the supervisor replaces the wedged agent with a healthy one
            stop_wedge.set()
            wedge_thread.join(3)
            for c in wedged_conns:
                c.close()
            wedge.close()
            server = FibAgentServer(host="::1", port=port)
            server.start()
            try:
                # backoff'd retries must reconnect and full-sync the routes
                assert wait_for(
                    lambda: "::9:0/112"
                    in server.table.unicast.get(CLIENT, {}),
                    timeout=15,
                ), server.table.unicast
                assert wait_for(lambda: fib.route_state.synced, timeout=5)
            finally:
                server.stop()
        finally:
            routeq.close()
            fib.stop()
            fib.wait_until_stopped(5)
