"""End-to-end tracing + shared histograms (openr_tpu/obs): the
log-bucketed histogram unit contract, the tracer's arming discipline
(zero hooks unarmed), queue/eventbase span carry, the armed serving
query span tree down to the engine rung, the kvstore->decision->fib
flap trace through a live daemon, and the determinism contract that
lets the chaos fuzzer ingest span structures as coverage tokens.
"""

from __future__ import annotations

import threading
import time

import pytest

from openr_tpu.obs import OBS_COUNTER_KEYS, Histogram, export_histogram
from openr_tpu.obs import trace as _trace
from openr_tpu.obs.trace import Span, Tracer
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import ReplicateQueue, RWQueue

from test_system import wait_for


@pytest.fixture
def tracer():
    """Arm tracing for one test; ALWAYS disarm after (tier-1 runs
    unarmed, and a leaked tracer would silently trace every later
    test)."""
    tr = _trace.enable(sample_every=1, ring=256)
    yield tr
    _trace.disable()


class TestHistogram:
    def test_power_of_two_buckets_bound_percentiles(self):
        h = Histogram()
        for v in (1, 3, 100, 1000, 100_000):
            h.record_us(v)
            p = h.percentile_us(100.0)
            # the reported percentile is the bucket's upper bound:
            # never below the true value, less than 2x above it
            assert v <= p < 2 * v, (v, p)

    def test_percentiles_are_monotone(self):
        h = Histogram()
        for i in range(1000):
            h.record_us(i + 1)
        p50, p99, p999 = (
            h.percentile_us(50),
            h.percentile_us(99),
            h.percentile_us(99.9),
        )
        assert 0 < p50 <= p99 <= p999

    def test_empty_and_zero(self):
        h = Histogram()
        assert h.percentile_us(99) == 0
        h.record_us(0)
        assert h.percentile_us(99) == 0  # zero-bucket upper bound

    def test_merge_sums_counts(self):
        a, b = Histogram(), Histogram()
        a.record_us(10)
        b.record_us(10)
        b.record_us(100_000)
        a.merge(b)
        counts, n = a.snapshot()
        assert sum(counts) == n == 3
        assert a.percentile_us(99) >= 100_000

    def test_export_emits_wire_keys(self):
        h = Histogram()
        for v in (100, 200, 3000):
            h.record_us(v)
        counters: dict = {}
        export_histogram(counters, "fam", h)
        assert counters["fam.hist_us.count"] == 3
        assert set(counters) >= {"fam.p50_us", "fam.p99_us", "fam.p999_us"}
        # non-empty buckets dump for offline re-aggregation
        bucket_total = sum(
            v for k, v in counters.items() if ".hist_us.b" in k
        )
        assert bucket_total == 3


class TestArmingDiscipline:
    def test_unarmed_by_default_and_queues_allocate_nothing(self):
        # tier-1 runs without OPENR_TRACE: the module constant is None
        # and a queue that moves items allocates NO token storage
        assert _trace.TRACE is None
        q: RWQueue = RWQueue()
        q.push(1)
        assert q.get() == 1
        assert q._obs_tokens is None

    def test_enable_disable_round_trip(self):
        assert _trace.TRACE is None
        tr = _trace.enable(sample_every=2, ring=8)
        try:
            assert _trace.TRACE is tr
            assert tr.sample_every == 2
        finally:
            _trace.disable()
        assert _trace.TRACE is None

    def test_maybe_child_unarmed_is_shared_noop(self):
        assert _trace.maybe_child("x") is _trace.maybe_child("y")

    def test_obs_stats_unarmed_answers_zeroed_shape(self):
        from openr_tpu.obs import ObsStats

        stats = ObsStats()
        assert stats.get_counters() == {k: 0 for k in OBS_COUNTER_KEYS}
        assert stats.dump_traces() == []
        assert stats.span_samples() == []


class TestTracerUnit:
    def test_deterministic_modulo_sampling(self, tracer):
        tr = _trace.enable(sample_every=3)
        roots = [tr.root("r") for _ in range(9)]
        kept = [r for r in roots if r is not None]
        assert len(kept) == 3  # roots 1, 4, 7 (1-in-3, modulo counter)
        c = tr.get_counters()
        assert c["obs.traces_started"] == 3
        assert c["obs.traces_sampled_out"] == 6

    def test_ring_is_bounded_with_eviction_ledger(self, tracer):
        tr = _trace.enable(ring=4)
        for i in range(7):
            sp = tr.root("r", i=i)
            tr.finish(sp)
        assert len(tr.dump(100)) == 4
        c = tr.get_counters()
        assert c["obs.traces_finished"] == 7
        assert c["obs.trace_ring_evictions"] == 3

    def test_structure_is_child_order_independent(self, tracer):
        def build(order):
            root = Span("root")
            root.tags["outcome"] = "ok"
            for name in order:
                Span(name, parent=root)
                root.children.append(Span(name, parent=root))
                root.children[-1].notes["t"] = time.time()  # non-structural
            return root.structure()

        assert build(["a", "b", "c"]) == build(["c", "a", "b"])
        assert "outcome=ok" in build(["a"])
        assert "t=" not in build(["a"])  # notes excluded

    def test_root_extends_under_active_scope(self, tracer):
        outer = tracer.root("router.query")
        with tracer.activate((outer,)):
            inner = tracer.root("serving.query")
        assert inner.parent is outer
        assert outer.children == [inner]

    def test_fan_in_scope_annotates_every_span(self, tracer):
        a, b = tracer.root("a"), tracer.root("b")
        with tracer.activate((a, b)):
            tracer.annotate("engine.rung", "delta")
            tracer.event("epoch_retry")
        for sp in (a, b):
            assert sp.tags["engine.rung"] == "delta"
            assert [c.name for c in sp.children] == ["epoch_retry"]

    def test_bind_scope_carries_across_threads(self, tracer):
        root = tracer.root("r")
        seen = []

        def probe():
            seen.append(tracer.scope())

        with tracer.activate((root,)):
            bound = tracer.bind_scope(probe)
        t = threading.Thread(target=bound)
        t.start()
        t.join(5)
        assert seen == [(root,)]

    def test_eventbase_handoff_reactivates_scope(self, tracer):
        evb = OpenrEventBase("obs-test")
        evb.run()
        try:
            root = tracer.root("r")
            with tracer.activate((root,)):
                fut = evb.run_in_event_base_thread(tracer.scope)
            assert fut.result(5) == (root,)
        finally:
            evb.stop()
            evb.wait_until_stopped(5)


class TestQueueCarry:
    def test_put_get_carries_scope_across_threads(self, tracer):
        q: RWQueue = RWQueue()
        root = tracer.root("r")
        with tracer.activate((root,)):
            q.push("item")
        got = []

        def consumer():
            q.get(timeout=5)
            got.append(tracer.take_carried())

        t = threading.Thread(target=consumer)
        t.start()
        t.join(5)
        assert got == [(root,)]

    def test_pop_clears_stale_carried_token(self, tracer):
        q: RWQueue = RWQueue()
        root = tracer.root("r")
        with tracer.activate((root,)):
            q.push("traced")
        q.push("untraced")  # no scope
        q.get(timeout=5)
        q.get(timeout=5)
        # the second pop must CLEAR the first pop's token, or the
        # untraced item would adopt the traced item's span
        assert tracer.take_carried() == ()

    def test_items_pushed_while_disarmed_carry_nothing(self, tracer):
        _trace.disable()
        q: RWQueue = RWQueue()
        q.push("old")
        tr = _trace.enable()
        root = tr.root("r")
        with tr.activate((root,)):
            q.push("new")
        q.get(timeout=5)
        assert tr.take_carried() == ()  # disarmed-era item: no context
        q.get(timeout=5)
        assert tr.take_carried() == (root,)

    def test_bounded_shed_keeps_tokens_aligned(self, tracer):
        q: RWQueue = RWQueue(maxlen=2)
        root = tracer.root("r")
        with tracer.activate((root,)):
            for i in range(4):
                q.push(i)
        assert q.size() == 2
        assert len(q._obs_tokens) == 2
        assert q.get(timeout=5) == 2
        assert tracer.take_carried() == (root,)

    def test_replicate_queue_carries_to_every_reader(self, tracer):
        rq: ReplicateQueue = ReplicateQueue()
        readers = [rq.get_reader() for _ in range(2)]
        root = tracer.root("r")
        with tracer.activate((root,)):
            rq.push("x")
        for r in readers:
            r.get(timeout=5)
            assert tracer.take_carried() == (root,)


def _make_scheduler():
    from openr_tpu.decision.spf_solver import DeviceSpfBackend
    from openr_tpu.serving import EngineBatchBackend, QueryScheduler

    from test_spf_solver import square

    ls = square()
    backend = EngineBatchBackend(
        {"0": ls},
        spf_backend=DeviceSpfBackend(min_device_nodes=1, min_device_sources=1),
    )
    sched = QueryScheduler(backend)
    sched.run()
    return sched


class TestServingSpanTree:
    def test_unarmed_queries_open_no_spans(self):
        assert _trace.TRACE is None
        sched = _make_scheduler()
        try:
            res = sched.submit("paths", sources=("1",)).result(20)
            assert res.value["1"]
            counters = sched.get_counters()
            # the shared histogram replaced the sorted-deque gauges but
            # kept the wire keys (plus the new p999)
            assert counters["serving.p99_us"] >= counters["serving.p50_us"] > 0
            assert "serving.p999_us" in counters
            assert counters["serving.hist_us.count"] == 1
        finally:
            sched.stop()

    def test_armed_query_attributes_every_stage_and_the_rung(self, tracer):
        sched = _make_scheduler()
        try:
            res = sched.submit("paths", sources=("1",)).result(20)
            assert res.value["1"]
            assert wait_for(
                lambda: tracer.get_counters()["obs.traces_finished"] >= 1, 10
            )
            roots = [d for d in tracer.dump(16) if d["name"] == "serving.query"]
            assert roots, tracer.dump(16)
            tree = roots[-1]
            assert tree["tags"]["outcome"] == "ok"
            assert tree["tags"]["op"] == "paths"
            stages = {c["name"]: c for c in tree["children"]}
            assert {"admission", "coalesce", "dispatch", "reply"} <= set(
                stages
            )
            # the dispatch stage names the exact engine rung taken and
            # the kernel flavor that served it
            dispatch = stages["dispatch"]
            assert dispatch["tags"].get("engine.rung") in {
                "restage",
                "spf",
                "incremental",
                "delta",
                "rewire",
                "blocked",
            }, dispatch
            # kernel attribution only appears on rungs that route through
            # the pallas/xla fallback wrapper; when present it names the
            # flavor that actually served the query
            kernel = dispatch["tags"].get("engine.kernel")
            if kernel is not None:
                assert kernel.split(":")[-1] in {"pallas", "fallback", "xla"}
            assert tree["duration_us"] is not None
        finally:
            sched.stop()

    def test_shed_query_closes_its_trace(self, tracer):
        from openr_tpu.serving import QueryShedError

        sched = _make_scheduler()
        try:
            sched.stop()  # closed admission -> every submit sheds
            fut = sched.submit("paths", sources=("1",))
            with pytest.raises(QueryShedError):
                fut.result(5)
            assert wait_for(
                lambda: any(
                    d["name"] == "serving.query"
                    and d["tags"].get("outcome") == "shed"
                    for d in tracer.dump(32)
                ),
                5,
            )
        finally:
            sched.stop()


class TestRouterSpanNesting:
    def test_router_trace_nests_scheduler_trace(self, tracer):
        from openr_tpu.serving import ReplicaRouter, SchedulerReplica

        sched = _make_scheduler()
        router = ReplicaRouter(
            [SchedulerReplica("rep-0", sched)], hedge_after_s=None
        )
        try:
            res = router.submit("paths", sources=("1",)).result(20)
            assert res.value["1"]
            assert wait_for(
                lambda: any(
                    d["name"] == "router.query" for d in tracer.dump(16)
                ),
                10,
            )
            tree = [
                d for d in tracer.dump(16) if d["name"] == "router.query"
            ][-1]
            assert tree["tags"]["outcome"] in {"ok", "hedge_win"}
            kids = {c["name"] for c in tree["children"]}
            # the dispatch edge and the replica's whole serving.query
            # tree hang under the ONE router trace (root-extends rule)
            assert "dispatch" in kids
            assert "serving.query" in kids
        finally:
            router.stop()


class TestFlapSpanTree:
    def test_publication_trace_attributes_decision_and_fib(self, tracer):
        from openr_tpu.kvstore import InProcessTransport
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.serializer import dumps
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import (
            Adjacency,
            AdjacencyDatabase,
            PrefixDatabase,
            PrefixEntry,
            Value,
            adj_key,
            prefix_key,
        )

        from test_system import make_config

        fabric = MockIoProvider()
        d = OpenrDaemon(
            make_config("solo", ctrl_port=0),
            io_provider=fabric.endpoint("solo"),
            kvstore_transport=InProcessTransport().bind("solo"),
        )
        d.start()
        try:
            # a topology event: a solo<->peer adjacency plus a prefix
            # advertised by the peer lands in kvstore, floods to internal
            # subscribers, rebuilds routes, programs fib — ONE trace must
            # attribute the whole pipeline
            def _adj(me, other):
                return Adjacency(
                    other_node_name=other,
                    if_name=f"{me}/{other}",
                    other_if_name=f"{other}/{me}",
                    metric=10,
                    next_hop_v6=f"fe80::{1 if other == 'solo' else 2}",
                )

            pfx = "::9:0/112"
            d.kvstore.set_key_vals(
                "0",
                {
                    adj_key("solo"): Value(
                        1,
                        "solo",
                        dumps(
                            AdjacencyDatabase(
                                "solo", [_adj("solo", "peer")]
                            )
                        ),
                    ),
                    adj_key("peer"): Value(
                        1,
                        "peer",
                        dumps(
                            AdjacencyDatabase(
                                "peer", [_adj("peer", "solo")]
                            )
                        ),
                    ),
                    prefix_key("peer", pfx, "0"): Value(
                        1,
                        "peer",
                        dumps(
                            PrefixDatabase(
                                "peer", [PrefixEntry(prefix=pfx)]
                            )
                        ),
                    ),
                },
            )

            def flap_trace():
                for t in tracer.dump(64):
                    if t["name"] != "kvstore.publication":
                        continue
                    names = {c["name"] for c in t["children"]}
                    if "decision" not in names:
                        continue
                    dec = [
                        c for c in t["children"] if c["name"] == "decision"
                    ][0]
                    if any(
                        g["name"] == "fib.program" for g in dec["children"]
                    ):
                        return t
                return None

            assert wait_for(lambda: flap_trace() is not None, 15)
            tree = flap_trace()
            assert tree["tags"]["area"] == "0"
            assert tree["duration_us"] is not None  # fib terminal closed it

            # the ctrl surface serves the same trees + the obs ledger
            from openr_tpu.ctrl import CtrlClient

            client = CtrlClient(port=d.ctrl_port)
            try:
                dumped = client.call("dumpTraces", n=64)
                assert any(
                    t["name"] == "kvstore.publication" for t in dumped
                )
                samples = client.call("getSpanSamples")
                assert samples and all("structure" in s for s in samples)
                counters = client.call("getCounters")
                assert counters["obs.traces_finished"] > 0
            finally:
                client.close()
        finally:
            d.stop()


class TestSpanStructureDeterminism:
    def test_same_seed_chaos_replay_has_identical_span_structure(
        self, tracer
    ):
        from openr_tpu.chaos import fuzz as fz

        t = fz.FuzzTimeline(
            seed=424242,
            events=[
                fz.FuzzEvent("fleet", "burst", {"q": 3}),
                fz.FuzzEvent("flap", "worsen", {"node": 5}),
                fz.FuzzEvent("fleet", "burst", {"q": 2}),
            ],
        )
        r1 = fz.run_timeline(t)
        r2 = fz.run_timeline(t)
        assert r1.ok and r2.ok, (r1.failures, r2.failures)

        span1 = {tok for tok in r1.fingerprint if tok.startswith("span:")}
        span2 = {tok for tok in r2.fingerprint if tok.startswith("span:")}
        # the fleet bursts produced traced queries, and the replay
        # reproduced their span trees BYTE-IDENTICALLY (stage names,
        # rungs, outcome tags; timers are excluded by design)
        assert span1, "armed fuzz run produced no span tokens"
        assert span1 == span2
        # the full fingerprint (counters + faults + spans) also agrees
        assert r1.fingerprint == r2.fingerprint

    def test_fingerprint_unarmed_has_no_span_tokens(self):
        from openr_tpu.chaos import fuzz as fz

        assert _trace.TRACE is None
        t = fz.FuzzTimeline(
            seed=424243,
            events=[fz.FuzzEvent("fleet", "burst", {"q": 2})],
        )
        r = fz.run_timeline(t)
        assert r.ok, r.failures
        assert not any(tok.startswith("span:") for tok in r.fingerprint)
