"""Config validation, Monitor/Watchdog, and ctrl streaming tests."""

from __future__ import annotations

import threading
import time

import pytest

from openr_tpu.config import (
    AreaConf,
    ConfigError,
    OpenrConfig,
    config_from_dict,
)
from openr_tpu.ctrl import CtrlClient
from openr_tpu.kvstore import InProcessTransport
from openr_tpu.main import OpenrDaemon
from openr_tpu.monitor import LogSample, Monitor, Watchdog
from openr_tpu.runtime.eventbase import OpenrEventBase
from openr_tpu.runtime.queue import ReplicateQueue
from openr_tpu.spark import MockIoProvider
from openr_tpu.types import LinkEvent, Publication

from test_system import FAST_SPARK, make_config, wait_for


class TestConfig:
    def test_valid_roundtrip(self):
        cfg = config_from_dict(
            {
                "node_name": "node-1",
                "areas": [{"area_id": "a1", "neighbor_regexes": ["node-.*"]}],
                "openr_ctrl_port": 3018,
                "kvstore_config": {"flood_msg_per_sec": 100},
            }
        )
        assert cfg.node_name == "node-1"
        assert cfg.area_ids == ("a1",)
        assert cfg.kvstore_config.flood_msg_per_sec == 100
        assert cfg.to_dict()["node_name"] == "node-1"

    def test_validation_errors(self):
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="").validate()
        with pytest.raises(ConfigError):
            OpenrConfig(node_name="bad name").validate()
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="x", areas=[AreaConf("1"), AreaConf("1")]
            ).validate()
        with pytest.raises(ConfigError):
            OpenrConfig(
                node_name="x",
                areas=[AreaConf("1", interface_regexes=["["])],
            ).validate()

    def test_load_file(self, tmp_path):
        path = tmp_path / "cfg.json"
        path.write_text('{"node_name": "filenode"}')
        from openr_tpu.config import load_config

        assert load_config(str(path)).node_name == "filenode"


class TestMonitor:
    def test_event_logs_and_counters(self):
        logq: ReplicateQueue = ReplicateQueue()
        monitor = Monitor("n1", logq.get_reader(), counter_interval_s=0.05)
        monitor.run()
        try:
            logq.push(LogSample(event="NEIGHBOR_UP", neighbor="n2"))
            logq.push({"event": "ROUTE_CONVERGENCE", "duration_ms": 12})
            assert wait_for(lambda: len(monitor.get_event_logs()) == 2)
            assert "NEIGHBOR_UP" in monitor.get_event_logs()[0]
            time.sleep(0.1)
            counters = monitor.get_counters()
            assert "monitor.uptime_s" in counters
            assert counters.get("monitor.process_rss_bytes", 0) > 0
        finally:
            logq.close()
            monitor.stop()
            monitor.wait_until_stopped(5)


class TestWatchdog:
    def test_stall_detection(self):
        fired = []
        watchdog = Watchdog(
            interval_s=0.05,
            thread_timeout_s=0.2,
            # this test is about STALLS: the default 800MB RSS limit can
            # fire first when the suite's jax compilations grow the
            # shared pytest process past it (observed flake)
            max_memory_bytes=1 << 40,
            on_crash=fired.append,
        )
        evb = OpenrEventBase(name="victim")
        evb.run()
        try:
            watchdog.add_evb(evb)
            watchdog.check_once()
            assert not fired
            # stall the loop.  The callback delivery itself can lag under
            # CPU contention (observed flake: >0.2s to reach the loop, so
            # a single check saw a still-fresh heartbeat) — wait for the
            # stall to actually begin, then poll the watchdog to a
            # deadline instead of trusting one fixed-sleep check.
            blocker = threading.Event()
            stalled = threading.Event()

            def _stall():
                stalled.set()
                # the stall must OUTLIVE the polling deadline below with
                # margin, or a contended tail can release the loop and
                # refresh the heartbeat mid-poll
                blocker.wait(20.0)

            evb._loop.call_soon_threadsafe(_stall)
            assert stalled.wait(5.0), "stall callback never reached the loop"
            deadline = time.monotonic() + 5.0
            while not fired and time.monotonic() < deadline:
                time.sleep(0.05)
                watchdog.check_once()
            assert fired and "stalled" in fired[0]
        finally:
            # ALWAYS release the loop: an assertion failure above must
            # not leave the loop thread in blocker.wait through teardown
            blocker.set()
            evb.stop()
            evb.wait_until_stopped(5)

    def test_memory_limit(self):
        fired = []
        watchdog = Watchdog(max_memory_bytes=1, on_crash=fired.append)
        watchdog.check_once()
        assert fired and "memory" in fired[0]


@pytest.fixture
def daemon():
    fabric = MockIoProvider()
    d = OpenrDaemon(
        make_config("solo", ctrl_port=0),
        io_provider=fabric.endpoint("solo"),
        kvstore_transport=InProcessTransport().bind("solo"),
    )
    d.start()
    yield d
    d.stop()


class TestCtrlStreaming:
    def test_kvstore_snapshot_plus_stream(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        stream = client.stream("subscribeKvStore", area="0", prefixes=[])
        first = next(stream)  # snapshot (may be empty)
        assert isinstance(first, Publication)

        from openr_tpu.types import Value

        daemon.kvstore.set_key_vals(
            "0", {"stream-key": Value(1, "solo", b"sv")}
        )
        got = next(stream)
        assert "stream-key" in got.key_vals
        client.close()

    def test_long_poll_adj(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        result: list = []

        def poll():
            result.append(
                client.call("longPollKvStoreAdjArea", area="0", snapshot={})
            )

        # no adj keys yet -> long poll blocks until one appears
        thread = threading.Thread(target=poll)
        thread.start()
        time.sleep(0.3)
        assert thread.is_alive()
        daemon.netlink_events_queue.push(LinkEvent("ifx", 1, True))
        # an interface alone creates no adjacency; force one via kvstore
        from openr_tpu.serializer import dumps
        from openr_tpu.types import Adjacency, AdjacencyDatabase, Value, adj_key

        daemon.kvstore.set_key_vals(
            "0",
            {
                adj_key("solo"): Value(
                    1, "solo", dumps(AdjacencyDatabase("solo", []))
                )
            },
        )
        thread.join(timeout=5)
        assert not thread.is_alive() and result == [True]
        client.close()

    def test_unknown_method_error(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        with pytest.raises(RuntimeError, match="unknown method"):
            client.call("noSuchMethod")
        client.close()


class TestCtrlGapRpcs:
    """Round-3 ctrl/CLI surface additions (reference: dryrunConfig
    OpenrCtrlHandler.h:69-78, getMplsRoutesFiltered,
    withdrawPrefixesByType, breeze kvstore compare / tech-support)."""

    def test_dryrun_config_valid_and_invalid(self, daemon):
        import json as _json

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            good = _json.dumps(make_config("dryrun-check").to_dict())
            parsed = client.call("dryrunConfig", file_contents=good)
            assert parsed["node_name"] == "dryrun-check"
            # nothing applied: the daemon keeps its own identity
            assert client.call("getMyNodeName") == "solo"
            with pytest.raises(RuntimeError):
                client.call("dryrunConfig", file_contents="{not json")
            bad = _json.dumps({"node_name": ""})
            with pytest.raises(RuntimeError):
                client.call("dryrunConfig", file_contents=bad)
        finally:
            client.close()

    def test_mpls_routes_filtered(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            routes = client.call("getMplsRoutesFiltered", labels=None)
            assert isinstance(routes, list)
            # label filter returns the subset
            if routes:
                lbl = routes[0].top_label
                only = client.call("getMplsRoutesFiltered", labels=[lbl])
                assert [r.top_label for r in only] == [lbl]
            assert client.call("getMplsRoutesFiltered", labels=[1 << 19]) == []
        finally:
            client.close()

    def test_withdraw_prefixes_by_type(self, daemon):
        from openr_tpu.types import PrefixEntry, PrefixType

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            client.call(
                "advertisePrefixes",
                type=PrefixType.BREEZE,
                prefixes=[PrefixEntry(prefix="fc51::/64", type=PrefixType.BREEZE)],
            )
            assert client.call("getPrefixesByType", type=PrefixType.BREEZE)
            client.call("withdrawPrefixesByType", type=PrefixType.BREEZE)
            assert not client.call(
                "getPrefixesByType", type=PrefixType.BREEZE
            )
        finally:
            client.close()

    def test_breeze_tech_support_and_compare(self, daemon, capsys):
        from openr_tpu.cli import breeze

        rc = breeze.main(["-p", str(daemon.ctrl_port), "tech-support"])
        out = capsys.readouterr().out
        assert rc == 0
        for section in ("VERSION", "RUNNING CONFIG", "COUNTERS", "FIB ROUTES"):
            assert f"======== {section} ========" in out
        # compare against ITSELF: stores agree
        rc = breeze.main(
            ["-p", str(daemon.ctrl_port), "kvstore", "compare", "::1",
             "--other-port", str(daemon.ctrl_port)]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "agree" in out

    def test_breeze_config_dryrun(self, daemon, tmp_path, capsys):
        import json as _json

        from openr_tpu.cli import breeze

        good = tmp_path / "good.conf"
        good.write_text(_json.dumps(make_config("x").to_dict()))
        rc = breeze.main(
            ["-p", str(daemon.ctrl_port), "config", "dryrun", str(good)]
        )
        assert rc == 0
        assert "VALID" in capsys.readouterr().out
        bad = tmp_path / "bad.conf"
        bad.write_text("{}")
        with pytest.raises(SystemExit):
            breeze.main(
                ["-p", str(daemon.ctrl_port), "config", "dryrun", str(bad)]
            )


class TestCtrlDeltaRpcs:
    """Round-5 RPC-delta closure vs the reference handler
    (OpenrCtrlHandler.h:53-381): persistent-store keys, build info,
    deprecated area-less aliases, spark GR flood, advertised-route and
    route-detail views."""

    def test_build_info(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            info = client.call("getBuildInfo")
            assert info["buildPackageName"] == "openr_tpu"
        finally:
            client.close()

    def test_config_key_roundtrip(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            client.call("setConfigKey", key="k1", value=b"\x01\x02")
            assert client.call("getConfigKey", key="k1") == b"\x01\x02"
            assert client.call("eraseConfigKey", key="k1") is True
            assert client.call("getConfigKey", key="k1") is None
            assert client.call("eraseConfigKey", key="k1") is False
        finally:
            client.close()

    def test_area_less_aliases_match_area_variants(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            assert client.call("getKvStorePeers") == client.call(
                "getKvStorePeersArea", area="0"
            )
            a = client.call("getKvStoreKeyVals", keys=[])
            b = client.call("getKvStoreKeyValsArea", area="0", keys=[])
            assert type(a) is type(b)
            assert client.call("getNeighbors") == client.call(
                "getSparkNeighbors"
            )
            assert client.call("getDecisionAdjacencyDbs") == client.call(
                "getDecisionAdjacenciesFiltered"
            )
        finally:
            client.close()

    def test_advertised_routes(self, daemon):
        from openr_tpu.types import PrefixEntry, PrefixType

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            client.call(
                "advertisePrefixes",
                type=PrefixType.BREEZE,
                prefixes=[
                    PrefixEntry(prefix="fc61::/64", type=PrefixType.BREEZE)
                ],
            )
            rows = client.call("getAdvertisedRoutes")
            assert any(r["prefix"] == "fc61::/64" for r in rows)
            only = client.call(
                "getAdvertisedRoutesFiltered", prefixes=["fc61::/64"]
            )
            assert len(only) == 1 and only[0]["prefix"] == "fc61::/64"
            types = [t for t, _e in only[0]["routes"]]
            assert int(PrefixType.BREEZE) in types
            assert (
                client.call(
                    "getAdvertisedRoutesFiltered", prefixes=["fc62::/64"]
                )
                == []
            )
        finally:
            client.close()

    def test_route_detail_db(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            detail = client.call("getRouteDetailDb")
            assert set(detail) == {"unicastRoutes", "mplsRoutes"}
        finally:
            client.close()

    def test_flood_restarting_msg(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            client.call("floodRestartingMsg")  # no neighbors: no-op send
        finally:
            client.close()


class TestCounterRegistrySweep:
    """Wire-level counterpart of the counter-registry static rule: every
    counter family the modules bump must actually surface through one
    getCounters RPC, and every dumped key must follow the module.name
    convention the analyzer enforces (counter-name rule)."""

    def test_full_counter_set_is_dumpable(self, daemon):
        import re

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            # fib's sync loop runs on its own thread; wait for the first
            # sync so the fib.* and fib.agent.* families are populated
            assert wait_for(
                lambda: client.call("getCounters").get(
                    "fib.sync_fib_calls", 0
                )
                > 0,
                timeout=10.0,
            ), "fib never completed its first sync"
            counters = client.call("getCounters")

            # one representative per wired family, including the two
            # wired in by this sweep (netlink events queue, fib agent)
            for key in (
                "kvstore.num_keys.0",
                "monitor.uptime_s",
                "queue.route_updates.writes",
                "queue.netlink_events.writes",
                "fib.sync_fib_calls",
                "fib.agent.sync_fib",
                # the device-residency engine pre-seeds its registry, so
                # the family is dumpable before any device query runs
                "device.engine.queries",
                # the edge-set rewire rung pre-seeds the same way: the
                # runbook's rewire ledger is scrapeable before any OCS
                # reconfiguration ever reaches the engine
                "device.engine.rewire_dispatches",
                "device.engine.rewire_fallbacks",
                # the query scheduler pre-seeds serving.* the same way,
                # and its admission RWQueue rides the daemon queue fabric
                "serving.admitted",
                "queue.serving_admission.overflows",
                # the blocked node-sharding rung pre-seeds mesh.blocked.*
                # in the engine's sub-registry before any product runs
                "mesh.blocked.products",
                # the TE optimizer pre-seeds te.* at construction, so the
                # family is dumpable before any optimizeMetrics runs
                "te.runs",
                # the schedule explorer pre-seeds sched.* at module
                # import, so the family is dumpable before any run
                "sched.schedules_explored",
                "sched.planted_finds",
            ):
                assert key in counters, f"{key} missing from getCounters"

            # the convention the counter-name rule enforces statically
            name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
            bad = [k for k in counters if not name_re.match(k)]
            assert not bad, f"non-conventional counter keys: {bad}"
        finally:
            client.close()

    def test_engine_family_on_both_wire_surfaces(self, daemon):
        """The full device.engine.* registry answers ONE getCounters on
        the native ctrl server AND the thrift-binary fb303 shim — no
        per-key plumbing, the engine rides _all_counters like any
        module."""
        from openr_tpu.device import ENGINE_COUNTER_KEYS
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert set(ENGINE_COUNTER_KEYS) <= set(native)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                41,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert set(ENGINE_COUNTER_KEYS) <= set(shimmed)

    def test_pallas_family_on_both_wire_surfaces(self, daemon):
        """The Pallas kernel ledger (launches per kind, demotions,
        policy skips) is pre-seeded in the engine registry, so the
        whole device.engine.pallas_* family answers ONE getCounters on
        the native ctrl server AND the fb303 shim before any kernel
        ever launches — the runbook's pallas_fallbacks check needs no
        warm-up query."""
        import re

        from openr_tpu.device import ENGINE_COUNTER_KEYS
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        family = {k for k in ENGINE_COUNTER_KEYS if ".pallas_" in k}
        assert {
            "device.engine.pallas_products",
            "device.engine.pallas_outer_updates",
            "device.engine.pallas_fallbacks",
            "device.engine.pallas_skips",
        } <= family
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert family <= set(native)
        assert all(native[k] == 0 for k in family)  # pre-seeded, untouched

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                43,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)

    def test_serving_family_on_both_wire_surfaces(self, daemon):
        """The full serving.* registry (admission, coalescing, shedding,
        latency gauges) answers ONE getCounters on the native ctrl
        server AND the fb303 shim, convention-clean, with no per-key
        plumbing — the scheduler rides _all_counters like any module."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.serving import SERVING_COUNTER_KEYS
        from test_thrift_binary import _call_ok

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert set(SERVING_COUNTER_KEYS) <= set(native)
        # the admission queue is registered in the daemon fabric, so its
        # overflow ledger is on the same surface the runbook points at
        assert "queue.serving_admission.overflows" in native

        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in SERVING_COUNTER_KEYS)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                42,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert set(SERVING_COUNTER_KEYS) <= set(shimmed)

    def test_pipeline_family_on_both_wire_surfaces(self, daemon):
        """The pipelined blocked closure's ledger (prefetches issued,
        rounds overlapped, demotions to bulk, the overlap-fraction
        gauge) is pre-seeded in the blocked sub-registry, so the whole
        mesh.blocked.pipeline_* family answers ONE getCounters on the
        native ctrl server AND the fb303 shim before any closure ever
        runs — the runbook's pipeline_fallbacks check needs no warm-up
        query."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.parallel.blocked import BLOCKED_COUNTER_KEYS
        from test_thrift_binary import _call_ok

        family = {k for k in BLOCKED_COUNTER_KEYS if ".pipeline_" in k}
        assert family == {
            "mesh.blocked.pipeline_rounds_overlapped",
            "mesh.blocked.pipeline_prefetch_issues",
            "mesh.blocked.pipeline_fallbacks",
            "mesh.blocked.pipeline_overlap_frac_est",
        }
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert family <= set(native)
        assert all(native[k] == 0 for k in family)  # pre-seeded, untouched

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                47,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)
        assert all(shimmed[k] == 0 for k in family)

    def test_router_family_on_both_wire_surfaces(self, daemon):
        """The replica-fleet front door pre-seeds serving.router.* and
        rides the same two surfaces: a ctrl server whose serving module
        is the ReplicaRouter (the fleet front-door posture), and the
        fb303 shim fed by that handler's merged dump.  The router's
        get_counters also rolls up its replicas' serving.* families, so
        one scrape covers the whole fleet."""
        import re

        from openr_tpu.ctrl import CtrlServer, OpenrCtrlHandler
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.serving import (
            ReplicaRouter,
            ROUTER_COUNTER_KEYS,
            SchedulerReplica,
        )
        from test_thrift_binary import _call_ok

        router = ReplicaRouter(
            [SchedulerReplica("solo", daemon.serving)], hedge_after_s=None
        )
        handler = OpenrCtrlHandler("fleet-front", serving=router)
        server = CtrlServer(handler, port=0)
        server.run()
        try:
            client = CtrlClient(port=server.port)
            try:
                native = client.call("getCounters")
            finally:
                client.close()
        finally:
            server.stop()
            server.wait_until_stopped(5)
        # pre-seeded: the whole family dumps before any dispatch
        assert set(ROUTER_COUNTER_KEYS) <= set(native)
        # fleet roll-up: the replica's serving.* rides the same dump
        assert "serving.admitted" in native

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                43,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
            router.stop()
        assert set(ROUTER_COUNTER_KEYS) <= set(shimmed)

        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in ROUTER_COUNTER_KEYS)

    def test_mesh_blocked_family_on_both_wire_surfaces(self, daemon):
        """The full mesh.blocked.* registry (blocked node-sharded APSP
        rung: products, rounds, panel broadcasts, bytes, phase timers,
        fallbacks) answers ONE getCounters on the native ctrl server AND
        the fb303 shim, pre-seeded — dashboards see every key before the
        first product dispatches."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.parallel.blocked import BLOCKED_COUNTER_KEYS
        from test_thrift_binary import _call_ok

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert set(BLOCKED_COUNTER_KEYS) <= set(native)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                41,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert set(BLOCKED_COUNTER_KEYS) <= set(shimmed)

        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in BLOCKED_COUNTER_KEYS)

    def test_delta_family_on_both_wire_surfaces(self, daemon):
        """The incremental-delta families (decision.delta.* from the
        coalescer pre-seed, device.engine.delta_* from the engine rung)
        answer ONE getCounters on the native ctrl server AND the fb303
        shim from daemon start — before any delta update has run — so
        dashboards can alert on fallbacks/full_restages going non-zero
        without waiting for the first storm."""
        import re

        from openr_tpu.decision.delta import DELTA_COUNTER_KEYS
        from openr_tpu.device import ENGINE_COUNTER_KEYS
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        engine_delta = [
            k for k in ENGINE_COUNTER_KEYS
            if k.startswith("device.engine.delta_")
        ]
        assert engine_delta, "engine registry lost its delta_* family"

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert set(DELTA_COUNTER_KEYS) <= set(native)
        assert set(engine_delta) <= set(native)

        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in DELTA_COUNTER_KEYS)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                43,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert set(DELTA_COUNTER_KEYS) <= set(shimmed)
        assert set(engine_delta) <= set(shimmed)

    def test_te_family_on_both_wire_surfaces(self, daemon):
        """The full te.* registry (runs, steps, round trips, accept /
        reject / abort ledgers, objective gauges) answers ONE getCounters
        on the native ctrl server AND the fb303 shim, pre-seeded at
        TeOptimizer construction — dashboards can alert on te.aborted or
        te.rejected going non-zero before the first optimizeMetrics ever
        runs."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.te import TE_COUNTER_KEYS
        from test_thrift_binary import _call_ok

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert set(TE_COUNTER_KEYS) <= set(native)

        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in TE_COUNTER_KEYS)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                44,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert set(TE_COUNTER_KEYS) <= set(shimmed)
        # representative key round-trips the strict-binary i64 map intact
        assert shimmed["te.runs"] == native["te.runs"]

    def test_fuzz_family_on_both_wire_surfaces(self, daemon):
        """The chaos-fuzzer ledger (runs, mutations, crossovers, novel
        fingerprints, oracle failures, shrink steps) is pre-seeded in
        its own process-wide registry and rides _all_counters like any
        module, so the whole chaos.fuzz.* family answers ONE getCounters
        on the native ctrl server AND the fb303 shim before any fuzz
        session has run — a soak box's dashboard can alert on
        oracle_failures going non-zero with no warm-up query."""
        import re

        from openr_tpu.chaos.fuzz import FUZZ_COUNTER_KEYS
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        family = set(FUZZ_COUNTER_KEYS)
        assert {
            "chaos.fuzz.runs",
            "chaos.fuzz.mutations",
            "chaos.fuzz.crossovers",
            "chaos.fuzz.novel_fingerprints",
            "chaos.fuzz.oracle_failures",
            "chaos.fuzz.shrink_steps",
        } == family
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert family <= set(native)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                45,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)
        # the family round-trips the strict-binary i64 map intact
        assert all(shimmed[k] == native[k] for k in family)

    def test_sched_family_on_both_wire_surfaces(self, daemon):
        """The schedule-explorer ledger (schedules explored, DPOR prunes,
        replays, shrinks, planted-bug finds) is pre-seeded in its own
        process-wide registry and rides _all_counters like chaos.fuzz,
        so the whole sched.* family answers ONE getCounters on the
        native ctrl server AND the fb303 shim before any exploration has
        run — a CI box can alert on planted_finds staying zero (the
        canary bug was not found) with no warm-up query."""
        import re

        from openr_tpu.analysis.sched import SCHED_COUNTER_KEYS, SchedCounters
        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from test_thrift_binary import _call_ok

        family = set(SCHED_COUNTER_KEYS)
        assert {
            "sched.schedules_explored",
            "sched.dpor_prunes",
            "sched.replays",
            "sched.shrinks",
            "sched.planted_finds",
        } == family
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)
        # construction pre-seeds every key to zero (the process-wide
        # singleton the daemon exports may have been bumped by an earlier
        # in-process exploration, so the zero contract is asserted on a
        # fresh registry)
        assert SchedCounters().get_counters() == {k: 0 for k in family}

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert family <= set(native)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                46,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)
        # the family round-trips the strict-binary i64 map intact
        assert all(shimmed[k] == native[k] for k in family)

    def test_snapshot_family_on_both_wire_surfaces(self, daemon):
        """The engine-snapshot ledger (checkpoints taken, restore rungs,
        replayed events, accounted demotions, digest failures, manifest
        prewarms, fleet scale transitions) is pre-seeded in its own
        process-wide registry and rides _all_counters like chaos.fuzz,
        so the whole snapshot.* family answers ONE getCounters on the
        native ctrl server AND the fb303 shim before any snapshot is
        ever taken — an operator can alert on replay_fallbacks or
        digest_failures going non-zero with no warm-up query."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.snapshot import SNAPSHOT_COUNTER_KEYS, SnapshotCounters
        from test_thrift_binary import _call_ok

        family = set(SNAPSHOT_COUNTER_KEYS)
        assert {
            "snapshot.taken",
            "snapshot.take_us",
            "snapshot.bytes",
            "snapshot.restores",
            "snapshot.restore_us",
            "snapshot.replayed_events",
            "snapshot.replay_fallbacks",
            "snapshot.digest_failures",
            "snapshot.manifest_programs",
            "snapshot.prewarmed_programs",
            "snapshot.scaleouts",
            "snapshot.scaleins",
        } == family
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)
        # construction pre-seeds every key to zero (the process-wide
        # singleton the daemon exports may have been bumped by an earlier
        # in-process take/restore, so the zero contract is asserted on a
        # fresh registry)
        assert SnapshotCounters().get_counters() == {k: 0 for k in family}

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
        finally:
            client.close()
        assert family <= set(native)

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                48,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)
        # the family round-trips the strict-binary i64 map intact
        assert all(shimmed[k] == native[k] for k in family)

    def test_obs_family_on_both_wire_surfaces(self, daemon):
        """The tracing surface (ObsStats) answers the whole obs.*
        family as ZEROS on the native ctrl server AND the fb303 shim
        while OPENR_TRACE is off — the wire shape is arming-independent,
        so a dashboard scraping obs.traces_finished needs no knowledge
        of whether the box is armed.  The span dump RPCs answer empty
        lists the same way.  The shared-histogram percentile gauges
        (serving.p50_us et al) ride the serving family on the same two
        surfaces."""
        import re

        from openr_tpu.interop import thrift_binary as tb
        from openr_tpu.interop.shim import ThriftBinaryShim
        from openr_tpu.obs import OBS_COUNTER_KEYS
        from test_thrift_binary import _call_ok

        family = set(OBS_COUNTER_KEYS)
        assert {
            "obs.traces_started",
            "obs.traces_sampled_out",
            "obs.traces_finished",
            "obs.spans_total",
            "obs.trace_ring_evictions",
        } == family
        name_re = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\Z")
        assert all(name_re.match(k) for k in family)

        client = CtrlClient(port=daemon.ctrl_port)
        try:
            native = client.call("getCounters")
            assert client.call("dumpTraces") == []
            assert client.call("getSpanSamples") == []
        finally:
            client.close()
        assert family <= set(native)
        assert all(native[k] == 0 for k in family)  # unarmed: zeroed
        # histogram percentile gauges ride the serving registry
        for key in ("serving.p50_us", "serving.p99_us", "serving.p999_us"):
            assert key in native, key

        shim = ThriftBinaryShim(
            daemon.kvstore,
            port=0,
            node_name="solo",
            counters_fn=daemon.ctrl_server.handler._all_counters,
        )
        shim.run()
        try:
            shimmed = _call_ok(
                shim.port,
                "getCounters",
                53,
                b"\x00",
                ("map", tb.T_STRING, tb.T_I64),
                dec=lambda m: {k.decode(): v for k, v in m.items()},
            )
        finally:
            shim.stop()
            shim.wait_until_stopped(5)
        assert family <= set(shimmed)
        assert all(shimmed[k] == 0 for k in family)
        assert "serving.p50_us" in shimmed


class TestOptimizeMetricsWire:
    """The ctrl optimizeMetrics front-end end to end: a bad request is
    answered with a clean error envelope through the serving admission
    path — never a hang, never a silent drop (tests/test_te.py covers
    the optimizer itself; this pins the wire registration)."""

    def test_bad_demand_gets_clean_error(self, daemon):
        client = CtrlClient(port=daemon.ctrl_port)
        try:
            with pytest.raises(RuntimeError):
                client.call(
                    "optimizeMetrics",
                    area="0",
                    demand=[["no-such-node", "also-missing", 1.0]],
                    steps=2,
                )
            # the surface stays alive and dumpable after the error
            assert "te.runs" in client.call("getCounters")
        finally:
            client.close()
