"""Library-usage examples + plugin seam, run against live daemons
(reference: /examples programs consumed openrlib the same way)."""

from __future__ import annotations

import contextlib
import io
import os
import sys

import pytest

# examples/ package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.kvstore_agent import AGENT_KEY_PREFIX, KvStoreAgent
from examples.kvstore_poller import poll
from examples.route_injector_plugin import INJECTED_PREFIX
from examples.set_rib_policy import main as set_rib_policy_main
from openr_tpu.types import LinkEvent
from tests.test_system import FIB_CLIENT, RingFixture, make_config, wait_for


class TestKvStoreAgentExample:
    def test_agents_exchange_data_across_ring(self):
        fixture = RingFixture(3)
        agents = []
        try:
            for daemon in fixture.daemons:
                agent = KvStoreAgent(
                    f"agent-{daemon.config.node_name}",
                    daemon.kvstore,
                    daemon.kvstore_updates_queue.get_reader(),
                    change_interval_s=0.1,
                )
                agent.start()
                agents.append(agent)
            # every agent's persisted key floods to every node, and every
            # agent observes the other two (the reference example's log)
            assert wait_for(
                lambda: all(len(a.peer_data) == 2 for a in agents)
            ), [sorted(a.peer_data) for a in agents]
            # persist-key ownership: the key is in every store
            pub = fixture.daemons[0].kvstore.dump_all("0")
            agent_keys = [
                k for k in pub.key_vals if k.startswith(AGENT_KEY_PREFIX)
            ]
            assert len(agent_keys) == 3
        finally:
            for agent in agents:
                agent.stop()
            fixture.stop()


class TestPollerAndPolicyExamples:
    @pytest.fixture
    def tcp_pair(self):
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from tests.test_platform_agent import free_port

        fabric = MockIoProvider()
        ports = (free_port(), free_port())
        daemons = []
        for i, port in enumerate(ports):
            cfg = make_config(f"ex-{i}", ctrl_port=port)
            cfg.enable_rib_policy = True  # the SetRibPolicy example needs it
            d = OpenrDaemon(
                cfg,
                io_provider=fabric.endpoint(f"ex-{i}"),
                spark_v6_addr="::1",
            )
            d.start()
            daemons.append(d)
        fabric.connect("ex-0", "e0", "ex-1", "e1")
        daemons[0].netlink_events_queue.push(LinkEvent("e0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("e1", 1, True))
        yield daemons, ports
        for d in daemons:
            d.stop()

    def test_kvstore_poller(self, tcp_pair):
        daemons, ports = tcp_pair
        # both directions: each daemon must hold BOTH adj keys before the
        # poller compares tables (flooding the two ways is not synchronized)
        assert wait_for(
            lambda: all(
                {"adj:ex-0", "adj:ex-1"}
                <= set(d.kvstore.dump_all("0").key_vals)
                for d in daemons
            ),
            timeout=60,  # spark + TCP peering can be slow under suite load
        ), [sorted(d.kvstore.dump_all("0").key_vals) for d in daemons]
        result = poll([("::1", p) for p in ports])
        tables = list(result.values())
        assert all(t is not None for t in tables), result
        assert "adj:ex-0" in tables[0] and "adj:ex-0" in tables[1]
        # unreachable endpoint reported as None, not an exception
        down = poll([("::1", 1)])
        assert list(down.values()) == [None]

    def test_set_rib_policy_example(self, tcp_pair):
        daemons, ports = tcp_pair
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = set_rib_policy_main(
                ["--port", str(ports[0]), "--prefix", "fc00::/64"]
            )
        assert rc == 0
        assert "example-statement" in out.getvalue()
        policy = daemons[0].decision.get_rib_policy()
        assert policy.statements[0].name == "example-statement"


class TestPluginSeam:
    def test_route_injector_plugin_originates_and_observes(self):
        """plugin_module config attaches examples.route_injector_plugin:
        its BGP-type prefix must reach the OTHER node's FIB, and it must
        see route updates (reference contract: Plugin.h queues)."""
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider
        from openr_tpu.types import normalize_prefix

        from tests.test_platform_agent import free_port

        fabric = MockIoProvider()
        daemons = []
        for i in range(2):
            cfg = make_config(f"pl-{i}", ctrl_port=free_port())
            if i == 0:
                cfg.plugin_module = "examples.route_injector_plugin"
            d = OpenrDaemon(
                cfg,
                io_provider=fabric.endpoint(f"pl-{i}"),
                spark_v6_addr="::1",
            )
            d.start()
            daemons.append(d)
        fabric.connect("pl-0", "p0", "pl-1", "p1")
        daemons[0].netlink_events_queue.push(LinkEvent("p0", 1, True))
        daemons[1].netlink_events_queue.push(LinkEvent("p1", 1, True))
        try:
            assert daemons[0]._plugin_handle is not None
            assert wait_for(
                lambda: normalize_prefix(INJECTED_PREFIX)
                in daemons[1].fib_agent.unicast.get(FIB_CLIENT, {}),
                timeout=30,
            ), "injected BGP prefix never reached the peer FIB"
            assert wait_for(
                lambda: daemons[0]._plugin_handle.seen_route_updates > 0
            ), "plugin never observed a route update"
        finally:
            for d in daemons:
                d.stop()

    def test_bad_plugin_module_fails_loudly(self):
        from openr_tpu.main import OpenrDaemon
        from openr_tpu.spark import MockIoProvider

        cfg = make_config("pl-bad")
        cfg.plugin_module = "examples.no_such_plugin"
        d = OpenrDaemon(
            cfg,
            io_provider=MockIoProvider().endpoint("pl-bad"),
            spark_v6_addr="::1",
        )
        with pytest.raises(ImportError):
            d.start()
        d.stop()
