"""Benchmark of record: all-sources SPF on a 1k-node grid (one chip).

This is BASELINE.json config #1 ("SpfSolver CPU ref: 1k-node grid LinkState,
single IGP metric") measured end-to-end on the device kernel: batched SSSP to
fixed point + shortest-path-DAG extraction for ALL 1024 sources in one call
(the reference runs 1024 sequential Dijkstras — openr/decision/
LinkState.cpp:809 — one per getSpfResult source).

Baseline for `vs_baseline` is the in-repo conformance oracle (host Dijkstra,
same semantics), timed on a source subsample and scaled — the reference
publishes no absolute numbers (BASELINE.md).  vs_baseline > 1 means the TPU
path is faster.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_SIDE = 32  # 1024 nodes
ORACLE_SOURCES = 16
DEVICE_REPS = 5


def main() -> None:
    import jax
    import jax.numpy as jnp

    from openr_tpu.decision.csr import CsrTopology
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.ops import sssp as ops
    from openr_tpu.utils.topo import grid_topology

    ls = LinkState()
    for db in grid_topology(N_SIDE):
        ls.update_adjacency_database(db)
    csr = CsrTopology.from_link_state(ls)
    n = csr.n_nodes

    sources = jnp.arange(n, dtype=jnp.int32)
    e_src = jnp.asarray(csr.edge_src)
    e_dst = jnp.asarray(csr.edge_dst)
    metric = jnp.asarray(csr.edge_metric)
    e_up = jnp.asarray(csr.edge_up)
    overloaded = jnp.asarray(csr.node_overloaded)

    all_sources_spf = ops.spf_forward  # the shipped flagship kernel

    args = (sources, e_src, e_dst, metric, e_up, overloaded)
    jax.block_until_ready(all_sources_spf(*args))  # compile + warm
    times = []
    for _ in range(DEVICE_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(all_sources_spf(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    device_ms = float(np.median(times))

    # host-oracle baseline on a subsample, scaled to all sources
    sample = list(np.linspace(0, n - 1, ORACLE_SOURCES, dtype=int))
    names = [csr.node_names[i] for i in sample]
    t0 = time.perf_counter()
    for name in names:
        ls.run_spf(name)
    oracle_ms = (time.perf_counter() - t0) * 1e3 * (n / len(names))

    print(
        json.dumps(
            {
                "metric": "allsrc_spf_grid1024_ms",
                "value": round(device_ms, 3),
                "unit": "ms",
                "vs_baseline": round(oracle_ms / device_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
